"""ManifestFile and ManifestList readers/writers (avro object files).

reference: paimon-core/.../manifest/ManifestFile.java, ManifestList.java,
ManifestFileMeta.java; spec manifest.md.
"""

from __future__ import annotations

import uuid
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

from paimon_tpu.format import avro as avro_fmt
from paimon_tpu.fs import FileIO
from paimon_tpu.manifest.manifest_entry import (
    MANIFEST_ENTRY_AVRO_SCHEMA, FileKind, ManifestEntry,
)
from paimon_tpu.manifest.simple_stats import SimpleStats

__all__ = ["ManifestFile", "ManifestFileMeta", "ManifestList"]

META_VERSION = 2


@dataclass
class ManifestFileMeta:
    file_name: str
    file_size: int
    num_added_files: int
    num_deleted_files: int
    partition_stats: SimpleStats
    schema_id: int
    min_row_id: Optional[int] = None
    max_row_id: Optional[int] = None
    # manifest-level pruning stats (ours; feed the columnar stats
    # sidecar — manifest/stats_sidecar.py): bucket range and the
    # trimmed-primary-key min/max (BinaryRow bytes, compared decoded)
    # over every entry in the manifest.  Optional so old manifests
    # round-trip; None disables the corresponding vectorized prune.
    min_bucket: Optional[int] = None
    max_bucket: Optional[int] = None
    min_key: Optional[bytes] = None
    max_key: Optional[bytes] = None

    def to_avro(self) -> dict:
        return {
            "_VERSION": META_VERSION,
            "_FILE_NAME": self.file_name,
            "_FILE_SIZE": self.file_size,
            "_NUM_ADDED_FILES": self.num_added_files,
            "_NUM_DELETED_FILES": self.num_deleted_files,
            "_PARTITION_STATS": self.partition_stats.to_avro(),
            "_SCHEMA_ID": self.schema_id,
            "_MIN_ROW_ID": self.min_row_id,
            "_MAX_ROW_ID": self.max_row_id,
            "_MIN_BUCKET": self.min_bucket,
            "_MAX_BUCKET": self.max_bucket,
            "_MIN_KEY": self.min_key,
            "_MAX_KEY": self.max_key,
        }

    @staticmethod
    def from_avro(d: dict) -> "ManifestFileMeta":
        min_key = d.get("_MIN_KEY")
        max_key = d.get("_MAX_KEY")
        return ManifestFileMeta(
            file_name=d["_FILE_NAME"],
            file_size=d["_FILE_SIZE"],
            num_added_files=d["_NUM_ADDED_FILES"],
            num_deleted_files=d["_NUM_DELETED_FILES"],
            partition_stats=SimpleStats.from_avro(d["_PARTITION_STATS"]),
            schema_id=d["_SCHEMA_ID"],
            min_row_id=d.get("_MIN_ROW_ID"),
            max_row_id=d.get("_MAX_ROW_ID"),
            min_bucket=d.get("_MIN_BUCKET"),
            max_bucket=d.get("_MAX_BUCKET"),
            min_key=bytes(min_key) if min_key is not None else None,
            max_key=bytes(max_key) if max_key is not None else None,
        )


MANIFEST_FILE_META_AVRO_SCHEMA = {
    "type": "record",
    "name": "ManifestFileMeta",
    "fields": [
        {"name": "_VERSION", "type": "int"},
        {"name": "_FILE_NAME", "type": "string"},
        {"name": "_FILE_SIZE", "type": "long"},
        {"name": "_NUM_ADDED_FILES", "type": "long"},
        {"name": "_NUM_DELETED_FILES", "type": "long"},
        {"name": "_PARTITION_STATS", "type": {
            "type": "record", "name": "record_PARTITION_STATS", "fields": [
                {"name": "_MIN_VALUES", "type": "bytes"},
                {"name": "_MAX_VALUES", "type": "bytes"},
                {"name": "_NULL_COUNTS",
                 "type": ["null", {"type": "array",
                                   "items": ["null", "long"]}],
                 "default": None},
            ]}},
        {"name": "_SCHEMA_ID", "type": "long"},
        {"name": "_MIN_ROW_ID", "type": ["null", "long"], "default": None},
        {"name": "_MAX_ROW_ID", "type": ["null", "long"], "default": None},
        {"name": "_MIN_BUCKET", "type": ["null", "int"], "default": None},
        {"name": "_MAX_BUCKET", "type": ["null", "int"], "default": None},
        {"name": "_MIN_KEY", "type": ["null", "bytes"], "default": None},
        {"name": "_MAX_KEY", "type": ["null", "bytes"], "default": None},
    ],
}


class ManifestFile:
    """Reads/writes manifest-<uuid>-<n> files under <table>/manifest/."""

    def __init__(self, file_io: FileIO, manifest_dir: str,
                 compression: str = "zstandard",
                 partition_types: Optional[list] = None,
                 key_types: Optional[list] = None,
                 sidecar: bool = True):
        self.file_io = file_io
        self.manifest_dir = manifest_dir.rstrip("/")
        self.compression = compression
        self.partition_types = partition_types or []
        # trimmed-primary-key types: enables per-manifest key-range
        # stats (min/max over every entry's file key stats).  The
        # stats' only consumer is the columnar sidecar — when it is
        # disabled, skip the two-BinaryRow-decodes-per-entry work on
        # the commit hot path
        self.key_types = key_types or []
        self.sidecar = sidecar
        self._suffix = 0

    def new_file_name(self) -> str:
        name = f"manifest-{uuid.uuid4()}-{self._suffix}"
        self._suffix += 1
        return name

    def path(self, name: str) -> str:
        return f"{self.manifest_dir}/{name}"

    def write(self, entries: Sequence[ManifestEntry],
              schema_id: int = 0) -> ManifestFileMeta:
        name = self.new_file_name()
        data = avro_fmt.write_container(
            MANIFEST_ENTRY_AVRO_SCHEMA, [e.to_avro() for e in entries],
            codec=self.compression)
        self.file_io.write_bytes(self.path(name), data, overwrite=False)
        num_added = sum(1 for e in entries if e.kind == FileKind.ADD)
        num_deleted = len(entries) - num_added
        min_bucket = min((e.bucket for e in entries), default=None)
        max_bucket = max((e.bucket for e in entries), default=None)
        min_key, max_key = self._key_range(entries) \
            if self.sidecar else (None, None)
        return ManifestFileMeta(
            file_name=name,
            file_size=len(data),
            num_added_files=num_added,
            num_deleted_files=num_deleted,
            partition_stats=self._partition_stats(entries),
            schema_id=schema_id,
            min_bucket=min_bucket,
            max_bucket=max_bucket,
            min_key=min_key,
            max_key=max_key,
        )

    def read(self, name: str) -> List[ManifestEntry]:
        _, records = avro_fmt.read_container(
            self.file_io.read_bytes(self.path(name)))
        return [ManifestEntry.from_avro(r) for r in records]

    def delete(self, name: str):
        self.file_io.delete_quietly(self.path(name))

    def _key_range(self, entries: Sequence[ManifestEntry]
                   ) -> Tuple[Optional[bytes], Optional[bytes]]:
        """Min/max trimmed-primary-key over every entry's file key
        stats, compared DECODED (BinaryRow bytes are little-endian
        slots, not order-comparable), returned as the winning rows'
        raw bytes.  None on any undecodable key — stats are advisory
        and the vectorized prune keeps unconstrained manifests."""
        if not self.key_types or not entries:
            return None, None
        from paimon_tpu.data.binary_row import BinaryRowCodec
        codec = BinaryRowCodec([t.copy(False) for t in self.key_types])
        best_min = best_max = None          # (decoded tuple, raw bytes)
        try:
            for e in entries:
                mk, xk = e.file.min_key, e.file.max_key
                if not mk or not xk:
                    return None, None
                lo = tuple(codec.from_bytes(mk))
                hi = tuple(codec.from_bytes(xk))
                if best_min is None or lo < best_min[0]:
                    best_min = (lo, mk)
                if best_max is None or hi > best_max[0]:
                    best_max = (hi, xk)
        except Exception:                   # noqa: BLE001 — advisory
            return None, None
        return best_min[1], best_max[1]

    def _partition_stats(self,
                         entries: Sequence[ManifestEntry]) -> SimpleStats:
        if not self.partition_types or not entries:
            return SimpleStats.EMPTY
        from paimon_tpu.data.binary_row import BinaryRowCodec
        codec = BinaryRowCodec(self.partition_types)
        arity = len(self.partition_types)
        mins = [None] * arity
        maxs = [None] * arity
        nulls = [0] * arity
        for e in entries:
            values = codec.from_bytes(e.partition)
            for i, v in enumerate(values):
                if v is None:
                    nulls[i] += 1
                    continue
                if mins[i] is None or v < mins[i]:
                    mins[i] = v
                if maxs[i] is None or v > maxs[i]:
                    maxs[i] = v
        return SimpleStats(codec.to_bytes(mins), codec.to_bytes(maxs), nulls)


class ManifestList:
    """Reads/writes manifest-list-<uuid>-<n> files.

    With `sidecar=True` (and typed partition/key columns available)
    every written list also gets a `stats-<name>` columnar sidecar
    (manifest/stats_sidecar.py) that scan planning prunes against
    vectorized, before fetching any manifest file."""

    def __init__(self, file_io: FileIO, manifest_dir: str,
                 compression: str = "zstandard",
                 partition_types: Optional[list] = None,
                 key_types: Optional[list] = None,
                 sidecar: bool = False):
        self.file_io = file_io
        self.manifest_dir = manifest_dir.rstrip("/")
        self.compression = compression
        self.partition_types = partition_types or []
        self.key_types = key_types or []
        self.sidecar = sidecar
        self._suffix = 0

    def new_file_name(self) -> str:
        name = f"manifest-list-{uuid.uuid4()}-{self._suffix}"
        self._suffix += 1
        return name

    def path(self, name: str) -> str:
        return f"{self.manifest_dir}/{name}"

    def write(self, metas: Sequence[ManifestFileMeta]) -> Tuple[str, int]:
        name = self.new_file_name()
        data = avro_fmt.write_container(
            MANIFEST_FILE_META_AVRO_SCHEMA, [m.to_avro() for m in metas],
            codec=self.compression)
        self.file_io.write_bytes(self.path(name), data, overwrite=False)
        if self.sidecar and metas:
            from paimon_tpu.manifest.stats_sidecar import (
                build_sidecar, sidecar_path,
            )
            from paimon_tpu.utils.deadline import DeadlineExceededError
            try:
                blob = build_sidecar(metas, self.partition_types,
                                     self.key_types)
                if blob is not None:
                    self.file_io.write_bytes(
                        sidecar_path(self.path(name)), blob,
                        overwrite=False)
            except (DeadlineExceededError, KeyboardInterrupt,
                    SystemExit):
                # genuine abort: the list PUT already landed but the
                # caller will treat this write as failed — without
                # this delete the list is unrecorded and no abort
                # path can ever clean it (delete_quietly is
                # deadline-shielded, so this runs even when the
                # sidecar PUT tripped the request deadline)
                self.file_io.delete_quietly(self.path(name))
                raise
            except Exception:
                # the sidecar is ADVISORY — readers fall back to the
                # python prune when it is absent or undecodable, so a
                # build or PUT failure must never fail a commit whose
                # required artifacts all landed; sweep any torn blob
                # and proceed without one
                self.file_io.delete_quietly(
                    sidecar_path(self.path(name)))
        return name, len(data)

    def read(self, name: str) -> List[ManifestFileMeta]:
        _, records = avro_fmt.read_container(
            self.file_io.read_bytes(self.path(name)))
        return [ManifestFileMeta.from_avro(r) for r in records]

    def read_sidecar(self, name: str):
        """The columnar stats sidecar for one list (arrow Table), or
        None when absent/undecodable."""
        from paimon_tpu.manifest.stats_sidecar import read_sidecar
        return read_sidecar(self.file_io, self.path(name))

    def read_all(self, base_name: str,
                 delta_name: Optional[str]) -> List[ManifestFileMeta]:
        out = self.read(base_name) if base_name else []
        if delta_name:
            out.extend(self.read(delta_name))
        return out

    def delete(self, name: str):
        from paimon_tpu.manifest.stats_sidecar import sidecar_path
        self.file_io.delete_quietly(self.path(name))
        self.file_io.delete_quietly(sidecar_path(self.path(name)))
