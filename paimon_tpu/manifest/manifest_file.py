"""ManifestFile and ManifestList readers/writers (avro object files).

reference: paimon-core/.../manifest/ManifestFile.java, ManifestList.java,
ManifestFileMeta.java; spec manifest.md.
"""

from __future__ import annotations

import uuid
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

from paimon_tpu.format import avro as avro_fmt
from paimon_tpu.fs import FileIO
from paimon_tpu.manifest.manifest_entry import (
    MANIFEST_ENTRY_AVRO_SCHEMA, FileKind, ManifestEntry,
)
from paimon_tpu.manifest.simple_stats import SimpleStats

__all__ = ["ManifestFile", "ManifestFileMeta", "ManifestList"]

META_VERSION = 2


@dataclass
class ManifestFileMeta:
    file_name: str
    file_size: int
    num_added_files: int
    num_deleted_files: int
    partition_stats: SimpleStats
    schema_id: int
    min_row_id: Optional[int] = None
    max_row_id: Optional[int] = None

    def to_avro(self) -> dict:
        return {
            "_VERSION": META_VERSION,
            "_FILE_NAME": self.file_name,
            "_FILE_SIZE": self.file_size,
            "_NUM_ADDED_FILES": self.num_added_files,
            "_NUM_DELETED_FILES": self.num_deleted_files,
            "_PARTITION_STATS": self.partition_stats.to_avro(),
            "_SCHEMA_ID": self.schema_id,
            "_MIN_ROW_ID": self.min_row_id,
            "_MAX_ROW_ID": self.max_row_id,
        }

    @staticmethod
    def from_avro(d: dict) -> "ManifestFileMeta":
        return ManifestFileMeta(
            file_name=d["_FILE_NAME"],
            file_size=d["_FILE_SIZE"],
            num_added_files=d["_NUM_ADDED_FILES"],
            num_deleted_files=d["_NUM_DELETED_FILES"],
            partition_stats=SimpleStats.from_avro(d["_PARTITION_STATS"]),
            schema_id=d["_SCHEMA_ID"],
            min_row_id=d.get("_MIN_ROW_ID"),
            max_row_id=d.get("_MAX_ROW_ID"),
        )


MANIFEST_FILE_META_AVRO_SCHEMA = {
    "type": "record",
    "name": "ManifestFileMeta",
    "fields": [
        {"name": "_VERSION", "type": "int"},
        {"name": "_FILE_NAME", "type": "string"},
        {"name": "_FILE_SIZE", "type": "long"},
        {"name": "_NUM_ADDED_FILES", "type": "long"},
        {"name": "_NUM_DELETED_FILES", "type": "long"},
        {"name": "_PARTITION_STATS", "type": {
            "type": "record", "name": "record_PARTITION_STATS", "fields": [
                {"name": "_MIN_VALUES", "type": "bytes"},
                {"name": "_MAX_VALUES", "type": "bytes"},
                {"name": "_NULL_COUNTS",
                 "type": ["null", {"type": "array",
                                   "items": ["null", "long"]}],
                 "default": None},
            ]}},
        {"name": "_SCHEMA_ID", "type": "long"},
        {"name": "_MIN_ROW_ID", "type": ["null", "long"], "default": None},
        {"name": "_MAX_ROW_ID", "type": ["null", "long"], "default": None},
    ],
}


class ManifestFile:
    """Reads/writes manifest-<uuid>-<n> files under <table>/manifest/."""

    def __init__(self, file_io: FileIO, manifest_dir: str,
                 compression: str = "zstandard",
                 partition_types: Optional[list] = None):
        self.file_io = file_io
        self.manifest_dir = manifest_dir.rstrip("/")
        self.compression = compression
        self.partition_types = partition_types or []
        self._suffix = 0

    def new_file_name(self) -> str:
        name = f"manifest-{uuid.uuid4()}-{self._suffix}"
        self._suffix += 1
        return name

    def path(self, name: str) -> str:
        return f"{self.manifest_dir}/{name}"

    def write(self, entries: Sequence[ManifestEntry],
              schema_id: int = 0) -> ManifestFileMeta:
        name = self.new_file_name()
        data = avro_fmt.write_container(
            MANIFEST_ENTRY_AVRO_SCHEMA, [e.to_avro() for e in entries],
            codec=self.compression)
        self.file_io.write_bytes(self.path(name), data, overwrite=False)
        num_added = sum(1 for e in entries if e.kind == FileKind.ADD)
        num_deleted = len(entries) - num_added
        return ManifestFileMeta(
            file_name=name,
            file_size=len(data),
            num_added_files=num_added,
            num_deleted_files=num_deleted,
            partition_stats=self._partition_stats(entries),
            schema_id=schema_id,
        )

    def read(self, name: str) -> List[ManifestEntry]:
        _, records = avro_fmt.read_container(
            self.file_io.read_bytes(self.path(name)))
        return [ManifestEntry.from_avro(r) for r in records]

    def delete(self, name: str):
        self.file_io.delete_quietly(self.path(name))

    def _partition_stats(self,
                         entries: Sequence[ManifestEntry]) -> SimpleStats:
        if not self.partition_types or not entries:
            return SimpleStats.EMPTY
        from paimon_tpu.data.binary_row import BinaryRowCodec
        codec = BinaryRowCodec(self.partition_types)
        arity = len(self.partition_types)
        mins = [None] * arity
        maxs = [None] * arity
        nulls = [0] * arity
        for e in entries:
            values = codec.from_bytes(e.partition)
            for i, v in enumerate(values):
                if v is None:
                    nulls[i] += 1
                    continue
                if mins[i] is None or v < mins[i]:
                    mins[i] = v
                if maxs[i] is None or v > maxs[i]:
                    maxs[i] = v
        return SimpleStats(codec.to_bytes(mins), codec.to_bytes(maxs), nulls)


class ManifestList:
    """Reads/writes manifest-list-<uuid>-<n> files."""

    def __init__(self, file_io: FileIO, manifest_dir: str,
                 compression: str = "zstandard"):
        self.file_io = file_io
        self.manifest_dir = manifest_dir.rstrip("/")
        self.compression = compression
        self._suffix = 0

    def new_file_name(self) -> str:
        name = f"manifest-list-{uuid.uuid4()}-{self._suffix}"
        self._suffix += 1
        return name

    def path(self, name: str) -> str:
        return f"{self.manifest_dir}/{name}"

    def write(self, metas: Sequence[ManifestFileMeta]) -> Tuple[str, int]:
        name = self.new_file_name()
        data = avro_fmt.write_container(
            MANIFEST_FILE_META_AVRO_SCHEMA, [m.to_avro() for m in metas],
            codec=self.compression)
        self.file_io.write_bytes(self.path(name), data, overwrite=False)
        return name, len(data)

    def read(self, name: str) -> List[ManifestFileMeta]:
        _, records = avro_fmt.read_container(
            self.file_io.read_bytes(self.path(name)))
        return [ManifestFileMeta.from_avro(r) for r in records]

    def read_all(self, base_name: str,
                 delta_name: Optional[str]) -> List[ManifestFileMeta]:
        out = self.read(base_name) if base_name else []
        if delta_name:
            out.extend(self.read(delta_name))
        return out

    def delete(self, name: str):
        self.file_io.delete_quietly(self.path(name))
