"""DataFileMeta: metadata of one data/changelog file.

reference: paimon-core/.../io/DataFileMeta.java:60 (367 lines) and the avro
wire schema in spec manifest.md (18 fields, _FILE_NAME ... _EXTERNAL_PATH).
"""

from __future__ import annotations

import time as _time
from dataclasses import dataclass, field, replace
from typing import List, Optional

from paimon_tpu.manifest.simple_stats import SimpleStats

__all__ = ["DataFileMeta", "FileSource"]


class FileSource:
    APPEND = 0
    COMPACT = 1


@dataclass
class DataFileMeta:
    file_name: str
    file_size: int
    row_count: int
    min_key: bytes            # BinaryRow of trimmed pk
    max_key: bytes
    key_stats: SimpleStats
    value_stats: SimpleStats
    min_sequence_number: int
    max_sequence_number: int
    schema_id: int
    level: int
    extra_files: List[str] = field(default_factory=list)
    creation_time: Optional[int] = None        # epoch millis
    delete_row_count: Optional[int] = None
    embedded_index: Optional[bytes] = None
    file_source: Optional[int] = FileSource.APPEND
    value_stats_cols: Optional[List[str]] = None
    external_path: Optional[str] = None
    first_row_id: Optional[int] = None
    write_cols: Optional[List[str]] = None

    def __post_init__(self):
        if self.creation_time is None:
            self.creation_time = int(_time.time() * 1000)

    @property
    def add_row_count(self) -> int:
        return self.row_count - (self.delete_row_count or 0)

    def upgrade(self, new_level: int) -> "DataFileMeta":
        """Metadata-only level promotion (reference DataFileMeta.upgrade)."""
        return replace(self, level=new_level)

    def rename(self, new_name: str) -> "DataFileMeta":
        return replace(self, file_name=new_name)

    def copy_without_stats(self) -> "DataFileMeta":
        return replace(self, value_stats=SimpleStats.EMPTY,
                       value_stats_cols=[])

    # -- avro wire -----------------------------------------------------------

    def to_avro(self) -> dict:
        return {
            "_FILE_NAME": self.file_name,
            "_FILE_SIZE": self.file_size,
            "_ROW_COUNT": self.row_count,
            "_MIN_KEY": self.min_key,
            "_MAX_KEY": self.max_key,
            "_KEY_STATS": self.key_stats.to_avro(),
            "_VALUE_STATS": self.value_stats.to_avro(),
            "_MIN_SEQUENCE_NUMBER": self.min_sequence_number,
            "_MAX_SEQUENCE_NUMBER": self.max_sequence_number,
            "_SCHEMA_ID": self.schema_id,
            "_LEVEL": self.level,
            "_EXTRA_FILES": self.extra_files,
            "_CREATION_TIME": self.creation_time,
            "_DELETE_ROW_COUNT": self.delete_row_count,
            "_EMBEDDED_FILE_INDEX": self.embedded_index,
            "_FILE_SOURCE": self.file_source,
            "_VALUE_STATS_COLS": self.value_stats_cols,
            "_EXTERNAL_PATH": self.external_path,
            "_FIRST_ROW_ID": self.first_row_id,
            "_WRITE_COLS": self.write_cols,
        }

    @staticmethod
    def from_avro(d: dict) -> "DataFileMeta":
        return DataFileMeta(
            file_name=d["_FILE_NAME"],
            file_size=d["_FILE_SIZE"],
            row_count=d["_ROW_COUNT"],
            min_key=bytes(d["_MIN_KEY"]),
            max_key=bytes(d["_MAX_KEY"]),
            key_stats=SimpleStats.from_avro(d["_KEY_STATS"]),
            value_stats=SimpleStats.from_avro(d["_VALUE_STATS"]),
            min_sequence_number=d["_MIN_SEQUENCE_NUMBER"],
            max_sequence_number=d["_MAX_SEQUENCE_NUMBER"],
            schema_id=d["_SCHEMA_ID"],
            level=d["_LEVEL"],
            extra_files=list(d.get("_EXTRA_FILES") or []),
            creation_time=d.get("_CREATION_TIME"),
            delete_row_count=d.get("_DELETE_ROW_COUNT"),
            embedded_index=(bytes(d["_EMBEDDED_FILE_INDEX"])
                            if d.get("_EMBEDDED_FILE_INDEX") is not None
                            else None),
            file_source=d.get("_FILE_SOURCE"),
            value_stats_cols=d.get("_VALUE_STATS_COLS"),
            external_path=d.get("_EXTERNAL_PATH"),
            first_row_id=d.get("_FIRST_ROW_ID"),
            write_cols=d.get("_WRITE_COLS"),
        )


DATA_FILE_META_AVRO_SCHEMA = {
    "type": "record",
    "name": "DataFileMeta",
    "fields": [
        {"name": "_FILE_NAME", "type": "string"},
        {"name": "_FILE_SIZE", "type": "long"},
        {"name": "_ROW_COUNT", "type": "long"},
        {"name": "_MIN_KEY", "type": "bytes"},
        {"name": "_MAX_KEY", "type": "bytes"},
        {"name": "_KEY_STATS", "type": {
            "type": "record", "name": "record_KEY_STATS", "fields": [
                {"name": "_MIN_VALUES", "type": "bytes"},
                {"name": "_MAX_VALUES", "type": "bytes"},
                {"name": "_NULL_COUNTS",
                 "type": ["null", {"type": "array",
                                   "items": ["null", "long"]}],
                 "default": None},
            ]}},
        {"name": "_VALUE_STATS", "type": {
            "type": "record", "name": "record_VALUE_STATS", "fields": [
                {"name": "_MIN_VALUES", "type": "bytes"},
                {"name": "_MAX_VALUES", "type": "bytes"},
                {"name": "_NULL_COUNTS",
                 "type": ["null", {"type": "array",
                                   "items": ["null", "long"]}],
                 "default": None},
            ]}},
        {"name": "_MIN_SEQUENCE_NUMBER", "type": "long"},
        {"name": "_MAX_SEQUENCE_NUMBER", "type": "long"},
        {"name": "_SCHEMA_ID", "type": "long"},
        {"name": "_LEVEL", "type": "int"},
        {"name": "_EXTRA_FILES", "type": {"type": "array",
                                          "items": "string"}},
        {"name": "_CREATION_TIME",
         "type": ["null", {"type": "long",
                           "logicalType": "timestamp-millis"}],
         "default": None},
        {"name": "_DELETE_ROW_COUNT", "type": ["null", "long"],
         "default": None},
        {"name": "_EMBEDDED_FILE_INDEX", "type": ["null", "bytes"],
         "default": None},
        {"name": "_FILE_SOURCE", "type": ["null", "int"], "default": None},
        {"name": "_VALUE_STATS_COLS",
         "type": ["null", {"type": "array", "items": "string"}],
         "default": None},
        {"name": "_EXTERNAL_PATH", "type": ["null", "string"],
         "default": None},
        {"name": "_FIRST_ROW_ID", "type": ["null", "long"], "default": None},
        {"name": "_WRITE_COLS",
         "type": ["null", {"type": "array", "items": "string"}],
         "default": None},
    ],
}
