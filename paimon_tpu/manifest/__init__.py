"""Manifest metadata layer (avro object files).

reference: paimon-core/.../manifest/ (ManifestEntry, ManifestFile,
ManifestList, IndexManifestFile, SimpleStats, FileEntry merge logic);
spec docs/docs/concepts/spec/manifest.md.
"""

from paimon_tpu.manifest.simple_stats import SimpleStats  # noqa: F401
from paimon_tpu.manifest.data_file_meta import DataFileMeta, FileSource  # noqa: F401
from paimon_tpu.manifest.manifest_entry import (  # noqa: F401
    FileKind, ManifestEntry, merge_manifest_entries,
)
from paimon_tpu.manifest.manifest_file import (  # noqa: F401
    ManifestFile, ManifestFileMeta, ManifestList,
)
from paimon_tpu.manifest.index_manifest import (  # noqa: F401
    IndexFileMeta, IndexManifestEntry, IndexManifestFile,
)
