"""Ray Datasets adapter (reference pypaimon/ray/ray_paimon.py).

Ray is not part of this image, so the adapter is import-gated: the
split-level plumbing (plan -> per-split Arrow read tasks) is plain
Python and unit-testable; the final `ray.data.Dataset` construction
needs ray installed.
"""

from typing import Any, Dict, List, Optional


def _require_ray():
    try:
        import ray  # noqa: F401
        import ray.data  # noqa: F401
        return ray
    except ImportError as e:
        raise ImportError(
            "ray is not installed; `pip install 'ray[data]'` to use "
            "paimon_tpu.integrations.ray_data") from e


def split_read_tasks(table, projection: Optional[List[str]] = None,
                     predicate=None) -> List[Dict[str, Any]]:
    """One task descriptor per split: {'fn': zero-arg callable -> Arrow
    table, 'num_rows': hint}.  This is the engine-agnostic core the Ray
    datasource maps over its workers (Ray owns cross-split parallelism
    there, so each task is a single serial split read)."""
    rb = table.new_read_builder()
    if projection:
        rb = rb.with_projection(projection)
    if predicate is not None:
        rb = rb.with_filter(predicate)
    plan = rb.new_scan().plan()

    tasks = []
    for split in plan.splits:
        def fn(split=split, rb=rb):
            return rb.new_read().read_split(split)

        tasks.append({
            "fn": fn,
            "num_rows": sum(f.row_count for f in split.data_files),
        })
    return tasks


def scan_batches(table, projection: Optional[List[str]] = None,
                 predicate=None, ordered: bool = True):
    """Yield per-split Arrow tables through the pipelined scan executor
    (parallel/scan_pipeline.py) — the in-process counterpart of
    `split_read_tasks` for engines that don't bring their own scheduler
    (daft handoff, plain python consumers)."""
    rb = table.new_read_builder()
    if projection:
        rb = rb.with_projection(projection)
    if predicate is not None:
        rb = rb.with_filter(predicate)
    plan = rb.new_scan().plan()
    read = rb.new_read()
    for _, _, t in read.iter_splits(plan.splits, ordered=ordered):
        yield t


def to_ray_dataset(table, projection: Optional[List[str]] = None,
                   predicate=None, parallelism: int = -1):
    """`ray.data.Dataset` over the table: each split becomes one read
    task scheduled by Ray (reference ray_paimon.read_paimon)."""
    ray = _require_ray()
    tasks = split_read_tasks(table, projection, predicate)
    if not tasks:
        import pyarrow as pa
        return ray.data.from_arrow(
            pa.Table.from_pylist([], schema=table.arrow_schema()))
    ds = ray.data.from_items([i for i in range(len(tasks))],
                             override_num_blocks=len(tasks)
                             if parallelism < 0 else parallelism)
    return ds.map_batches(
        lambda batch: tasks[int(batch["item"][0])]["fn"](),
        batch_size=1, batch_format="numpy")
