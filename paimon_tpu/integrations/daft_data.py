"""Daft adapter (reference pypaimon/daft/daft_datasource.py).

Daft is not part of this image; like ray_data, the split plumbing is
shared (`ray_data.split_read_tasks`) and only the DataFrame handoff
needs daft installed.
"""

from typing import List, Optional

from paimon_tpu.integrations.ray_data import scan_batches


def _require_daft():
    try:
        import daft
        return daft
    except ImportError as e:
        raise ImportError(
            "daft is not installed; `pip install daft` to use "
            "paimon_tpu.integrations.daft_data") from e


def to_daft_dataframe(table, projection: Optional[List[str]] = None,
                      predicate=None):
    """daft.DataFrame over the table's current snapshot (reference
    daft_paimon.read_paimon).  Reads the splits into Arrow and hands
    the batches to daft; predicate/projection pushdown happened in the
    paimon scan."""
    daft = _require_daft()
    import pyarrow as pa

    # pipelined split reads (parallel/scan_pipeline.py): splits decode
    # concurrently instead of the previous serial per-task loop
    batches = list(scan_batches(table, projection, predicate))
    if not batches:
        schema = table.arrow_schema()
        if projection:
            schema = pa.schema([schema.field(c) for c in projection])
        return daft.from_arrow(pa.Table.from_pylist([], schema=schema))
    return daft.from_arrow(pa.concat_tables(batches,
                                            promote_options="none"))
