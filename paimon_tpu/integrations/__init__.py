"""Engine/data-loader integrations (reference paimon-python engines:
pypaimon/ray/, pypaimon/daft/, plus the JVM connectors' role).

- torch_data:  PyTorch IterableDataset / DataLoader over table scans
- jax_data:    device-placed jax batch iterator (the TPU-native loader)
- ray_data:    Ray Datasets adapter (gated on ray being installed)
- daft_data:   Daft DataFrame adapter (gated on daft being installed)
"""
