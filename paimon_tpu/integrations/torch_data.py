"""PyTorch integration: stream a paimon table as an IterableDataset.

The reference integrates with Python training stacks through Ray/Daft
readers (pypaimon/ray/ray_paimon.py, daft/daft_datasource.py) whose
unit of parallelism is the paimon split.  Same design here: the scan
plan's splits are the shard unit — split across DataLoader workers (and
optionally across distributed ranks), each worker merge-reads only its
own splits, so no two workers decode the same file.

Numeric columns become torch tensors; strings/binaries/other types stay
as Python lists per batch.
"""

from typing import Any, Dict, Iterator, List, Optional

import pyarrow as pa
import torch.utils.data as _tud


def _to_torch_batch(t: pa.Table) -> Dict[str, Any]:
    import numpy as np
    import torch

    out: Dict[str, Any] = {}
    for name in t.column_names:
        col = t.column(name)
        if pa.types.is_integer(col.type) or pa.types.is_floating(col.type) \
                or pa.types.is_boolean(col.type):
            np_col = col.to_numpy(zero_copy_only=False)
            if np_col.dtype == np.bool_:
                np_col = np_col.astype(np.uint8)
            out[name] = torch.from_numpy(np_col)
        else:
            out[name] = col.to_pylist()
    return out


class PaimonIterableDataset(_tud.IterableDataset):
    """`torch.utils.data.IterableDataset` over a table scan.

    Splits are deterministically assigned round-robin to
    (rank, worker) pairs, so the union over all workers of all ranks is
    exactly one pass over the table.  A plain module-level subclass so
    instances pickle for spawn/forkserver DataLoader workers.
    """

    def __init__(self, table, projection: Optional[List[str]] = None,
                 predicate=None, batch_size: int = 8192,
                 rank: int = 0, world_size: int = 1):
        self.table = table
        self.projection = projection
        self.predicate = predicate
        self.batch_size = batch_size
        self.rank = rank
        self.world_size = world_size

    def _read_builder(self):
        rb = self.table.new_read_builder()
        if self.projection:
            rb = rb.with_projection(self.projection)
        if self.predicate is not None:
            rb = rb.with_filter(self.predicate)
        return rb

    def __iter__(self) -> Iterator[Dict[str, Any]]:
        import torch.utils.data as tud

        info = tud.get_worker_info()
        wid = info.id if info is not None else 0
        nworkers = info.num_workers if info is not None else 1
        shard = self.rank * nworkers + wid
        nshards = self.world_size * nworkers

        rb = self._read_builder()
        splits = rb.new_scan().plan().splits
        read = rb.new_read()
        mine = [s for i, s in enumerate(splits) if i % nshards == shard]
        # pipelined reader (parallel/scan_pipeline.py): the next split
        # downloads/decodes while this worker converts batches
        for _, _, t in read.iter_splits(mine):
            for start in range(0, t.num_rows, self.batch_size):
                yield _to_torch_batch(t.slice(start, self.batch_size))


def to_torch_dataloader(table, projection: Optional[List[str]] = None,
                        predicate=None, batch_size: int = 8192,
                        num_workers: int = 0, **loader_kwargs):
    """A DataLoader of column-dict batches.  Batching happens at the
    Arrow layer (batch_size rows per yielded dict), so the loader runs
    with batch_size=None (no re-collation)."""
    import torch.utils.data as tud

    ds = PaimonIterableDataset(table, projection, predicate, batch_size)
    return tud.DataLoader(ds, batch_size=None, num_workers=num_workers,
                          **loader_kwargs)
