"""TPU-native data loading: stream a table as device-placed jax batches.

This is the loader a jax training loop uses instead of the reference's
Ray/torch readers: fixed-shape batches (static shapes keep XLA from
recompiling per step), numeric columns stacked as device arrays,
optional sharding over a `jax.sharding.Mesh` axis so each device gets
its slice without a host-side gather.
"""

from typing import Any, Dict, Iterator, List, Optional

import numpy as np
import pyarrow as pa


def _numeric_columns(schema: pa.Schema,
                     projection: Optional[List[str]]) -> List[str]:
    names = projection or schema.names
    out = []
    for n in names:
        t = schema.field(n).type
        if pa.types.is_integer(t) or pa.types.is_floating(t) or \
                pa.types.is_boolean(t):
            out.append(n)
    return out


def jax_batches(table, batch_size: int,
                projection: Optional[List[str]] = None,
                predicate=None,
                drop_remainder: bool = True,
                sharding=None) -> Iterator[Dict[str, Any]]:
    """Yield dicts of jax arrays of EXACTLY batch_size rows (fixed
    shapes; a short tail is dropped unless drop_remainder=False, where
    it is zero-padded and yielded with a `_mask` bool array).

    Non-numeric columns are skipped — a training loop consumes numbers;
    use torch_data / to_arrow for heterogeneous reads.

    sharding: an optional `jax.sharding.Sharding` applied on device_put
    (e.g. NamedSharding(mesh, P("data")) to scatter the batch across a
    data-parallel mesh axis).
    """
    import jax

    rb = table.new_read_builder()
    if projection:
        rb = rb.with_projection(projection)
    if predicate is not None:
        rb = rb.with_filter(predicate)
    plan = rb.new_scan().plan()
    read = rb.new_read()
    cols = _numeric_columns(table.arrow_schema(), projection)
    if not cols:
        raise ValueError("no numeric columns to batch; pass a "
                         "projection of numeric fields")

    def put(arrs: Dict[str, np.ndarray]) -> Dict[str, Any]:
        if sharding is not None:
            return {k: jax.device_put(v, sharding)
                    for k, v in arrs.items()}
        return {k: jax.device_put(v) for k, v in arrs.items()}

    pending: List[pa.Table] = []
    buffered = 0
    for split in plan.splits:
        t = read.read_split(split).select(cols)
        pending.append(t)
        buffered += t.num_rows
        while buffered >= batch_size:
            merged = pa.concat_tables(pending, promote_options="none")
            head = merged.slice(0, batch_size)
            rest = merged.slice(batch_size)
            pending = [rest] if rest.num_rows else []
            buffered = rest.num_rows
            yield put({c: head.column(c).to_numpy(zero_copy_only=False)
                       for c in cols})
    if buffered and not drop_remainder:
        merged = pa.concat_tables(pending, promote_options="none")
        arrs = {}
        mask = np.zeros(batch_size, dtype=bool)
        mask[:merged.num_rows] = True
        for c in cols:
            v = merged.column(c).to_numpy(zero_copy_only=False)
            padded = np.zeros(batch_size, dtype=v.dtype)
            padded[: len(v)] = v
            arrs[c] = padded
        batch = put(arrs)
        batch["_mask"] = jax.device_put(mask) if sharding is None else \
            jax.device_put(mask, sharding)
        yield batch
