"""TPU-native data loading: stream a table as device-placed jax batches.

This is the loader a jax training loop uses instead of the reference's
Ray/torch readers: fixed-shape batches (static shapes keep XLA from
recompiling per step), numeric columns stacked as device arrays,
optional sharding over a `jax.sharding.Mesh` axis so each device gets
its slice without a host-side gather.

Split reads route through the pipelined scan executor
(parallel/scan_pipeline.py): worker threads download/decode/merge the
next splits while the training loop consumes the current batch, and a
device-put double buffer issues step N+1's (async) host-to-device
transfer before step N's batch is handed out — the accelerator never
waits on the object store for a warm pipeline.
"""

from typing import Any, Dict, Iterator, List, Optional

import numpy as np
import pyarrow as pa


def _numeric_columns(schema: pa.Schema,
                     projection: Optional[List[str]]) -> List[str]:
    names = projection or schema.names
    out = []
    for n in names:
        t = schema.field(n).type
        if pa.types.is_integer(t) or pa.types.is_floating(t) or \
                pa.types.is_boolean(t):
            out.append(n)
    return out


def jax_batches(table, batch_size: int,
                projection: Optional[List[str]] = None,
                predicate=None,
                drop_remainder: bool = True,
                sharding=None,
                ordered: bool = True) -> Iterator[Dict[str, Any]]:
    """Yield dicts of jax arrays of EXACTLY batch_size rows (fixed
    shapes; a short tail is dropped unless drop_remainder=False, where
    it is zero-padded and yielded with a `_mask` bool array).

    Non-numeric columns are skipped — a training loop consumes numbers;
    use torch_data / to_arrow for heterogeneous reads.

    sharding: an optional `jax.sharding.Sharding` applied on device_put
    (e.g. NamedSharding(mesh, P("data")) to scatter the batch across a
    data-parallel mesh axis).

    ordered=False lets splits arrive in completion order (faster under
    skew); set it only when batch composition across epochs need not be
    deterministic.
    """
    import jax

    rb = table.new_read_builder()
    if projection:
        rb = rb.with_projection(projection)
    if predicate is not None:
        rb = rb.with_filter(predicate)
    plan = rb.new_scan().plan()
    read = rb.new_read()
    cols = _numeric_columns(table.arrow_schema(), projection)
    if not cols:
        raise ValueError("no numeric columns to batch; pass a "
                         "projection of numeric fields")

    def put(arrs: Dict[str, np.ndarray]) -> Dict[str, Any]:
        if sharding is not None:
            return {k: jax.device_put(v, sharding)
                    for k, v in arrs.items()}
        return {k: jax.device_put(v) for k, v in arrs.items()}

    def host_batches() -> Iterator[Dict[str, np.ndarray]]:
        """Fixed-size numpy batches off the pipelined split reader."""
        pending: List[pa.Table] = []
        buffered = 0
        for _, _, t in read.iter_splits(plan.splits, ordered=ordered):
            t = t.select(cols)
            pending.append(t)
            buffered += t.num_rows
            while buffered >= batch_size:
                merged = pa.concat_tables(pending,
                                          promote_options="none")
                head = merged.slice(0, batch_size)
                rest = merged.slice(batch_size)
                pending = [rest] if rest.num_rows else []
                buffered = rest.num_rows
                yield {c: head.column(c).to_numpy(zero_copy_only=False)
                       for c in cols}
        if buffered and not drop_remainder:
            merged = pa.concat_tables(pending, promote_options="none")
            arrs = {}
            mask = np.zeros(batch_size, dtype=bool)
            mask[:merged.num_rows] = True
            for c in cols:
                v = merged.column(c).to_numpy(zero_copy_only=False)
                padded = np.zeros(batch_size, dtype=v.dtype)
                padded[: len(v)] = v
                arrs[c] = padded
            arrs["_mask"] = mask
            yield arrs

    # device-put double buffer: device_put is asynchronous, so issuing
    # batch N+1's transfer before yielding batch N overlaps the H2D
    # copy with the consumer's step on batch N
    staged: Optional[Dict[str, Any]] = None
    for arrs in host_batches():
        mask = arrs.pop("_mask", None)
        batch = put(arrs)
        if mask is not None:
            batch["_mask"] = jax.device_put(mask) if sharding is None \
                else jax.device_put(mask, sharding)
        if staged is not None:
            yield staged
        staged = batch
    if staged is not None:
        yield staged
