"""Iceberg compatibility: dual-write Iceberg metadata so Iceberg readers
can open paimon-tpu tables.

reference: paimon-core/.../iceberg/ (IcebergCommitCallback, metadata/
IcebergMetadata JSON, manifest/ avro manifests) + paimon-iceberg module.
"""

from paimon_tpu.iceberg.metadata import sync_iceberg  # noqa: F401
