"""Iceberg REST catalog committer: publish the exported Iceberg
metadata to an Iceberg-REST-protocol catalog so any Iceberg REST reader
sees paimon tables without touching paimon metadata.

reference: paimon-iceberg/.../IcebergRestMetadataCommitter.java —
semantics mirrored (not translated): load-or-create the table in the
REST catalog; when the catalog's current state matches the base we
exported from, commit the new snapshot with a CAS requirement on the
main branch's snapshot id; when the base is incorrect (diverged /
manually edited), drop and recreate, same as the reference's
recreateTable() path. Wire format is the public Apache Iceberg REST
catalog OpenAPI: POST /v1/{prefix}/namespaces/{ns}/tables/{table} with
`requirements` (assert-table-uuid / assert-ref-snapshot-id) and
`updates` (add-snapshot, set-snapshot-ref, remove-snapshots, ...).

IcebergRESTCatalogServer is the loopback protocol double used by tests
(role of the reference's RESTCatalogServer test harness): it applies
updates under requirement checks (409 CommitFailedException on CAS
miss) and persists each committed metadata JSON at a
`metadata-location`, which independent readers (iceberg/reader.py)
consume directly.
"""

from __future__ import annotations

import json
import threading
import urllib.error
import urllib.request
import uuid as uuid_mod
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

__all__ = [
    "IcebergRestClient", "IcebergRestCommitter",
    "IcebergRESTCatalogServer", "IcebergCommitConflictError",
]


class IcebergCommitConflictError(RuntimeError):
    """CAS requirement failed at the REST catalog (409)."""


class IcebergRestClient:
    """Minimal Iceberg REST catalog protocol client."""

    def __init__(self, uri: str, prefix: str = "",
                 auth_provider=None, timeout: float = 30.0):
        self.uri = uri.rstrip("/")
        self.prefix = prefix.strip("/")
        self.auth = auth_provider
        self.timeout = timeout

    def _path(self, suffix: str) -> str:
        base = f"/v1/{self.prefix}" if self.prefix else "/v1"
        return f"{base}/{suffix}"

    def _request(self, method: str, suffix: str,
                 body: Optional[dict] = None) -> dict:
        path = self._path(suffix)
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(self.uri + path, data=data,
                                     method=method)
        req.add_header("Content-Type", "application/json")
        if self.auth is not None:
            for k, v in self.auth.auth_headers(
                    method, path, None,
                    data.decode() if data else None).items():
                req.add_header(k, v)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                payload = r.read()
                return json.loads(payload) if payload else {}
        except urllib.error.HTTPError as e:
            try:
                detail = json.loads(e.read())
            except Exception:
                detail = {}
            if e.code == 409:
                raise IcebergCommitConflictError(
                    detail.get("error", {}).get("message", str(e)))
            if e.code == 404:
                raise FileNotFoundError(path)
            raise RuntimeError(
                f"iceberg rest {method} {path}: {e.code} {detail}") from e

    # -- protocol operations ------------------------------------------------

    def config(self) -> dict:
        return self._request("GET", "config")

    def create_namespace(self, ns: str):
        try:
            self._request("POST", "namespaces", {"namespace": [ns]})
        except IcebergCommitConflictError:
            pass    # already exists

    def load_table(self, ns: str, table: str) -> Optional[dict]:
        """-> {"metadata-location": ..., "metadata": {...}} or None."""
        try:
            return self._request("GET", f"namespaces/{ns}/tables/{table}")
        except FileNotFoundError:
            return None

    def create_table(self, ns: str, table: str, metadata: dict) -> dict:
        return self._request(
            "POST", f"namespaces/{ns}/tables",
            {"name": table, "metadata": metadata})

    def drop_table(self, ns: str, table: str):
        try:
            self._request("DELETE", f"namespaces/{ns}/tables/{table}")
        except FileNotFoundError:
            pass

    def commit_table(self, ns: str, table: str,
                     requirements: List[dict],
                     updates: List[dict]) -> dict:
        return self._request(
            "POST", f"namespaces/{ns}/tables/{table}",
            {"requirements": requirements, "updates": updates})


class IcebergRestCommitter:
    """Publishes exported metadata (iceberg/metadata.py dict) to a REST
    catalog. reference IcebergRestMetadataCommitter.commitMetadata:
    the same load -> create | CAS-commit | recreate decision tree."""

    def __init__(self, client: IcebergRestClient, namespace: str,
                 table: str):
        self.client = client
        self.namespace = namespace
        self.table = table

    def commit_metadata(self, metadata: dict,
                        base_snapshot_id: Optional[int]) -> dict:
        """Commit `metadata` (a full replacement export whose snapshots
        list holds exactly the current snapshot). `base_snapshot_id` is
        the snapshot the export was derived from (None = first export).
        Returns the catalog's load-table response after commit."""
        c = self.client
        c.create_namespace(self.namespace)
        current = c.load_table(self.namespace, self.table)
        if current is None:
            c.create_table(self.namespace, self.table, metadata)
            return c.load_table(self.namespace, self.table)

        cur_meta = current["metadata"]
        cur_snap = cur_meta.get("current-snapshot-id")
        if base_snapshot_id is not None and cur_snap != base_snapshot_id \
                and cur_snap != metadata["current-snapshot-id"]:
            # incorrect base: catalog diverged from what we exported
            # from — recreate, as the reference does (recreateTable)
            c.drop_table(self.namespace, self.table)
            c.create_table(self.namespace, self.table, metadata)
            return c.load_table(self.namespace, self.table)

        snapshot = metadata["snapshots"][-1]
        requirements = [
            {"type": "assert-table-uuid",
             "uuid": cur_meta.get("table-uuid")},
            # the CAS: main must still point at the base we exported from
            {"type": "assert-ref-snapshot-id", "ref": "main",
             "snapshot-id": base_snapshot_id},
        ]
        old_ids = [s["snapshot-id"] for s in cur_meta.get("snapshots", [])
                   if s["snapshot-id"] != snapshot["snapshot-id"]]
        updates: List[dict] = [
            {"action": "add-schema",
             "schema": metadata["schemas"][-1],
             "last-column-id": metadata["last-column-id"]},
            {"action": "set-current-schema", "schema-id": -1},
            {"action": "add-snapshot", "snapshot": snapshot},
            {"action": "set-snapshot-ref", "ref-name": "main",
             "type": "branch",
             "snapshot-id": snapshot["snapshot-id"]},
            {"action": "set-properties",
             "updates": metadata.get("properties", {})},
        ]
        if old_ids:
            updates.append({"action": "remove-snapshots",
                            "snapshot-ids": old_ids})
        c.commit_table(self.namespace, self.table, requirements, updates)
        return c.load_table(self.namespace, self.table)


# ---------------------------------------------------------------------------
# loopback protocol server (test double / single-host catalog service)
# ---------------------------------------------------------------------------

class _TableState:
    def __init__(self, metadata: dict, location: str):
        self.metadata = metadata
        self.metadata_location = location


class IcebergRESTCatalogServer:
    """Implements the subset of the Iceberg REST catalog protocol the
    committer uses, with real requirement enforcement and durable
    metadata: every committed version is written as
    `<warehouse>/<ns>/<table>/metadata/rest-v<N>.metadata.json` so an
    independent reader can consume the `metadata-location` it returns.
    """

    def __init__(self, warehouse: str, file_io=None,
                 auth_check=None, host: str = "127.0.0.1", port: int = 0):
        from paimon_tpu.fs.fileio import LocalFileIO
        self.warehouse = warehouse.rstrip("/")
        self.file_io = file_io or LocalFileIO()
        self.auth_check = auth_check   # fn(handler, method, path, body)
        self._tables: Dict[Tuple[str, str], _TableState] = {}
        self._namespaces = set()
        self._lock = threading.Lock()
        self.httpd = ThreadingHTTPServer((host, port),
                                         self._make_handler())
        self.port = self.httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    @property
    def uri(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def start(self):
        from paimon_tpu.parallel.executors import spawn_thread
        self._thread = spawn_thread(self.httpd.serve_forever,
                                    name="paimon-iceberg-rest")
        return self

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()

    # -- state transitions (under lock) -------------------------------------

    def _persist(self, ns: str, table: str, metadata: dict) -> str:
        version = int(metadata.get("_rest-version", 0)) + 1
        metadata = {k: v for k, v in metadata.items()
                    if not k.startswith("_rest")}
        metadata["_rest-version"] = version
        loc = (f"{self.warehouse}/{ns}/{table}/metadata/"
               f"rest-v{version}.metadata.json")
        self.file_io.write_bytes(
            loc, json.dumps(metadata, indent=2).encode(), overwrite=True)
        self._tables[(ns, table)] = _TableState(metadata, loc)
        return loc

    def _apply_commit(self, ns: str, table: str, body: dict):
        state = self._tables.get((ns, table))
        if state is None:
            raise FileNotFoundError(f"{ns}.{table}")
        meta = json.loads(json.dumps(state.metadata))   # deep copy
        for req in body.get("requirements", []):
            kind = req.get("type")
            if kind == "assert-table-uuid":
                if meta.get("table-uuid") != req.get("uuid"):
                    raise IcebergCommitConflictError("table-uuid changed")
            elif kind == "assert-ref-snapshot-id":
                want = req.get("snapshot-id")
                have = meta.get("refs", {}).get(
                    req.get("ref-name", req.get("ref", "main")),
                    {}).get("snapshot-id",
                            meta.get("current-snapshot-id"))
                if want != have:
                    raise IcebergCommitConflictError(
                        f"ref {req.get('ref', 'main')} at {have}, "
                        f"required {want}")
            elif kind == "assert-create":
                raise IcebergCommitConflictError("table exists")
        for up in body.get("updates", []):
            action = up.get("action")
            if action == "add-schema":
                meta.setdefault("schemas", []).append(up["schema"])
                meta["last-column-id"] = max(
                    meta.get("last-column-id", 0),
                    up.get("last-column-id", 0))
            elif action == "set-current-schema":
                sid = up["schema-id"]
                if sid == -1:
                    sid = meta["schemas"][-1].get("schema-id", 0)
                meta["current-schema-id"] = sid
            elif action == "add-snapshot":
                snap = up["snapshot"]
                snaps = [s for s in meta.get("snapshots", [])
                         if s["snapshot-id"] != snap["snapshot-id"]]
                snaps.append(snap)
                meta["snapshots"] = snaps
                meta["last-sequence-number"] = max(
                    meta.get("last-sequence-number", 0),
                    snap.get("sequence-number", 0))
            elif action == "set-snapshot-ref":
                meta.setdefault("refs", {})[up["ref-name"]] = {
                    "snapshot-id": up["snapshot-id"],
                    "type": up.get("type", "branch")}
                if up["ref-name"] == "main":
                    meta["current-snapshot-id"] = up["snapshot-id"]
            elif action == "remove-snapshots":
                drop = set(up.get("snapshot-ids", []))
                meta["snapshots"] = [
                    s for s in meta.get("snapshots", [])
                    if s["snapshot-id"] not in drop]
            elif action == "set-properties":
                meta.setdefault("properties", {}).update(
                    up.get("updates", {}))
            elif action == "remove-properties":
                for k in up.get("removals", []):
                    meta.get("properties", {}).pop(k, None)
            elif action == "set-location":
                meta["location"] = up["location"]
        return self._persist(ns, table, meta)

    # -- HTTP plumbing -------------------------------------------------------

    def _make_handler(self):
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _reply(self, code: int, payload: dict):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _err(self, code: int, message: str):
                self._reply(code, {"error": {"message": message,
                                             "code": code}})

            def _handle(self, method: str):
                from urllib.parse import urlparse
                raw_path = urlparse(self.path).path
                n = int(self.headers.get("Content-Length", 0))
                raw_body = self.rfile.read(n).decode() if n else None
                if server.auth_check is not None and not \
                        server.auth_check(dict(self.headers), method,
                                          raw_path, raw_body):
                    return self._err(401, "unauthorized")
                body = json.loads(raw_body) if raw_body else {}
                parts = [p for p in raw_path.split("/") if p]
                if not parts or parts[0] != "v1":
                    return self._err(404, raw_path)
                parts = parts[1:]
                try:
                    return self._route(method, parts, body)
                except FileNotFoundError as e:
                    return self._err(404, str(e))
                except IcebergCommitConflictError as e:
                    return self._err(409, str(e))
                except Exception as e:      # noqa: BLE001
                    return self._err(500, str(e))

            def _route(self, method: str, parts: List[str], body: dict):
                with server._lock:
                    if parts == ["config"] and method == "GET":
                        return self._reply(200, {
                            "defaults": {}, "overrides": {}})
                    if parts == ["namespaces"] and method == "POST":
                        ns = ".".join(body["namespace"])
                        if ns in server._namespaces:
                            return self._err(409, "namespace exists")
                        server._namespaces.add(ns)
                        return self._reply(200, {"namespace": [ns]})
                    if len(parts) >= 3 and parts[0] == "namespaces" and \
                            parts[2] == "tables":
                        ns = parts[1]
                        if len(parts) == 3 and method == "POST":
                            name = body["name"]
                            if (ns, name) in server._tables:
                                return self._err(409, "table exists")
                            meta = dict(body["metadata"])
                            meta.setdefault("table-uuid",
                                            str(uuid_mod.uuid4()))
                            snap = meta.get("current-snapshot-id")
                            if snap is not None:
                                meta.setdefault("refs", {})["main"] = {
                                    "snapshot-id": snap,
                                    "type": "branch"}
                            loc = server._persist(ns, name, meta)
                            return self._reply(200, {
                                "metadata-location": loc,
                                "metadata": meta})
                        if len(parts) == 4:
                            name = parts[3]
                            if method == "GET":
                                st = server._tables.get((ns, name))
                                if st is None:
                                    raise FileNotFoundError(
                                        f"{ns}.{name}")
                                return self._reply(200, {
                                    "metadata-location":
                                        st.metadata_location,
                                    "metadata": st.metadata})
                            if method == "DELETE":
                                server._tables.pop((ns, name), None)
                                return self._reply(200, {})
                            if method == "POST":
                                loc = server._apply_commit(ns, name,
                                                           body)
                                st = server._tables[(ns, name)]
                                return self._reply(200, {
                                    "metadata-location": loc,
                                    "metadata": st.metadata})
                    return self._err(404, "/".join(parts))

            def do_GET(self):
                self._handle("GET")

            def do_POST(self):
                self._handle("POST")

            def do_DELETE(self):
                self._handle("DELETE")

        return Handler
