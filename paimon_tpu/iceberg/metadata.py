"""Iceberg v2 metadata generation.

reference: iceberg/IcebergCommitCallback.java + iceberg/metadata/*
(IcebergMetadata, IcebergSnapshot, IcebergSchema, IcebergPartitionSpec)
+ iceberg/manifest/* (avro manifest list + manifest entries). Layout:

    <table>/metadata/v<N>.metadata.json
    <table>/metadata/version-hint.text
    <table>/metadata/snap-<id>.avro              (manifest list)
    <table>/metadata/manifest-<uuid>.avro        (manifest entries)

Only data files the CURRENT paimon snapshot references are exported
(each sync is a full replacement snapshot — operation 'overwrite').
Append tables export every live file; primary-key tables export the
READ-OPTIMIZED view — top-level (fully compacted) files only, since an
Iceberg reader cannot run the merge — so upserts become visible to
Iceberg readers after a full compaction, matching the reference's pk
contract (docs/iceberg).
"""

from __future__ import annotations

import json
import uuid
from typing import Dict, List, Optional, Tuple

from paimon_tpu.format import avro as avro_fmt
from paimon_tpu.types import (
    BigIntType, BooleanType, DataType, DateType, DecimalType, DoubleType,
    FloatType, IntType, LocalZonedTimestampType, SmallIntType,
    TimestampType, TinyIntType, VarCharType,
)

__all__ = ["sync_iceberg"]


def _iceberg_type(t: DataType) -> str:
    if isinstance(t, BooleanType):
        return "boolean"
    if isinstance(t, (TinyIntType, SmallIntType, IntType)):
        return "int"
    if isinstance(t, BigIntType):
        return "long"
    if isinstance(t, FloatType):
        return "float"
    if isinstance(t, DoubleType):
        return "double"
    if isinstance(t, DateType):
        return "date"
    if isinstance(t, LocalZonedTimestampType):
        return "timestamptz"
    if isinstance(t, TimestampType):
        return "timestamp"
    if isinstance(t, DecimalType):
        return f"decimal({t.precision}, {t.scale})"
    return "string"


def _iceberg_schema(schema) -> dict:
    return {
        "type": "struct",
        "schema-id": schema.id,
        "fields": [{
            "id": f.id + 1,              # iceberg ids are 1-based
            "name": f.name,
            "required": not f.type.nullable,
            "type": _iceberg_type(f.type),
        } for f in schema.fields],
        "identifier-field-ids": [
            f.id + 1 for f in schema.fields
            if f.name in schema.primary_keys],
    }


def _partition_spec(schema) -> dict:
    fields = []
    by_name = {f.name: f for f in schema.fields}
    for i, k in enumerate(schema.partition_keys):
        fields.append({
            "name": k,
            "transform": "identity",
            "source-id": by_name[k].id + 1,
            "field-id": 1000 + i,
        })
    return {"spec-id": 0, "fields": fields}


_DATA_FILE_SCHEMA = {
    "type": "record", "name": "manifest_entry", "fields": [
        {"name": "status", "type": "int", "field-id": 0},
        {"name": "snapshot_id", "type": ["null", "long"],
         "field-id": 1, "default": None},
        {"name": "sequence_number", "type": ["null", "long"],
         "field-id": 3, "default": None},
        {"name": "file_sequence_number", "type": ["null", "long"],
         "field-id": 4, "default": None},
        {"name": "data_file", "field-id": 2, "type": {
            "type": "record", "name": "r2", "fields": [
                {"name": "content", "type": "int", "field-id": 134},
                {"name": "file_path", "type": "string", "field-id": 100},
                {"name": "file_format", "type": "string",
                 "field-id": 101},
                {"name": "partition", "field-id": 102, "type": {
                    "type": "record", "name": "r102", "fields": []}},
                {"name": "record_count", "type": "long", "field-id": 103},
                {"name": "file_size_in_bytes", "type": "long",
                 "field-id": 104},
            ]}},
    ]}

_MANIFEST_FILE_SCHEMA = {
    "type": "record", "name": "manifest_file", "fields": [
        {"name": "manifest_path", "type": "string", "field-id": 500},
        {"name": "manifest_length", "type": "long", "field-id": 501},
        {"name": "partition_spec_id", "type": "int", "field-id": 502},
        {"name": "content", "type": "int", "field-id": 517},
        {"name": "sequence_number", "type": "long", "field-id": 515},
        {"name": "min_sequence_number", "type": "long", "field-id": 516},
        {"name": "added_snapshot_id", "type": "long", "field-id": 503},
        {"name": "added_files_count", "type": "int", "field-id": 504},
        {"name": "existing_files_count", "type": "int", "field-id": 505},
        {"name": "deleted_files_count", "type": "int", "field-id": 506},
        {"name": "added_rows_count", "type": "long", "field-id": 512},
        {"name": "existing_rows_count", "type": "long", "field-id": 513},
        {"name": "deleted_rows_count", "type": "long", "field-id": 514},
    ]}


def _partition_entry_schema(schema) -> Tuple[dict, List[str]]:
    """Manifest entry schema whose data_file.partition record mirrors the
    table's identity partition fields."""
    import copy

    entry = copy.deepcopy(_DATA_FILE_SCHEMA)
    by_name = {f.name: f for f in schema.fields}
    part_fields = []
    type_map = {"int": "int", "long": "long", "string": "string",
                "boolean": "boolean", "double": "double", "float": "float",
                "date": "int"}
    for k in schema.partition_keys:
        it = _iceberg_type(by_name[k].type)
        part_fields.append({
            "name": k,
            "type": ["null", type_map.get(it, "string")],
            "field-id": by_name[k].id + 1,
            "default": None,
        })
    entry["fields"][4]["type"]["fields"][3]["type"]["fields"] = \
        part_fields
    return entry, list(schema.partition_keys)


def sync_iceberg(table, committer=None) -> Optional[str]:
    """Export the table's current snapshot as Iceberg v2 metadata.
    Returns the metadata file path (or None when there is no snapshot).

    `committer` (optional, iceberg/rest.py IcebergRestCommitter) also
    publishes the export to an Iceberg REST catalog after the file
    metadata is written, passing the PREVIOUS export's snapshot id as
    the CAS base — reference IcebergCommitCallback +
    IcebergRestMetadataCommitter.commitMetadata(newPath, basePath)."""
    snapshot = table.snapshot_manager.latest_snapshot()
    if snapshot is None:
        return None
    scan = table.new_scan()
    entries = scan.read_entries(snapshot)
    schema = table.schema
    meta_dir = f"{table.path}/metadata"
    fio = table.file_io

    entry_schema, part_keys = _partition_entry_schema(schema)
    # primary-key tables expose the READ-OPTIMIZED view: only top-level
    # (fully compacted, non-overlapping, deletes folded) files are
    # consumable by an engine that cannot run the merge — matching the
    # reference's Iceberg compat contract for pk tables ("visible after
    # full compaction", docs/iceberg + IcebergCommitCallback)
    max_level = None
    if table.primary_keys:
        max_level = table.options.max_level
    records = []
    total_rows = 0
    for e in entries:
        if e.bucket == -2:
            continue
        if max_level is not None and e.file.level != max_level:
            continue
        partition = scan._partition_codec.from_bytes(e.partition)
        path = e.file.external_path or scan.path_factory.data_file_path(
            partition, e.bucket, e.file.file_name)
        fmt = e.file.file_name.rsplit(".", 1)[-1].upper()
        records.append({
            "status": 1,                     # ADDED
            "snapshot_id": snapshot.id,
            "sequence_number": snapshot.id,
            "file_sequence_number": snapshot.id,
            "data_file": {
                "content": 0,               # DATA
                "file_path": path,
                "file_format": fmt,
                "partition": dict(zip(part_keys, partition)),
                "record_count": e.file.row_count,
                "file_size_in_bytes": e.file.file_size,
            }})
        total_rows += e.file.row_count

    manifest_name = f"manifest-{uuid.uuid4()}.avro"
    manifest_path = f"{meta_dir}/{manifest_name}"
    manifest_bytes = avro_fmt.write_container(entry_schema, records,
                                              codec="null")
    fio.write_bytes(manifest_path, manifest_bytes, overwrite=False)

    list_name = f"snap-{snapshot.id}-{uuid.uuid4()}.avro"
    list_path = f"{meta_dir}/{list_name}"
    fio.write_bytes(list_path, avro_fmt.write_container(
        _MANIFEST_FILE_SCHEMA, [{
            "manifest_path": manifest_path,
            "manifest_length": len(manifest_bytes),
            "partition_spec_id": 0,
            "content": 0,
            "sequence_number": snapshot.id,
            "min_sequence_number": snapshot.id,
            "added_snapshot_id": snapshot.id,
            "added_files_count": len(records),
            "existing_files_count": 0,
            "deleted_files_count": 0,
            "added_rows_count": total_rows,
            "existing_rows_count": 0,
            "deleted_rows_count": 0,
        }], codec="null"), overwrite=False)

    # next metadata version; remember the previous export's snapshot as
    # the REST committer's CAS base
    version = 1
    base_snapshot_id = None
    hint_path = f"{meta_dir}/version-hint.text"
    if fio.exists(hint_path):
        try:
            version = int(fio.read_utf8(hint_path)) + 1
            prev = json.loads(fio.read_utf8(
                f"{meta_dir}/v{version - 1}.metadata.json"))
            base_snapshot_id = prev.get("current-snapshot-id")
        except (ValueError, OSError, FileNotFoundError):
            pass
    metadata = {
        "format-version": 2,
        "table-uuid": str(uuid.uuid5(uuid.NAMESPACE_URL, table.path)),
        "location": table.path,
        "last-sequence-number": snapshot.id,
        "last-updated-ms": snapshot.time_millis,
        "last-column-id": max((f.id + 1 for f in schema.fields),
                              default=0),
        "current-schema-id": schema.id,
        "schemas": [_iceberg_schema(schema)],
        "default-spec-id": 0,
        "partition-specs": [_partition_spec(schema)],
        "last-partition-id": 1000 + max(0, len(schema.partition_keys) - 1),
        "default-sort-order-id": 0,
        "sort-orders": [{"order-id": 0, "fields": []}],
        "properties": {"paimon.snapshot-id": str(snapshot.id)},
        "current-snapshot-id": snapshot.id,
        "snapshots": [{
            "snapshot-id": snapshot.id,
            "sequence-number": snapshot.id,
            "timestamp-ms": snapshot.time_millis,
            "manifest-list": list_path,
            "summary": {"operation": "overwrite"},
            "schema-id": schema.id,
        }],
        "statistics": [],
        "snapshot-log": [],
        "metadata-log": [],
    }
    meta_path = f"{meta_dir}/v{version}.metadata.json"
    fio.write_bytes(meta_path, json.dumps(metadata, indent=2).encode(),
                    overwrite=True)
    fio.write_bytes(hint_path, str(version).encode(), overwrite=True)
    if committer is not None:
        committer.commit_metadata(metadata, base_snapshot_id)
    return meta_path
