"""Independent Iceberg v2 reader.

Consumes a table's `metadata/` directory purely through the Iceberg
spec (table metadata JSON -> manifest-list avro -> manifest avro ->
data files); shares nothing with the export path in metadata.py except
the generic avro OCF codec and Arrow file readers.  Its role is the
external-consumer check the reference gets from Spark/Trino reading
its Iceberg compat output (no pyiceberg in this environment): if this
reader round-trips the data, the export is structurally consumable.

reference: paimon-core/.../iceberg/ (IcebergCommitCallback writes,
external engines read) + the Iceberg table-spec v2.
"""

import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import pyarrow as pa

from paimon_tpu.format import avro as avro_fmt
from paimon_tpu.fs.fileio import FileIO, LocalFileIO

_REQUIRED_V2_FIELDS = [
    "format-version", "table-uuid", "location", "last-sequence-number",
    "last-updated-ms", "last-column-id", "current-schema-id", "schemas",
    "default-spec-id", "partition-specs", "current-snapshot-id",
    "snapshots",
]


@dataclass
class IcebergDataFile:
    file_path: str
    file_format: str
    record_count: int
    file_size_in_bytes: int
    partition: Dict[str, Any] = field(default_factory=dict)


class IcebergTable:
    """A read-only view over Iceberg v2 metadata."""

    def __init__(self, metadata: dict, file_io: FileIO):
        self.metadata = metadata
        self.file_io = file_io
        self._validate()

    # -- loading ------------------------------------------------------------
    @staticmethod
    def load(location: str, file_io: Optional[FileIO] = None,
             metadata_file: Optional[str] = None) -> "IcebergTable":
        """Load from a table location (via metadata/version-hint.text)
        or an explicit vN.metadata.json path."""
        fio = file_io or LocalFileIO()
        if metadata_file is None:
            hint = f"{location.rstrip('/')}/metadata/version-hint.text"
            version = int(fio.read_utf8(hint).strip())
            metadata_file = (f"{location.rstrip('/')}/metadata/"
                             f"v{version}.metadata.json")
        metadata = json.loads(fio.read_utf8(metadata_file))
        return IcebergTable(metadata, fio)

    def _validate(self):
        missing = [k for k in _REQUIRED_V2_FIELDS
                   if k not in self.metadata]
        if missing:
            raise ValueError(f"not Iceberg v2 metadata; missing "
                             f"fields: {missing}")
        if self.metadata["format-version"] != 2:
            raise ValueError("only format-version 2 is supported")
        ids = {s["schema-id"] for s in self.metadata["schemas"]}
        if self.metadata["current-schema-id"] not in ids:
            raise ValueError("current-schema-id not in schemas")

    # -- metadata accessors --------------------------------------------------
    @property
    def schema(self) -> dict:
        sid = self.metadata["current-schema-id"]
        return next(s for s in self.metadata["schemas"]
                    if s["schema-id"] == sid)

    @property
    def column_names(self) -> List[str]:
        return [f["name"] for f in self.schema["fields"]]

    def current_snapshot(self) -> Optional[dict]:
        sid = self.metadata.get("current-snapshot-id")
        if sid in (None, -1):
            return None
        return self._snapshot(sid)

    def _snapshot(self, sid: int) -> dict:
        snap = next((s for s in self.metadata["snapshots"]
                     if s["snapshot-id"] == sid), None)
        if snap is None:
            raise ValueError(f"snapshot {sid} not in the metadata's "
                             f"snapshots list")
        return snap

    # -- planning ------------------------------------------------------------
    def plan_files(self, snapshot_id: Optional[int] = None
                   ) -> List[IcebergDataFile]:
        """manifest-list -> manifests -> live data files."""
        snap = (self.current_snapshot() if snapshot_id is None else
                self._snapshot(snapshot_id))
        if snap is None:
            return []
        out: List[IcebergDataFile] = []
        _, mlist = avro_fmt.read_container(
            self.file_io.read_bytes(snap["manifest-list"]))
        for mf in mlist:
            _, entries = avro_fmt.read_container(
                self.file_io.read_bytes(mf["manifest_path"]))
            for e in entries:
                if e["status"] == 2:             # DELETED
                    continue
                df = e["data_file"]
                if df.get("content", 0) != 0:    # only DATA files
                    continue
                out.append(IcebergDataFile(
                    file_path=df["file_path"],
                    file_format=str(df["file_format"]).lower(),
                    record_count=df["record_count"],
                    file_size_in_bytes=df["file_size_in_bytes"],
                    partition=dict(df.get("partition") or {}),
                ))
        return out

    # -- reading -------------------------------------------------------------
    def to_arrow(self, projection: Optional[List[str]] = None
                 ) -> pa.Table:
        """Read the current snapshot's rows (columns of the Iceberg
        schema, in schema order)."""
        cols = projection or self.column_names
        files = self.plan_files()
        parts: List[pa.Table] = []
        for f in files:
            t = self._read_file(f)
            missing = [c for c in cols if c not in t.column_names]
            if missing:
                raise ValueError(
                    f"data file {f.file_path} lacks columns {missing}")
            parts.append(t.select(cols))
        if not parts:
            return pa.table({c: pa.array([]) for c in cols})
        out = pa.concat_tables(parts, promote_options="permissive")
        total = sum(f.record_count for f in files)
        if out.num_rows != total:
            raise ValueError(
                f"manifest record_count {total} != rows read "
                f"{out.num_rows}")
        return out

    def _read_file(self, f: IcebergDataFile) -> pa.Table:
        data = self.file_io.read_bytes(f.file_path)
        buf = pa.BufferReader(data)
        if f.file_format == "parquet":
            import pyarrow.parquet as pq
            return pq.read_table(buf)
        if f.file_format == "orc":
            import pyarrow.orc as orc
            return orc.ORCFile(buf).read()
        if f.file_format == "avro":
            _, recs = avro_fmt.read_container(data)
            return pa.Table.from_pylist(recs)
        raise ValueError(f"unsupported data format {f.file_format}")
