"""Drift rules: artifacts that must track the source.

Previously grep/subprocess tests (tests/test_metrics.py's
metric-producer grep, tests/test_docs.py's generate_options --check);
now engine rules with structured findings, running over the
already-parsed sources — no re-walk, no subprocess.

Both rules are repo-shaped (they need paimon_tpu.metrics /
docs/generate_options.py next to the package) and no-op on fixture
packages that lack those anchors.
"""

from __future__ import annotations

import os
import re
from typing import List

from paimon_tpu.analysis.engine import Finding, rule
from paimon_tpu.analysis.model import ProgramModel


@rule("metric-drift",
      "exported metric-name constant with no producer")
def check_metric_drift(model: ProgramModel) -> List[Finding]:
    """Every exported ALL_CAPS metric-name constant in metrics.py must
    be referenced by name somewhere else in the package — an orphaned
    constant means a dashboard/test greps for a metric nothing
    emits."""
    metrics_mod = model.modules.get("metrics.py")
    if metrics_mod is None or model.package_name != "paimon_tpu":
        return []
    import paimon_tpu.metrics as M
    consts = [n for n in M.__all__ if n.isupper()]
    blob = "\n".join(m.source for m in model.modules.values()
                     if m is not metrics_mod)
    out = []
    for name in consts:
        if name in blob:
            continue
        m = re.search(rf"^{name}\b", metrics_mod.source, re.MULTILINE)
        line = metrics_mod.source[:m.start()].count("\n") + 1 if m \
            else 1
        out.append(Finding(
            "metric-drift", metrics_mod.rel, line,
            f"metric-name constant {name} has no producer in "
            f"{model.package_name}/ — emit it or delete it"))
    return out


_OBS_CONST_MODULES = ("obs/trace.py", "obs/flight.py")
_OBS_CONST_RE = re.compile(
    r"^((?:STAGE|EV)_[A-Z0-9_]+)\s*=\s*['\"]", re.MULTILINE)


@rule("obs-drift",
      "exported stage/flight-event constant with no producer")
def check_obs_drift(model: ProgramModel) -> List[Finding]:
    """Every ``STAGE_*`` span-stage constant (obs/trace.py) and
    ``EV_*`` flight-event constant (obs/flight.py) must be USED
    somewhere in the package beyond its defining assignment — an
    orphaned name means the merged fleet trace / flight ring
    documents an event nothing records.  Same-module uses count:
    the producer for ``STAGE_SERVE_REQUEST`` is trace.py's own
    header-adoption path.  (SLO metric-name constants live in
    metrics.py's ``__all__`` and are covered by `metric-drift`.)

    Unlike the other drift rules this one is pure source analysis —
    no imports, no repo anchors — so it runs on fixture packages
    too."""
    out: List[Finding] = []
    all_mods = list(model.modules.values())
    for rel in _OBS_CONST_MODULES:
        mod = model.modules.get(rel)
        if mod is None:
            continue
        # the defining module minus the definition lines themselves
        residue = _OBS_CONST_RE.sub("", mod.source)
        others = "\n".join(m.source for m in all_mods if m is not mod)
        for m in _OBS_CONST_RE.finditer(mod.source):
            name = m.group(1)
            if re.search(rf"\b{name}\b", others) or \
                    re.search(rf"\b{name}\b", residue):
                continue
            line = mod.source[:m.start()].count("\n") + 1
            out.append(Finding(
                "obs-drift", mod.rel, line,
                f"observability constant {name} has no producer in "
                f"{model.package_name}/ — record it or delete it"))
    return out


@rule("options-drift",
      "docs/options.md or CoreOptions out of sync")
def check_options_drift(model: ProgramModel) -> List[Finding]:
    """docs/options.md must regenerate byte-identically from
    paimon_tpu/options.py, and no option key may be declared twice
    (duplicates with the same attribute name collapse in the class
    dict — the second silently wins)."""
    gen_path = os.path.join(model.repo_root, "docs",
                            "generate_options.py")
    if not os.path.exists(gen_path) or \
            model.package_name != "paimon_tpu":
        return []
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "paimon_docs_generate_options", gen_path)
    gen = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(gen)

    out: List[Finding] = []
    options_mod = model.modules.get("options.py")
    options_rel = options_mod.rel if options_mod \
        else "paimon_tpu/options.py"
    import inspect

    from paimon_tpu.options import CoreOptions
    dups = gen.duplicate_option_keys(inspect.getsource(CoreOptions))
    for key in dups:
        line = 1
        if options_mod:
            m = re.search(re.escape(key), options_mod.source)
            if m:
                line = options_mod.source[:m.start()].count("\n") + 1
        out.append(Finding(
            "options-drift", options_rel, line,
            f"option key '{key}' declared more than once in "
            f"CoreOptions — the second declaration silently wins"))
    if dups:
        return out      # render() refuses to run on duplicates
    current_path = os.path.join(model.repo_root, "docs", "options.md")
    current = open(current_path).read() \
        if os.path.exists(current_path) else ""
    if current != gen.render():
        out.append(Finding(
            "options-drift", "docs/options.md", 1,
            "docs/options.md is out of date with "
            "paimon_tpu/options.py — run "
            "`python docs/generate_options.py`"))
    return out
