"""deadline-wait: every blocking wait must be bounded.

PR 9 made end-to-end deadlines the request plane's defense against
slowness: every blocking wait caps itself to the remaining budget
(`utils/deadline.py`, `utils/backoff.py`).  That contract only holds
if NO unbounded wait exists outside the sanctioned forms — one
`Event.wait()` with no timeout and a timed-out request (or a whole
worker) is parked forever behind a builder that died.

This rule flags every unbounded blocking wait (per rules/blocking.py:
zero-arg `.wait()`, module-level `cf.wait(fs)` without `timeout=`,
zero-arg `.result()`, blocking queue `.get()` without timeout,
zero-arg `.join()`) outside the whitelisted wait-owning modules:

* utils/deadline.py / utils/backoff.py — the bounded forms themselves;
* parallel/executors.py — pool plumbing whose joins are
  shutdown-owned.

What "bounded" means here is syntactic (a timeout argument is
present); whether the timeout DERIVES from the deadline is the wait
loop's job — the idiom is `wait(0.5)` in a loop that calls
`check_deadline()` (see parallel/write_pipeline.py) or
`result(timeout=dl.remaining_s())` (see parallel/scan_pipeline.py).

A worker's IDLE dispatch wait (a daemon thread parked on its own inbox
with nothing to do and nothing waiting on it) is the legitimate
exemption shape — suppress at the site with the reason.  Lock
acquisitions are deliberately out of scope (the lock-order rule owns
lock risk; flagging every `with lock:` would drown the signal).
"""

from __future__ import annotations

from typing import List

from paimon_tpu.analysis.engine import Finding, rule
from paimon_tpu.analysis.model import ProgramModel
from paimon_tpu.analysis.rules.blocking import iter_blocking_sites

_WHITELIST = frozenset({
    "utils/deadline.py", "utils/backoff.py", "parallel/executors.py",
})

_FIX = {
    "wait": "pass a timeout and loop with check_deadline(), or use "
            "utils.backoff.wait_for()",
    "future-result": "use .result(timeout=...) — derive it from "
                     "current_deadline().remaining_s() when a request "
                     "is in scope",
    "queue-get": "use .get(timeout=...) in a loop that calls "
                 "check_deadline()",
    "join": "pass a timeout and handle the still-alive case",
}


@rule("deadline-wait",
      "unbounded blocking wait outside the deadline-aware forms")
def check_deadline_wait(model: ProgramModel) -> List[Finding]:
    out: List[Finding] = []
    for fn in model.functions.values():
        mod = fn.module
        if mod.pkg_rel in _WHITELIST:
            continue
        for site in iter_blocking_sites(model, fn):
            if site.bounded or site.kind in ("lock", "sleep",
                                             "file-io"):
                continue
            out.append(Finding(
                "deadline-wait", mod.rel, site.line,
                f"unbounded {site.kind} ({site.detail}) in "
                f"{fn.qname} — a spent request deadline cannot "
                f"escape this wait: {_FIX.get(site.kind, 'bound it')}"))
    return out
