"""The seven migrated tier-1 hygiene lints.

These started life as ad-hoc AST walks in tests/test_lint_swallow.py,
each re-parsing every file; they now run over the shared program model
(one parse per file per run).  Semantics are unchanged — only the
exemption mechanism moved: the reviewed allowlists and the ad-hoc
`# host-ok:` marker are now uniform `# lint-ok: <rule> <reason>`
markers at the exempted site, so adding an exemption is a reviewed
diff on the line it exempts and a stale exemption is itself a finding.
"""

from __future__ import annotations

import ast
from typing import List

from paimon_tpu.analysis.engine import Finding, rule
from paimon_tpu.analysis.model import ProgramModel, except_names

_BROAD = {"Exception", "BaseException", "<bare>"}


def _broad_names(type_node):
    return [n for n in except_names(type_node) if n in _BROAD]


@rule("swallow",
      "silent broad-exception swallowing")
def check_swallow(model: ProgramModel) -> List[Finding]:
    """An `except Exception: pass` (or bare except / continue body)
    hides every error class — including the transient faults the
    maintenance plane must retry or propagate (parallel/fault.py).
    Narrow typed catches are out of scope: they are deliberate, local
    decisions.  Genuine best-effort paths carry a
    `# lint-ok: swallow <reason>` on the except line."""
    out = []
    for mod in model.modules.values():
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if len(node.body) != 1 or not isinstance(
                    node.body[0], (ast.Pass, ast.Continue)):
                continue
            if not _broad_names(node.type):
                continue
            fn = model.enclosing_function(mod, node.lineno)
            where = fn.qname.split("::")[-1] if fn else "<module>"
            out.append(Finding(
                "swallow", mod.rel, node.lineno,
                f"silent broad except in {where}: handle the error, "
                f"propagate it, or mark the reviewed best-effort path "
                f"with `# lint-ok: swallow <reason>`"))
    return out


@rule("threads",
      "bare threading.Thread outside parallel/")
def check_threads(model: ProgramModel) -> List[Finding]:
    """All threads and pools go through parallel/executors.py
    (spawn_thread / new_thread_pool) so every worker carries an
    attributable name and the no-leaked-thread tier-1 tests can key
    on it."""
    out = []
    for mod in model.modules.values():
        if mod.pkg_rel.startswith("parallel/"):
            continue               # the one reviewed home of threads
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            name = fn.attr if isinstance(fn, ast.Attribute) else \
                fn.id if isinstance(fn, ast.Name) else None
            if name == "Thread":
                out.append(Finding(
                    "threads", mod.rel, node.lineno,
                    "bare threading.Thread( outside parallel/ — use "
                    "parallel/executors.py spawn_thread/"
                    "new_thread_pool so the thread is named and "
                    "reviewable"))
    return out


@rule("sleeps",
      "bare time.sleep outside utils/backoff.py")
def check_sleeps(model: ProgramModel) -> List[Finding]:
    """Every wait in library code must be deadline-aware and
    injectable — `Backoff.pause()` for retry ladders, `wait_for()`
    for one-shot waits.  A bare sleep is an un-interruptible stall a
    timed-out request cannot escape.  Injectable sleeps stored as
    attributes (`self._sleep(...)`) are fine — only direct
    `time.sleep` / `from time import sleep` CALLS are flagged."""
    out = []
    for mod in model.modules.values():
        if mod.pkg_rel == "utils/backoff.py":
            continue          # the one reviewed home of real sleeps
        time_sleep_names = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ImportFrom) and \
                    node.module == "time":
                for alias in node.names:
                    if alias.name == "sleep":
                        time_sleep_names.add(alias.asname or alias.name)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            hit = (isinstance(fn, ast.Attribute) and
                   fn.attr == "sleep" and
                   isinstance(fn.value, ast.Name) and
                   fn.value.id in ("time", "_time")) or \
                  (isinstance(fn, ast.Name) and
                   fn.id in time_sleep_names)
            if hit:
                out.append(Finding(
                    "sleeps", mod.rel, node.lineno,
                    "bare time.sleep( outside utils/backoff.py — use "
                    "Backoff.pause() for retry ladders or "
                    "utils.backoff.wait_for() for one-shot waits"))
    return out


_NET_MODULES = {"socket", "selectors"}


@rule("sockets",
      "raw socket/selectors import outside service/async_server.py")
def check_sockets(model: ProgramModel) -> List[Finding]:
    """The event-loop request engine is the ONE reviewed home of
    non-blocking socket code: its loop owns every fd, bounds
    connections and pipelining, measures loop lag and shuts down
    cleanly.  HTTP clients use http.client, servers use
    service/async_server.AsyncHttpServer."""
    out = []
    for mod in model.modules.values():
        if mod.pkg_rel == "service/async_server.py":
            continue          # the one reviewed home of raw sockets
        for node in ast.walk(mod.tree):
            hit = False
            if isinstance(node, ast.Import):
                hit = any(a.name.split(".")[0] in _NET_MODULES
                          for a in node.names)
            elif isinstance(node, ast.ImportFrom):
                hit = bool(node.module) and \
                    node.module.split(".")[0] in _NET_MODULES
            if hit:
                out.append(Finding(
                    "sockets", mod.rel, node.lineno,
                    "raw socket/selectors import outside "
                    "service/async_server.py — ad-hoc network loops "
                    "are banned: serve through AsyncHttpServer and "
                    "talk HTTP through http.client"))
    return out


_COLLECTIVES = {"sync_global_devices", "broadcast_one_to_all",
                "process_allgather"}


@rule("collectives",
      "raw multihost collectives outside parallel/multihost.py")
def check_collectives(model: ProgramModel) -> List[Finding]:
    """multihost.py's barrier() / broadcast_value() /
    allgather_bytes() are the ONE reviewed wrap: deadline-bounded,
    barrier_wait_ms-instrumented, degrading to single-process no-ops.
    A raw jax.experimental.multihost_utils call elsewhere gets none of
    that — and a hung collective with a dead peer is exactly the
    failure the lease-based maintenance plane exists to tolerate."""
    out = []
    for mod in model.modules.values():
        if mod.pkg_rel == "parallel/multihost.py":
            continue        # the one reviewed home of collectives
        bound = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ImportFrom) and node.module \
                    and node.module.endswith("multihost_utils"):
                for alias in node.names:
                    if alias.name in _COLLECTIVES:
                        bound.add(alias.asname or alias.name)
                        out.append(Finding(
                            "collectives", mod.rel, node.lineno,
                            f"raw {alias.name} import outside "
                            f"parallel/multihost.py — use the "
                            f"deadline-bounded multihost wrappers"))
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            hit = (isinstance(fn, ast.Attribute) and
                   fn.attr in _COLLECTIVES) or \
                  (isinstance(fn, ast.Name) and fn.id in bound)
            if hit:
                out.append(Finding(
                    "collectives", mod.rel, node.lineno,
                    "raw multihost collective call outside "
                    "parallel/multihost.py — use multihost.barrier() "
                    "/ broadcast_value() / allgather_bytes()"))
    return out


@rule("distributed-init",
      "jax.distributed.initialize outside parallel/multihost.py")
def check_distributed_init(model: ProgramModel) -> List[Finding]:
    """multihost.initialize is the ONE reviewed bring-up: it opts the
    CPU backend into Gloo cross-process collectives BEFORE the backend
    initializes; a direct call elsewhere bypasses that and resurrects
    the 'Multiprocess computations aren't implemented' failure
    mode."""
    out = []
    for mod in model.modules.values():
        if mod.pkg_rel == "parallel/multihost.py":
            continue        # the one reviewed bring-up path
        init_names = set()
        dist_aliases = set()
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.ImportFrom):
                continue
            if node.module == "jax.distributed":
                for alias in node.names:
                    if alias.name == "initialize":
                        init_names.add(alias.asname or alias.name)
                        out.append(Finding(
                            "distributed-init", mod.rel, node.lineno,
                            "direct import of "
                            "jax.distributed.initialize outside "
                            "parallel/multihost.py — use "
                            "multihost.initialize()"))
            elif node.module == "jax":
                for alias in node.names:
                    if alias.name == "distributed":
                        dist_aliases.add(alias.asname or alias.name)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            hit = (isinstance(fn, ast.Attribute) and
                   fn.attr == "initialize" and
                   ((isinstance(fn.value, ast.Attribute) and
                     fn.value.attr == "distributed") or
                    (isinstance(fn.value, ast.Name) and
                     fn.value.id in dist_aliases))) or \
                  (isinstance(fn, ast.Name) and fn.id in init_names)
            if hit:
                out.append(Finding(
                    "distributed-init", mod.rel, node.lineno,
                    "direct jax.distributed.initialize( outside "
                    "parallel/multihost.py — use "
                    "multihost.initialize(), which opts the CPU "
                    "backend into Gloo collectives before the "
                    "backend comes up"))
    return out


# device-kernel modules whose bodies must stay traceable end to end: a
# host materialization here silently reintroduces the round-trip the
# device decode plane exists to remove (the host boundary lives in
# format/rawpage.py, which orchestrates these kernels)
_KERNEL_MODULES = ("ops/decode.py", "ops/pallas_kernels.py")


@rule("host-materialization",
      "host materialization inside a device-kernel module")
def check_host_materialization(model: ProgramModel) -> List[Finding]:
    """`np.asarray(...)` / `.tolist()` / `jax.device_get(...)` inside
    ops/decode.py or ops/pallas_kernels.py — keep the kernel traceable
    and materialize at the format/rawpage.py boundary instead, or mark
    a reviewed exception with
    `# lint-ok: host-materialization <reason>`."""
    out = []
    for pkg_rel in _KERNEL_MODULES:
        mod = model.modules.get(pkg_rel)
        if mod is None:
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if not isinstance(fn, ast.Attribute):
                continue
            hit = (fn.attr == "asarray"
                   and isinstance(fn.value, ast.Name)
                   and fn.value.id in ("np", "numpy")) \
                or fn.attr == "tolist" \
                or (fn.attr == "device_get"
                    and isinstance(fn.value, ast.Name)
                    and fn.value.id == "jax")
            if hit:
                out.append(Finding(
                    "host-materialization", mod.rel, node.lineno,
                    "host materialization (np.asarray / .tolist() / "
                    "jax.device_get) inside a device-kernel module — "
                    "materialize at the format/rawpage.py boundary "
                    "instead"))
    return out
