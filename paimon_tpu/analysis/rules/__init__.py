"""Rule catalog: importing this package registers every rule with the
engine.  Grouped by family:

* hygiene  — the seven migrated tier-1 AST lints (swallow, threads,
  sleeps, sockets, collectives, distributed-init,
  host-materialization)
* drift    — metric-name and options-doc drift (previously grep tests)
* locks    — lock-order: inter-procedural lock-acquisition cycles
* eventloop — loop-blocking: blocking primitive reachable from the
  event-loop thread
* deadline — deadline-wait: unbounded blocking waits outside the
  sanctioned bounded forms
* fault    — fault-taxonomy: transient store errors handled outside
  parallel/fault.py's ladder
* ownership — ownership-history: ownership-stamp properties parsed
  outside parallel/distributed.py's stamp/history API
"""

from paimon_tpu.analysis.rules import (  # noqa: F401
    deadline, drift, eventloop, fault, hygiene, locks, ownership,
)
