"""fault-taxonomy: transient store errors route through ONE ladder.

parallel/fault.py is the single definition of "worth retrying":
`is_transient_error` excludes decode corruption (deterministic bad
bytes) and spent deadlines (the caller is gone), and
`BucketRetryPolicy.retry_call` is the ladder with capped jittered
backoff and traced attempts.  The moment a module hand-rolls its own
`except TransientStoreError: <loop again>` it forks that taxonomy:
the hand-rolled copy won't exclude DeadlineExceededError, won't
back off, won't trace, and silently diverges the next time the
taxonomy learns a new error class.

Two shapes are flagged:

* naming a transient STORE error class (`TransientStoreError`,
  `CircuitOpenError`) in an `except` outside the whitelisted fault
  plane (parallel/fault.py, fs/object_store.py, fs/resilience.py) —
  storage-transient handling belongs behind the ladder, not at call
  sites;
* a hand-rolled transient RETRY: a RETRY-SHAPED loop (`while ...`, or
  `for` over an attempt counter — `range(...)` / a constant tuple)
  whose body is a `try` whose handler names a transient class or
  `OSError`/`ConnectionError` and flows back to the next attempt (a
  `continue`, or falling off the handler without return/raise/break)
  without consulting the taxonomy (`is_transient_error` /
  `retry_call`) or a `Backoff` — a retry loop the ladder cannot see.

A `for f in files: ... except OSError: continue` SKIP loop is
deliberately NOT a finding: skipping a bad item while iterating a
collection is item-level fault isolation (fsck walks, cache eviction
sweeps), a different contract from re-attempting the same operation.

A deliberate, narrowly-scoped local recovery (rebuild-once of an
evicted local file, a stale keep-alive reconnect) is the legitimate
exemption shape — suppress at the `except` with the reason.
"""

from __future__ import annotations

import ast
from typing import List

from paimon_tpu.analysis.engine import Finding, rule
from paimon_tpu.analysis.model import (
    ProgramModel, except_names, iter_function_nodes,
)

_TRANSIENT = frozenset({"TransientStoreError", "CircuitOpenError"})
_RETRYISH = _TRANSIENT | frozenset({"OSError", "ConnectionError",
                                    "InjectedIOError"})
_WHITELIST = frozenset({
    "parallel/fault.py", "fs/object_store.py", "fs/resilience.py",
})
_TAXONOMY_CALLS = frozenset({"is_transient_error", "retry_call",
                             "Backoff", "pause"})


def _handler_rearms_loop(handler: ast.ExceptHandler) -> bool:
    """True when control can flow from this handler back into another
    loop iteration: an explicit `continue`, or the handler body
    falling off its end (no return/raise/break on the trailing
    statement)."""
    for node in ast.walk(handler):
        if isinstance(node, ast.Continue):
            return True
    last = handler.body[-1]
    return not isinstance(last, (ast.Return, ast.Raise, ast.Break))


def _consults_taxonomy(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Call):
            fn = node.func
            name = fn.attr if isinstance(fn, ast.Attribute) else \
                fn.id if isinstance(fn, ast.Name) else None
            if name in _TAXONOMY_CALLS:
                return True
    return False


def _retry_shaped(loop) -> bool:
    """A loop that RE-ATTEMPTS (while ..., for over range()/constant
    tuple) rather than iterating a collection — the skip-vs-retry
    distinction the rule's second arm rests on."""
    if isinstance(loop, ast.While):
        return True
    it = loop.iter
    if isinstance(it, ast.Call) and isinstance(it.func, ast.Name) \
            and it.func.id == "range":
        return True
    return isinstance(it, (ast.Tuple, ast.List)) and \
        all(isinstance(e, ast.Constant) for e in it.elts)


@rule("fault-taxonomy",
      "transient store errors handled outside parallel/fault.py")
def check_fault_taxonomy(model: ProgramModel) -> List[Finding]:
    out: List[Finding] = []
    for fn in model.functions.values():
        mod = fn.module
        if mod.pkg_rel in _WHITELIST:
            continue
        for node in iter_function_nodes(fn.node):
            if isinstance(node, ast.ExceptHandler):
                transient = set(except_names(node.type)) & _TRANSIENT
                if transient:
                    out.append(Finding(
                        "fault-taxonomy", mod.rel, node.lineno,
                        f"except {'/'.join(sorted(transient))} in "
                        f"{fn.qname} — transient store errors are "
                        f"the fault plane's to classify: route "
                        f"through parallel/fault.py "
                        f"(is_transient_error / "
                        f"BucketRetryPolicy.retry_call) or the "
                        f"resilient store backend"))
                continue
            if not isinstance(node, (ast.For, ast.While)) or \
                    not _retry_shaped(node):
                continue
            for stmt in node.body:
                if not isinstance(stmt, ast.Try):
                    continue
                for handler in stmt.handlers:
                    names = set(except_names(handler.type))
                    if not (names & _RETRYISH):
                        continue
                    if _handler_rearms_loop(handler) and \
                            not _consults_taxonomy(handler):
                        out.append(Finding(
                            "fault-taxonomy", mod.rel,
                            handler.lineno,
                            f"hand-rolled transient retry in "
                            f"{fn.qname}: except "
                            f"{'/'.join(sorted(names & _RETRYISH))} "
                            f"re-arms the enclosing retry loop "
                            f"without consulting the taxonomy — use "
                            f"BucketRetryPolicy.retry_call (backoff, "
                            f"attempt caps, tracing) or check "
                            f"is_transient_error"))
    return out
