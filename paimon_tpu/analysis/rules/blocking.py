"""Shared blocking-primitive classifier.

One definition of "this call can park the thread", used by BOTH the
event-loop reachability rule (anything blocking is fatal on the loop
thread) and the deadline-propagation rule (blocking is fine off-loop
— but only in a BOUNDED form that a spent request budget can escape).

Classification is syntactic and conservative:

* `<x>.wait()` with no arguments — Event/Condition wait, unbounded;
  with any argument it is bounded (`bounded=True`);
* `cfmod.wait(fs)` through an imported-module alias
  (concurrent.futures) — bounded iff a `timeout=` keyword is present
  (the first positional is the future list, not a timeout);
* `<fut>.result()` with no arguments — unbounded future wait;
* `<q>.get()` / `<q>.get(True)` / `<q>.get(block=True)` with no
  timeout on a QUEUE-SHAPED receiver (last name segment `q`, `queue`,
  `jobs`, `tasks`, `work`, `inbox`) — unbounded queue wait.  The
  receiver shape filter keeps `dict.get(k)` / `ContextVar.get()` out;
* `<t>.join()` with no arguments — unbounded thread/queue join;
* `<lock>.acquire()` and `with <lock>:` — lock waits (reported only
  by the event-loop rule: flagging every lock acquisition as a
  deadline hazard would drown the signal, and lock hold times are the
  lock-order rule's domain);
* `time.sleep` / builtin `open()` — reported only by the event-loop
  rule (sleeps have their own hygiene rule; file IO off-loop is the
  storage plane's job).
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, Optional

from paimon_tpu.analysis.model import (
    LOCKLIKE_RE, FunctionInfo, ProgramModel, dotted_name,
    iter_function_nodes,
)

__all__ = ["BlockingSite", "iter_blocking_sites"]

_QUEUE_RE = re.compile(
    r"(?:^|_)(?:q|queue|jobs|tasks|work|inbox)\d*$", re.IGNORECASE)


class BlockingSite:
    """One potentially-parking call: kind in {'wait', 'future-result',
    'queue-get', 'join', 'lock', 'sleep', 'file-io'};
    `bounded` True when a timeout bounds it."""

    __slots__ = ("line", "kind", "detail", "bounded")

    def __init__(self, line: int, kind: str, detail: str,
                 bounded: bool):
        self.line = line
        self.kind = kind
        self.detail = detail
        self.bounded = bounded


def _kw(call: ast.Call, name: str) -> bool:
    return any(k.arg == name for k in call.keywords)


def _receiver(call: ast.Call) -> Optional[str]:
    if isinstance(call.func, ast.Attribute):
        return dotted_name(call.func.value)
    return None


def iter_blocking_sites(model: ProgramModel, fn: FunctionInfo) \
        -> Iterator[BlockingSite]:
    mod = fn.module
    for node in iter_function_nodes(fn.node):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                d = dotted_name(item.context_expr)
                if d and LOCKLIKE_RE.search(d.split(".")[-1]):
                    yield BlockingSite(node.lineno, "lock",
                                       f"with {d}", False)
            continue
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if isinstance(func, ast.Name):
            if func.id == "open":
                yield BlockingSite(node.lineno, "file-io", "open(",
                                   True)
            continue
        if not isinstance(func, ast.Attribute):
            continue
        attr = func.attr
        recv = _receiver(node) or "<expr>"
        tail = recv.split(".")[-1]
        if attr == "sleep" and tail in ("time", "_time"):
            yield BlockingSite(node.lineno, "sleep", "time.sleep(",
                               False)
        elif attr == "acquire" and LOCKLIKE_RE.search(tail):
            # .acquire(timeout=t) / .acquire(True, t) is bounded
            bounded = _kw(node, "timeout") or len(node.args) >= 2
            yield BlockingSite(node.lineno, "lock",
                               f"{recv}.acquire(", bounded)
        elif attr == "wait":
            base = recv.split(".")[0]
            if base in mod.imports and \
                    model._module_for(mod.imports[base]) is None:
                # module-level wait (concurrent.futures.wait): the
                # positional args are futures, only timeout= bounds it
                bounded = _kw(node, "timeout")
            else:
                bounded = bool(node.args) or _kw(node, "timeout")
            yield BlockingSite(node.lineno, "wait", f"{recv}.wait(",
                               bounded)
        elif attr == "result":
            bounded = bool(node.args) or _kw(node, "timeout")
            yield BlockingSite(node.lineno, "future-result",
                               f"{recv}.result(", bounded)
        elif attr == "get" and _QUEUE_RE.search(tail):
            blocking = True
            if node.args and isinstance(node.args[0], ast.Constant) \
                    and node.args[0].value is False:
                blocking = False
            for k in node.keywords:
                if k.arg == "block" and \
                        isinstance(k.value, ast.Constant) and \
                        k.value.value is False:
                    blocking = False
            bounded = (not blocking) or _kw(node, "timeout") \
                or len(node.args) >= 2
            yield BlockingSite(node.lineno, "queue-get",
                               f"{recv}.get(", bounded)
        elif attr == "join" and not node.args and not node.keywords:
            yield BlockingSite(node.lineno, "join", f"{recv}.join()",
                               False)
