"""loop-blocking: blocking call reachable from the event-loop thread.

The serving plane's whole design rests on ONE invariant: the selectors
loop thread (service/async_server.py) never parks.  A blocking call on
the loop thread stalls every connection at once — reads, writes and
accepts all stop, and the loop-lag canary fires only AFTER the damage.
The dangerous regressions are not in the loop functions themselves
(those get reviewed hard) but two or three calls away: a helper grows
a lock, a metrics path grows a queue, and nothing in a per-function
lint notices.

This rule finds the loop ROOT (the function handed to `spawn_thread`
with a thread name containing "loop" inside service/async_server.py),
computes its call-graph closure, and flags every blocking primitive
(per rules/blocking.py — lock acquires, waits, queue gets, future
results, sleeps, file IO) in any reachable function, with the call
path in the message.

Known-held exemption: the loop does take `_done_lock`-style MICRO
critical sections shared with workers (append/popleft under lock).
Those are deliberate bounded waits — suppress at the site with
`# lint-ok: loop-blocking <reason>`; the marker is the review.

If the server module exists but no loop root can be found, that is
itself a finding — a refactor that renames the loop thread must not
silently disable the rule.
"""

from __future__ import annotations

import ast
from typing import List

from paimon_tpu.analysis.engine import Finding, rule
from paimon_tpu.analysis.model import (
    FunctionInfo, ProgramModel, iter_function_nodes,
)
from paimon_tpu.analysis.rules.blocking import iter_blocking_sites

_SERVER_MODULE = "service/async_server.py"


def _contains_loop_name(node: ast.Call) -> bool:
    for kw in node.keywords:
        if kw.arg != "name":
            continue
        for sub in ast.walk(kw.value):
            if isinstance(sub, ast.Constant) and \
                    isinstance(sub.value, str) and "loop" in sub.value:
                return True
    return False


def _loop_roots(model: ProgramModel) -> List[FunctionInfo]:
    mod = model.modules.get(_SERVER_MODULE)
    if mod is None:
        return []
    roots: List[FunctionInfo] = []
    for fn in model.functions.values():
        if fn.module is not mod:
            continue
        for node in iter_function_nodes(fn.node):
            if not (isinstance(node, ast.Call)
                    and getattr(node.func, "id",
                                getattr(node.func, "attr", None))
                    == "spawn_thread"
                    and node.args and _contains_loop_name(node)):
                continue
            target = node.args[0]
            for cand in model.resolve_call(
                    fn, ast.Call(func=target, args=[], keywords=[])):
                roots.append(cand)
    return roots


@rule("loop-blocking",
      "blocking call reachable from the event-loop thread")
def check_loop_blocking(model: ProgramModel) -> List[Finding]:
    mod = model.modules.get(_SERVER_MODULE)
    if mod is None:
        return []          # fixture package without a serving plane
    roots = _loop_roots(model)
    if not roots:
        return [Finding(
            "loop-blocking", mod.rel, 1,
            "cannot locate the event-loop root (no spawn_thread(..., "
            "name=...'loop'...) in service/async_server.py) — the "
            "loop thread was renamed or removed; update the rule's "
            "root discovery so loop-thread reachability stays "
            "checked")]
    reach = model.reachable(roots)
    out: List[Finding] = []
    for qname, (fn, _parent) in reach.items():
        for site in iter_blocking_sites(model, fn):
            # bounded waits still park the loop (a 500 ms cond.wait
            # stalls every connection for 500 ms) — flag them all
            path = model.call_path(reach, qname)
            out.append(Finding(
                "loop-blocking", fn.module.rel, site.line,
                f"{site.kind} ({site.detail}) on the event-loop "
                f"thread via {path} — the loop must never park: move "
                f"the work to the handler pool or restructure the "
                f"completion hand-off"))
    return out
