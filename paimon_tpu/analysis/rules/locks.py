"""lock-order: inter-procedural lock-acquisition cycle detector.

The classic two-thread deadlock needs no blocked system and no load:
thread 1 holds A and wants B, thread 2 holds B and wants A.  With five
concurrency planes sharing the cache tiers, the serving plane and the
write/scan pipelines, the pairs are spread across FILES — no
single-function lint can see them.

This rule builds a lock-acquisition ORDER graph:

* lock identity comes from the model's canonicalised lock ids
  (`fs/caching.py::BlockCache.lock`): `self.X` resolves to the
  base-most class that assigns X, and `Condition(self._lock)` aliases
  to the underlying lock;
* an edge A -> B means "B was acquired while A was held": directly
  (`with a: with b:`), or transitively — while A is held, a call chain
  resolved through the conservative call graph reaches a function that
  acquires B;
* a CYCLE in the graph is a potential deadlock (finding per cycle);
  re-acquiring a NON-reentrant lock while already holding it is the
  1-cycle special case (guaranteed self-deadlock when the path
  executes) and is reported at the inner acquisition site.

Scope: edges are seeded from the lock-heavy planes (fs/caching.py,
service/, parallel/, lookup/, plus anything else that holds a lock);
call chains may leave the seed set — the point is whole-program
visibility.

Caveats (documented in docs/static_analysis.md): lock identity is
per-CLASS, not per-instance — two instances of one class locked in a
parent/child chain look like a self-cycle; when such a hierarchy is
deliberate and instance-ordered, suppress at the inner site with the
reason.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from paimon_tpu.analysis.engine import Finding, rule
from paimon_tpu.analysis.model import (
    LOCKLIKE_RE, FunctionInfo, ProgramModel, dotted_name,
)


def _transitive_acquires(model: ProgramModel, fn: FunctionInfo,
                         memo: Dict[str, Set[Tuple[str, str, int]]],
                         stack: Set[str]) \
        -> Tuple[Set[Tuple[str, str, int]], bool]:
    """((lock_id, rel, line) for every lock `fn` may acquire — itself
    or through its callees — , complete?).  Cycle-safe: a back edge to
    a function on the current DFS stack is cut, which makes that
    subtree's set INCOMPLETE (the on-stack ancestor's locks are
    missing) — such results must NOT be memoized, or a function inside
    a recursive call chain permanently loses the cycle's lock
    contributions.  The top-level call (fresh stack) is always
    complete: every cut edge points at an ancestor whose own locks are
    accumulated at that ancestor's level."""
    if fn.qname in memo:
        return memo[fn.qname], True
    if fn.qname in stack:
        return set(), False
    stack.add(fn.qname)
    acq: Set[Tuple[str, str, int]] = set()
    complete = True
    for site in model.lock_sites:
        if site.fn is fn:
            acq.add((site.lock_id, fn.module.rel, site.line))
    for callee in model.callees(fn):
        sub, sub_complete = _transitive_acquires(
            model, callee, memo, stack)
        acq |= sub
        complete = complete and sub_complete
    stack.discard(fn.qname)
    if complete:
        memo[fn.qname] = acq
    return acq, complete


class _Edge:
    __slots__ = ("src", "dst", "rel", "line", "why")

    def __init__(self, src: str, dst: str, rel: str, line: int,
                 why: str):
        self.src = src
        self.dst = dst
        self.rel = rel        # file+line where the edge is created
        self.line = line
        self.why = why


def _lock_expr(model: ProgramModel, fn: FunctionInfo, expr) \
        -> Optional[Tuple[str, bool]]:
    d = dotted_name(expr)
    if d and LOCKLIKE_RE.search(d.split(".")[-1]):
        return model.lock_identity(fn, d)
    return None


def _scan_function(model: ProgramModel, fn: FunctionInfo,
                   memo, edges: List[_Edge],
                   self_deadlocks: List[Finding]):
    """Walk `fn` tracking which with-locks are held, emitting an edge
    for every acquisition (direct or via calls) under a held lock."""
    rel = fn.module.rel

    def visit(node, held: List[Tuple[str, bool]]):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            acquired: List[Tuple[str, bool]] = []
            for item in node.items:
                li = _lock_expr(model, fn, item.context_expr)
                if li is None:
                    continue
                lock_id, reentrant = li
                for held_id, _ in held:
                    if held_id == lock_id:
                        if not reentrant:
                            self_deadlocks.append(Finding(
                                "lock-order", rel, node.lineno,
                                f"non-reentrant lock {lock_id} "
                                f"re-acquired while already held in "
                                f"{fn.qname} — guaranteed "
                                f"self-deadlock on this path"))
                    else:
                        edges.append(_Edge(
                            held_id, lock_id, rel, node.lineno,
                            f"{fn.qname} acquires {lock_id} while "
                            f"holding {held_id} ({rel}:{node.lineno})"))
                acquired.append((lock_id, reentrant))
            inner = held + acquired
            for child in node.body:
                visit(child, inner)
            return
        if isinstance(node, ast.Call) and held:
            fnode = node.func
            if isinstance(fnode, ast.Attribute) and \
                    fnode.attr == "acquire":
                li = _lock_expr(model, fn, fnode.value)
                if li is not None:
                    lock_id, reentrant = li
                    for held_id, _ in held:
                        if held_id != lock_id:
                            edges.append(_Edge(
                                held_id, lock_id, rel, node.lineno,
                                f"{fn.qname} acquires {lock_id} while "
                                f"holding {held_id} "
                                f"({rel}:{node.lineno})"))
                        elif not reentrant:
                            self_deadlocks.append(Finding(
                                "lock-order", rel, node.lineno,
                                f"non-reentrant lock {lock_id} "
                                f".acquire()d while already held in "
                                f"{fn.qname}"))
            else:
                is_self_call = isinstance(fnode, ast.Attribute) and \
                    isinstance(fnode.value, ast.Name) and \
                    fnode.value.id == "self"
                for callee in model.resolve_call(fn, node):
                    if callee is fn:
                        continue
                    if is_self_call:
                        # a direct self.m() runs on the SAME instance:
                        # the callee re-acquiring a held non-reentrant
                        # lock is a guaranteed self-deadlock, not a
                        # cross-instance maybe
                        for site in model.lock_sites:
                            if site.fn is callee and not \
                                    site.reentrant and any(
                                        h == site.lock_id
                                        for h, _ in held):
                                self_deadlocks.append(Finding(
                                    "lock-order", rel, node.lineno,
                                    f"{fn.qname} holds "
                                    f"{site.lock_id} and calls "
                                    f"{callee.qname}, which "
                                    f"re-acquires it "
                                    f"({callee.module.rel}:"
                                    f"{site.line}) — guaranteed "
                                    f"self-deadlock (same instance, "
                                    f"non-reentrant lock)"))
                    for (lock_id, arel, aline) in _transitive_acquires(
                            model, callee, memo, set())[0]:
                        for held_id, _ in held:
                            if held_id == lock_id:
                                # same CLASS-level lock id through a
                                # non-self call: may be another
                                # instance — not provably a cycle
                                continue
                            edges.append(_Edge(
                                held_id, lock_id, rel, node.lineno,
                                f"{fn.qname} holds {held_id} and "
                                f"calls {callee.qname} "
                                f"({rel}:{node.lineno}) which "
                                f"acquires {lock_id} "
                                f"({arel}:{aline})"))
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    for child in ast.iter_child_nodes(fn.node):
        visit(child, [])


def _cycles(edges: List[_Edge]) -> List[List[_Edge]]:
    """Tarjan SCCs over the lock graph; any SCC with >1 node (or a
    2-node mutual pair) is a potential deadlock.  Returns one edge
    list per cyclic SCC (evidence, deduped per src->dst pair)."""
    adj: Dict[str, Dict[str, _Edge]] = {}
    for e in edges:
        adj.setdefault(e.src, {}).setdefault(e.dst, e)
        adj.setdefault(e.dst, {})
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on: Set[str] = set()
    stack: List[str] = []
    counter = [0]
    sccs: List[List[str]] = []

    def strongconnect(v: str):
        # iterative Tarjan: (node, child-iterator) frames
        frames = [(v, iter(adj.get(v, ())))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on.add(v)
        while frames:
            node, it = frames[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on.add(w)
                    frames.append((w, iter(adj.get(w, ()))))
                    advanced = True
                    break
                elif w in on:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            frames.pop()
            if frames:
                parent = frames[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                scc = []
                while True:
                    w = stack.pop()
                    on.discard(w)
                    scc.append(w)
                    if w == node:
                        break
                if len(scc) > 1:
                    sccs.append(scc)

    for v in list(adj):
        if v not in index:
            strongconnect(v)
    out = []
    for scc in sccs:
        members = set(scc)
        evid = [e for d in scc for e in adj[d].values()
                if e.dst in members]
        out.append(evid)
    return out


@rule("lock-order",
      "inter-procedural lock-acquisition cycle (potential deadlock)")
def check_lock_order(model: ProgramModel) -> List[Finding]:
    memo: Dict[str, Set[Tuple[str, str, int]]] = {}
    edges: List[_Edge] = []
    findings: List[Finding] = []
    for fn in model.functions.values():
        _scan_function(model, fn, memo, edges, findings)
    for evid in _cycles(edges):
        evid.sort(key=lambda e: (e.rel, e.line))
        locks = sorted({e.src for e in evid} | {e.dst for e in evid})
        why = "; ".join(e.why for e in evid[:4])
        anchor = evid[0]
        findings.append(Finding(
            "lock-order", anchor.rel, anchor.line,
            f"lock-order cycle over {{{', '.join(locks)}}} — two "
            f"threads taking these locks in opposite orders deadlock: "
            f"{why}"))
    return findings
