"""ownership-history: ownership stamps are read through ONE API.

parallel/distributed.py owns the on-snapshot encoding of the fleet's
ownership state: the `multihost.ownership.*` properties (version /
processes / buckets / dead / history) and the per-host rejoin
properties (`multihost.rejoin.request.p<i>` / `.floor.p<i>`).  The
encoding has already changed once — the generation HISTORY property
was added so `owner_of` at a historical version is exact instead of
reconstructed — and any module that parses the raw properties itself
silently breaks on the next change: it reads the current map where it
needed the governing one, or misses the dead-set.  The sanctioned
readers are `stamp_from_properties` / `has_ownership_stamp` /
`resume_generation_history` (and friends) in parallel/distributed.py.

Two shapes are flagged outside that module:

* a string literal spelling one of the canonical property keys or
  per-host prefixes — the telltale of hand-rolled stamp parsing or
  construction (docstrings are exempt: prose may NAME the properties,
  code may not touch them);
* importing the property-name constants (`OWNERSHIP_*_PROP`,
  `REJOIN_*_PREFIX`) from parallel.distributed — the same fork one
  step removed.

`multihost.rejoin.enabled` (an OPTION key, options.py's to register)
and `multihost.lease.*` (already behind `lease_props` /
`merge_lease_view`, with no versioned encoding to fork) are
deliberately out of scope.
"""

from __future__ import annotations

import ast
from typing import List, Set

from paimon_tpu.analysis.engine import Finding, rule
from paimon_tpu.analysis.model import ProgramModel

# the canonical keys/prefixes distributed.py defines; a literal that
# STARTS WITH one of these is parsing/constructing a stamp property
_PROP_KEYS = (
    "multihost.ownership.version",
    "multihost.ownership.processes",
    "multihost.ownership.buckets",
    "multihost.ownership.dead",
    "multihost.ownership.history",
    "multihost.rejoin.request.p",
    "multihost.rejoin.floor.p",
)
_CONST_NAMES = frozenset({
    "OWNERSHIP_VERSION_PROP", "OWNERSHIP_PROCESSES_PROP",
    "OWNERSHIP_BUCKETS_PROP", "OWNERSHIP_DEAD_PROP",
    "OWNERSHIP_HISTORY_PROP",
    "REJOIN_REQUEST_PREFIX", "REJOIN_FLOOR_PREFIX",
})
_ALLOWED = frozenset({
    "parallel/distributed.py",      # the owner of the encoding
    "analysis/rules/ownership.py",  # this rule's own key table
})


def _docstring_constants(tree: ast.Module) -> Set[int]:
    """ids of the Constant nodes that are docstrings (module / class /
    function leading string statements) — prose, not parsing."""
    out: Set[int] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.Module, ast.ClassDef,
                             ast.FunctionDef, ast.AsyncFunctionDef)):
            body = getattr(node, "body", None)
            if body and isinstance(body[0], ast.Expr) and \
                    isinstance(body[0].value, ast.Constant) and \
                    isinstance(body[0].value.value, str):
                out.add(id(body[0].value))
    return out


@rule("ownership-history",
      "ownership-stamp properties parsed outside parallel/distributed")
def check_ownership_history(model: ProgramModel) -> List[Finding]:
    out: List[Finding] = []
    for mod in model.modules.values():
        if mod.pkg_rel in _ALLOWED:
            continue
        docstrings = _docstring_constants(mod.tree)
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Constant) and \
                    isinstance(node.value, str) and \
                    id(node) not in docstrings and \
                    node.value.startswith(_PROP_KEYS):
                out.append(Finding(
                    "ownership-history", mod.rel, node.lineno,
                    f"literal {node.value!r} spells an ownership-"
                    f"stamp property — read stamps through "
                    f"stamp_from_properties / has_ownership_stamp / "
                    f"resume_generation_history "
                    f"(parallel/distributed.py), which track the "
                    f"encoding as it evolves"))
            elif isinstance(node, ast.ImportFrom) and \
                    node.module and \
                    node.module.endswith("parallel.distributed"):
                forked = sorted(a.name for a in node.names
                                if a.name in _CONST_NAMES)
                if forked:
                    out.append(Finding(
                        "ownership-history", mod.rel, node.lineno,
                        f"importing {', '.join(forked)} from "
                        f"parallel.distributed forks the stamp "
                        f"encoding — use the stamp/history API "
                        f"instead of the raw property names"))
    return out
