"""Rule engine over the shared program model.

A rule is a function `(ProgramModel) -> list[Finding]` registered
under a stable id via `@rule(...)`.  `run()` builds findings from
every requested rule, then applies the UNIFORM suppression contract:

* a finding whose line (or the line directly below a comment-only
  marker line) carries `# lint-ok: <rule-id> <reason>` is kept but
  marked suppressed — CI fails only on unsuppressed findings, humans
  still see the suppressed ones in `paimon lint --json`;
* the reason is mandatory: a bare `# lint-ok: deadline-wait` is a
  `bad-suppression` finding (an exemption nobody can review is not an
  exemption);
* a marker naming a rule that is running but matching no finding is a
  `stale-suppression` finding — suppressions rot the moment the code
  they exempted changes, and stale ones hide the next real bug;
* a marker naming a rule id that does not exist at all is
  `bad-suppression` (usually a typo that silently disables nothing).

The engine is the ONE place parse/suppress/report logic lives; rules
only look at the model and emit findings.  Tier-1 runs the engine once
per session (tests share the cached report via conftest), the CLI
(`paimon lint`) runs the same pass for humans and CI.
"""

from __future__ import annotations

import json
from typing import Callable, Dict, List, Optional, Sequence

from paimon_tpu.analysis.model import ProgramModel, build_model

__all__ = ["Finding", "Rule", "rule", "all_rules", "get_rule", "run",
           "run_package", "Report", "META_RULES"]

# engine-emitted rule ids (no registered checker behind them)
META_RULES = ("bad-suppression", "stale-suppression")


class Finding:
    """One structured result: rule id, location, message — plus the
    suppression state the engine fills in."""

    __slots__ = ("rule", "file", "line", "message", "suppressed",
                 "suppress_reason")

    def __init__(self, rule: str, file: str, line: int, message: str):
        self.rule = rule
        self.file = file            # repo-relative display path
        self.line = int(line)
        self.message = message
        self.suppressed = False
        self.suppress_reason: Optional[str] = None

    def key(self):
        return (self.rule, self.file, self.line, self.message)

    def to_dict(self) -> dict:
        return {"rule": self.rule, "file": self.file,
                "line": self.line, "message": self.message,
                "suppressed": self.suppressed,
                "suppress_reason": self.suppress_reason}

    def __repr__(self):
        tag = " [suppressed]" if self.suppressed else ""
        return f"{self.file}:{self.line}: [{self.rule}]{tag} " \
               f"{self.message}"


class Rule:
    __slots__ = ("id", "title", "check")

    def __init__(self, id: str, title: str,
                 check: Callable[[ProgramModel], List[Finding]]):
        self.id = id
        self.title = title
        self.check = check


_RULES: Dict[str, Rule] = {}


def rule(id: str, title: str):
    """Register a checker under a stable rule id."""
    def deco(fn):
        if id in _RULES:
            raise ValueError(f"duplicate rule id: {id}")
        _RULES[id] = Rule(id, title, fn)
        return fn
    return deco


def _load_rules():
    # importing the package registers every rule module exactly once
    from paimon_tpu.analysis import rules  # noqa: F401


def all_rules() -> List[Rule]:
    _load_rules()
    return [_RULES[k] for k in sorted(_RULES)]


def get_rule(id: str) -> Rule:
    _load_rules()
    try:
        return _RULES[id]
    except KeyError:
        raise ValueError(
            f"unknown rule id '{id}' (known: "
            f"{', '.join(sorted(_RULES) + list(META_RULES))})") \
            from None


class Report:
    """Findings (suppressed + not) from one engine run."""

    def __init__(self, model: ProgramModel, rules: List[Rule],
                 findings: List[Finding]):
        self.model = model
        self.rules = rules
        self.findings = findings

    @property
    def unsuppressed(self) -> List[Finding]:
        return [f for f in self.findings if not f.suppressed]

    def by_rule(self, rule_id: str) -> List[Finding]:
        return [f for f in self.findings if f.rule == rule_id]

    def unsuppressed_by_rule(self, rule_id: str) -> List[Finding]:
        return [f for f in self.by_rule(rule_id) if not f.suppressed]

    def to_dict(self) -> dict:
        return {
            "package": self.model.package_name,
            "files": len(self.model.modules),
            "rules": [r.id for r in self.rules] + list(META_RULES),
            "findings": [f.to_dict() for f in self.findings],
            "summary": {
                "total": len(self.findings),
                "unsuppressed": len(self.unsuppressed),
                "suppressed": len(self.findings)
                - len(self.unsuppressed),
            },
        }

    def to_json(self, indent: Optional[int] = 2) -> str:
        return json.dumps(self.to_dict(), indent=indent,
                          sort_keys=True)


def _apply_suppressions(model: ProgramModel, rules: List[Rule],
                        findings: List[Finding]) -> List[Finding]:
    """Mark suppressed findings, then audit the markers themselves."""
    by_file = {m.rel: m for m in model.modules.values()}
    for f in findings:
        mod = by_file.get(f.file)
        if mod is None:
            continue
        s = mod.suppression_for(f.rule, f.line)
        if s is not None and s.reason:
            f.suppressed = True
            f.suppress_reason = s.reason
            s.consumed = True
        elif s is not None:
            s.consumed = True       # reasonless: audited below anyway
    known = {r.id for r in all_rules()} | set(META_RULES)
    running = {r.id for r in rules}
    audit: List[Finding] = []
    for mod in model.modules.values():
        for s in mod.suppressions:
            if s.rule not in known:
                audit.append(Finding(
                    "bad-suppression", mod.rel, s.line,
                    f"marker names unknown rule '{s.rule}' — typo? "
                    f"it suppresses nothing"))
            elif not s.reason:
                audit.append(Finding(
                    "bad-suppression", mod.rel, s.line,
                    f"marker for '{s.rule}' has no reason — "
                    f"`# lint-ok: {s.rule} <why this is deliberate>`"))
            elif s.rule in running and not s.consumed:
                audit.append(Finding(
                    "stale-suppression", mod.rel, s.line,
                    f"marker for '{s.rule}' suppresses no finding — "
                    f"the exempted code changed or moved; remove the "
                    f"marker"))
    return findings + audit


def run(model: ProgramModel,
        rule_ids: Optional[Sequence[str]] = None) -> Report:
    """Run the requested rules (default: all) over `model` and return
    the suppression-applied report.  Findings are sorted by file, line,
    rule for stable output.

    The engine-emitted meta ids (`bad-suppression`,
    `stale-suppression`) are valid in `rule_ids` — every report's
    `rules` array advertises them, so an id round-tripped from the
    JSON must not be rejected.  They select no checker (the marker
    audit always runs; stale detection needs the named rules running
    to know a marker matched nothing)."""
    rules = all_rules() if rule_ids is None \
        else [get_rule(r) for r in rule_ids if r not in META_RULES]
    findings: List[Finding] = []
    for r in rules:
        findings.extend(r.check(model))
    findings = _apply_suppressions(model, rules, findings)
    findings.sort(key=lambda f: (f.file, f.line, f.rule, f.message))
    return Report(model, rules, findings)


def run_package(package_dir: str,
                rule_ids: Optional[Sequence[str]] = None,
                repo_root: Optional[str] = None) -> Report:
    """Build the model (ONE parse per file) and run the rules."""
    return run(build_model(package_dir, repo_root=repo_root), rule_ids)
