"""Shared program model for the whole-program analysis plane.

Before this plane existed, correctness invariants were enforced by
seven ad-hoc AST lints (tests/test_lint_swallow.py) that each re-parsed
every file under paimon_tpu/, plus grep drift tests — and none of them
could see ACROSS functions, so the bug classes that actually bite a
five-concurrency-plane architecture (lock-order inversions, a blocking
call reachable from the event-loop thread, a wait that ignores the
PR-9 deadlines) were invisible.

This module parses each source file exactly ONCE into a `ProgramModel`:

* `modules` — source + AST per file (`SourceModule`), keyed by the
  package-relative posix path (`utils/backoff.py`), so rules written
  against the real tree also run unchanged over fixture packages;
* `functions` / `classes` — every def/class with a stable qualified
  name (`fs/caching.py::BlockCache.get`), per-class self-assigned
  attribute sets (for lock ownership), and base-class links;
* a CONSERVATIVE call graph: `self.m()` resolves through the class and
  its in-package bases, bare names through local defs and from-imports,
  `mod.f()` through import aliases, and `self.X.m()` through the
  constructor type `__init__` assigned to `self.X` — anything the
  model cannot pin down stays unresolved, because a phantom call edge
  is worse than a missed one for every rule built on the graph;
* a lock-site index: every `with <lock-like>:` and `.acquire()` call,
  with lock IDENTITY canonicalised to the class that assigns the
  attribute (so `B(A)` methods and `A` methods agree on `A._lock`) and
  `threading.Condition(self._lock)` aliased to its underlying lock;
* the `# lint-ok: <rule> <reason>` suppression markers
  (engine.py consumes these; a marker that suppresses nothing is
  itself a finding).

Rules receive the model and never touch the filesystem again — one
parse per file per run is the whole point (the old tier-1 lints parsed
the full tree seven times).
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from typing import Dict, Iterable, List, Optional, Set, Tuple

__all__ = ["SourceModule", "FunctionInfo", "ClassInfo", "LockSite",
           "Suppression", "ProgramModel", "build_model", "dotted_name",
           "except_names", "iter_function_nodes", "LOCKLIKE_RE"]

# last attribute segment that makes a `with`-target / `.acquire()`
# receiver count as a lock: _lock, lock, _build_lock, _cond, rlock,
# _sem, mutex ... ("cond" must terminate the name so `second` is not
# a lock)
LOCKLIKE_RE = re.compile(
    r"(?:^|_)(?:r?lock|cond(?:ition)?|mutex|sem(?:aphore)?)$",
    re.IGNORECASE)

_SUPPRESS_RE = re.compile(r"#\s*lint-ok:\s*([A-Za-z0-9_-]+)\s*(.*)$")


def iter_function_nodes(fn_node: ast.AST):
    """Walk a function body WITHOUT descending into nested
    def/class scopes — a nested def's body runs when the closure is
    called (often on another thread), so attributing its lock
    acquisitions or calls to the enclosing function would invent
    held-lock edges the program never takes.  Nested defs are
    registered as their own FunctionInfos and analysed separately."""
    stack = list(ast.iter_child_nodes(fn_node))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef, ast.Lambda)):
            stack.extend(ast.iter_child_nodes(node))


def _nested_stmt_bodies(node: ast.stmt) -> List[list]:
    """The statement lists nested inside a compound statement (loop
    bodies, if/else branches, try/except/else/finally, with bodies) —
    everywhere a def can legally appear outside a new scope."""
    if isinstance(node, (ast.If, ast.For, ast.AsyncFor, ast.While)):
        return [node.body, node.orelse]
    if isinstance(node, (ast.With, ast.AsyncWith)):
        return [node.body]
    if isinstance(node, ast.Try):
        return [node.body, node.orelse, node.finalbody] \
            + [h.body for h in node.handlers]
    return []


def except_names(type_node: Optional[ast.AST]) -> List[str]:
    """Exception-class simple names an `except` clause catches —
    `["<bare>"]` for a bare except, the last attribute segment for
    dotted names, tuple clauses flattened."""
    if type_node is None:
        return ["<bare>"]
    nodes = type_node.elts if isinstance(type_node, ast.Tuple) \
        else [type_node]
    out = []
    for n in nodes:
        name = n.id if isinstance(n, ast.Name) else \
            n.attr if isinstance(n, ast.Attribute) else None
        if name:
            out.append(name)
    return out


def dotted_name(node: ast.AST) -> Optional[str]:
    """`a.b.c` for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class Suppression:
    """One `# lint-ok: <rule> <reason>` marker.  A marker on a
    comment-only line covers the next CODE line (the reason may wrap
    over following comment lines); a trailing marker covers its own
    line.  `consumed` flips when a finding matches — unconsumed
    markers are stale (engine emits them as findings)."""

    __slots__ = ("rule", "reason", "line", "applies_to", "consumed")

    def __init__(self, rule: str, reason: str, line: int,
                 applies_to: int):
        self.rule = rule
        self.reason = reason
        self.line = line              # where the marker itself sits
        self.applies_to = applies_to  # the line it exempts
        self.consumed = False


class SourceModule:
    """One parsed file: source, split lines, AST, import map,
    suppression markers."""

    __slots__ = ("rel", "pkg_rel", "path", "source", "lines", "tree",
                 "imports", "suppressions")

    def __init__(self, rel: str, pkg_rel: str, path: str, source: str,
                 tree: ast.Module):
        self.rel = rel          # repo-relative (display): paimon_tpu/x.py
        self.pkg_rel = pkg_rel  # package-relative (rule scoping): x.py
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        # local name -> dotted target ("paimon_tpu.utils.backoff" or
        # "paimon_tpu.utils.backoff.Backoff")
        self.imports: Dict[str, str] = {}
        self.suppressions: List[Suppression] = []

    def suppression_for(self, rule: str, line: int) \
            -> Optional[Suppression]:
        for s in self.suppressions:
            if s.rule == rule and s.applies_to == line:
                return s
        return None


class FunctionInfo:
    __slots__ = ("module", "node", "name", "class_name", "qname",
                 "_callees")

    def __init__(self, module: SourceModule, node: ast.AST,
                 name: str, class_name: Optional[str]):
        self.module = module
        self.node = node
        self.name = name
        self.class_name = class_name
        owner = f"{class_name}.{name}" if class_name else name
        self.qname = f"{module.pkg_rel}::{owner}"
        self._callees: Optional[List["FunctionInfo"]] = None

    def __repr__(self):
        return f"FunctionInfo({self.qname})"


class ClassInfo:
    __slots__ = ("module", "name", "bases", "methods", "self_attrs",
                 "cond_aliases", "reentrant_attrs", "attr_classes")

    def __init__(self, module: SourceModule, name: str,
                 bases: List[str]):
        self.module = module
        self.name = name
        self.bases = bases                       # base-class simple names
        self.methods: Dict[str, FunctionInfo] = {}
        self.self_attrs: Set[str] = set()        # attrs assigned on self
        # self.<cond> -> "self.<lock>" for Condition(self._lock)
        self.cond_aliases: Dict[str, str] = {}
        self.reentrant_attrs: Set[str] = set()   # threading.RLock()
        # self.X = SomeClass(...) -> {"X": "SomeClass"}: lets
        # `self.X.m()` resolve to SomeClass.m when SomeClass is an
        # in-package class (resolved lazily — classes fill as modules
        # index)
        self.attr_classes: Dict[str, str] = {}


class LockSite:
    """One lock acquisition: a `with <lock>:` or `<lock>.acquire()`."""

    __slots__ = ("fn", "lock_id", "line", "kind", "reentrant")

    def __init__(self, fn: FunctionInfo, lock_id: str, line: int,
                 kind: str, reentrant: bool):
        self.fn = fn
        self.lock_id = lock_id
        self.line = line
        self.kind = kind            # "with" | "acquire"
        self.reentrant = reentrant


class ProgramModel:
    """The parse-once view every rule runs over."""

    def __init__(self, repo_root: str, package_dir: str,
                 package_name: str):
        self.repo_root = repo_root
        self.package_dir = package_dir
        self.package_name = package_name
        self.modules: Dict[str, SourceModule] = {}   # by pkg_rel
        self.functions: Dict[str, FunctionInfo] = {}  # by qname
        self.functions_by_name: Dict[str, List[FunctionInfo]] = {}
        self.classes: Dict[str, List[ClassInfo]] = {}  # by simple name
        self.lock_sites: List[LockSite] = []

    # -- construction --------------------------------------------------------

    def _add_function(self, fn: FunctionInfo):
        if fn.qname in self.functions:
            # a nested def shadowing a method name (or two same-named
            # nested defs) must not overwrite the earlier entry —
            # rules iterate self.functions, so an overwrite would
            # silently drop a whole function body from every check
            n = 2
            while f"{fn.qname}#{n}" in self.functions:
                n += 1
            fn.qname = f"{fn.qname}#{n}"
        self.functions[fn.qname] = fn
        self.functions_by_name.setdefault(fn.name, []).append(fn)

    def _index_module(self, mod: SourceModule):
        # imports
        pkg_dotted = self.package_name
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    mod.imports[alias.asname
                                or alias.name.split(".")[0]] = alias.name
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                if node.level:          # relative: anchor in the package
                    rel_dir = os.path.dirname(mod.pkg_rel).replace(
                        os.sep, "/")
                    parts = [p for p in rel_dir.split("/") if p]
                    parts = parts[:len(parts) - (node.level - 1)] \
                        if node.level > 1 else parts
                    base = ".".join([pkg_dotted] + parts
                                    + ([node.module] if node.module
                                       else []))
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    mod.imports[alias.asname or alias.name] = \
                        f"{base}.{alias.name}" if base else alias.name
        # suppression markers — taken from real COMMENT tokens only,
        # so `# lint-ok:` inside a docstring or string literal (this
        # plane's own documentation, a fixture snippet embedded in a
        # test string) never becomes a live marker
        try:
            tokens = tokenize.generate_tokens(
                io.StringIO(mod.source).readline)
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                m = _SUPPRESS_RE.search(tok.string)
                if not m:
                    continue
                i = tok.start[0]
                applies_to = i
                if mod.lines[i - 1].strip().startswith("#"):
                    # comment-only marker: exempt the next CODE line
                    # (the reason may wrap onto further comment lines)
                    applies_to = i + 1
                    while applies_to <= len(mod.lines) and (
                            not mod.lines[applies_to - 1].strip()
                            or mod.lines[applies_to - 1]
                            .strip().startswith("#")):
                        applies_to += 1
                mod.suppressions.append(Suppression(
                    m.group(1), m.group(2).strip(), i, applies_to))
        except tokenize.TokenError:
            pass
        # defs / classes
        self._index_scope(mod, mod.tree.body, class_name=None)

    def _index_scope(self, mod: SourceModule, body, class_name,
                     in_function: bool = False):
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fn = FunctionInfo(mod, node, node.name, class_name)
                self._add_function(fn)
                if class_name is not None and not in_function:
                    # only CLASS-BODY defs are methods: a def nested
                    # inside a method is a closure — registering it
                    # would let `self.<name>()` resolve to it (phantom
                    # call edges, false self-deadlocks).  It still
                    # keeps class_name so `self._lock` inside the
                    # closure canonicalises like the enclosing method.
                    for ci in self.classes.get(class_name, []):
                        if ci.module is mod:
                            ci.methods[node.name] = fn
                # nested defs resolve by bare name within the module
                self._index_scope(mod, node.body, class_name,
                                  in_function=True)
            elif isinstance(node, ast.ClassDef):
                bases = [dotted_name(b).split(".")[-1]
                         for b in node.bases if dotted_name(b)]
                ci = ClassInfo(mod, node.name, bases)
                self.classes.setdefault(node.name, []).append(ci)
                self._index_scope(mod, node.body, node.name)
                self._collect_class_attrs(ci, node)
            else:
                # a def can hide in ANY compound statement (loop
                # bodies, except handlers, else/finally) — missing one
                # makes the function invisible to every rule
                for sub in _nested_stmt_bodies(node):
                    self._index_scope(mod, sub, class_name, in_function)

    def _collect_class_attrs(self, ci: ClassInfo, cls: ast.ClassDef):
        """`self.X = ...` targets, Condition-over-lock aliases, and
        RLock attrs for every method of the class."""
        for node in ast.walk(cls):
            if not isinstance(node, ast.Assign):
                continue
            for tgt in node.targets:
                if not (isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"):
                    continue
                ci.self_attrs.add(tgt.attr)
                val = node.value
                if not isinstance(val, ast.Call):
                    continue
                ctor = dotted_name(val.func) or ""
                ctor_tail = ctor.split(".")[-1]
                if ctor_tail == "RLock":
                    ci.reentrant_attrs.add(tgt.attr)
                elif ctor_tail == "Condition" and val.args:
                    arg = dotted_name(val.args[0])
                    if arg and arg.startswith("self."):
                        ci.cond_aliases[tgt.attr] = arg
                elif ctor_tail and ctor_tail[0].isupper():
                    ci.attr_classes[tgt.attr] = ctor_tail

    # -- class / lock resolution ---------------------------------------------

    def _class_chain(self, name: Optional[str],
                     mod: SourceModule) -> List[ClassInfo]:
        """The class and its in-package bases (module-local ClassInfo
        preferred), breadth-first, cycle-safe."""
        out: List[ClassInfo] = []
        seen: Set[str] = set()
        queue = [name] if name else []
        while queue:
            nm = queue.pop(0)
            if nm in seen:
                continue
            seen.add(nm)
            infos = self.classes.get(nm, [])
            local = [c for c in infos if c.module is mod]
            for ci in (local or infos):
                out.append(ci)
                queue.extend(ci.bases)
        return out

    def lock_identity(self, fn: FunctionInfo,
                      dotted: str) -> Tuple[str, bool]:
        """(lock_id, reentrant) for a lock expression in `fn`.

        `self.X` canonicalises to the BASE-most in-package class that
        assigns X (so a subclass method and the defining class agree),
        and `self.<cond>` follows a `Condition(self._lock)` alias to
        the underlying lock.  Anything else is scoped to the module.
        """
        if dotted.startswith("self.") and fn.class_name:
            attr = dotted.split(".", 1)[1]
            chain = self._class_chain(fn.class_name, fn.module)
            # follow a Condition alias first (nearest class wins)
            for ci in chain:
                alias = ci.cond_aliases.get(attr.split(".")[0])
                if alias:
                    attr = alias.split(".", 1)[1]
                    break
            owner = fn.class_name
            owner_mod = fn.module
            reentrant = False
            for ci in chain:            # base-most assigner wins
                if attr.split(".")[0] in ci.self_attrs:
                    owner, owner_mod = ci.name, ci.module
                    reentrant = attr.split(".")[0] in ci.reentrant_attrs
            return f"{owner_mod.pkg_rel}::{owner}.{attr}", reentrant
        return f"{fn.module.pkg_rel}::{dotted}", False

    # -- call graph ----------------------------------------------------------

    def _module_for(self, dotted: str) -> Optional[SourceModule]:
        """SourceModule for a dotted import path inside the package."""
        prefix = self.package_name + "."
        if dotted == self.package_name:
            return self.modules.get("__init__.py")
        if not dotted.startswith(prefix):
            return None
        tail = dotted[len(prefix):].replace(".", "/")
        return self.modules.get(f"{tail}.py") \
            or self.modules.get(f"{tail}/__init__.py")

    def _module_functions(self, mod: SourceModule,
                          name: str) -> List[FunctionInfo]:
        return [f for f in self.functions_by_name.get(name, [])
                if f.module is mod]

    def resolve_call(self, fn: FunctionInfo,
                     call: ast.Call) -> List[FunctionInfo]:
        """Possible in-package targets of `call` made from `fn` —
        conservative: empty when the target cannot be pinned down."""
        func = call.func
        mod = fn.module
        if isinstance(func, ast.Name):
            nm = func.id
            local = self._module_functions(mod, nm)
            if local:
                return local
            target = mod.imports.get(nm)
            if target:
                # `from m import f` -> f in module m; or a re-export
                owner = self._module_for(
                    target.rsplit(".", 1)[0]) if "." in target else None
                if owner is not None:
                    return self._module_functions(
                        owner, target.rsplit(".", 1)[1])
            return []
        if not isinstance(func, ast.Attribute):
            return []
        attr = func.attr
        base = func.value
        if isinstance(base, ast.Name):
            if base.id == "self" and fn.class_name:
                for ci in self._class_chain(fn.class_name, mod):
                    if attr in ci.methods:
                        return [ci.methods[attr]]
                return []
            if base.id in ("cls", fn.class_name or ""):
                for ci in self._class_chain(fn.class_name, mod):
                    if attr in ci.methods:
                        return [ci.methods[attr]]
            # imported module alias:  backoff.wait_for(...)
            target = mod.imports.get(base.id)
            if target:
                owner = self._module_for(target)
                if owner is not None:
                    return self._module_functions(owner, attr)
                # a known import that is NOT a package module
                # (threading.Thread, np.argsort, ...): never fall
                # through to uniqueness guessing
                return []
            # class name used directly:  BlockCache.evict(...)
            if base.id in self.classes:
                for ci in self.classes[base.id]:
                    if attr in ci.methods:
                        return [ci.methods[attr]]
                return []
        # `self.X.m(...)` where __init__ recorded self.X = SomeClass():
        # resolve through the attribute's constructor type.  Anything
        # else stays UNRESOLVED — guessing a target for `x.get()` /
        # `sel.unregister()` by name uniqueness invents call edges the
        # program never takes (and phantom reachability is worse than
        # a missed edge for every rule built on this graph).
        if isinstance(base, ast.Attribute) \
                and isinstance(base.value, ast.Name) \
                and base.value.id == "self" and fn.class_name:
            for ci in self._class_chain(fn.class_name, mod):
                cls = ci.attr_classes.get(base.attr)
                if cls is None:
                    continue
                for target_ci in self.classes.get(cls, []):
                    if attr in target_ci.methods:
                        return [target_ci.methods[attr]]
                break
        return []

    def callees(self, fn: FunctionInfo) -> List[FunctionInfo]:
        if fn._callees is None:
            out: List[FunctionInfo] = []
            seen: Set[str] = set()
            for node in iter_function_nodes(fn.node):
                if isinstance(node, ast.Call):
                    for tgt in self.resolve_call(fn, node):
                        if tgt.qname not in seen and tgt is not fn:
                            seen.add(tgt.qname)
                            out.append(tgt)
            fn._callees = out
        return fn._callees

    def reachable(self, roots: Iterable[FunctionInfo]) \
            -> Dict[str, Tuple[FunctionInfo, Optional[str]]]:
        """BFS closure over the call graph: qname -> (fn, parent
        qname) — parents give a readable path for findings."""
        out: Dict[str, Tuple[FunctionInfo, Optional[str]]] = {}
        queue: List[FunctionInfo] = []
        for r in roots:
            if r.qname not in out:
                out[r.qname] = (r, None)
                queue.append(r)
        while queue:
            fn = queue.pop(0)
            for tgt in self.callees(fn):
                if tgt.qname not in out:
                    out[tgt.qname] = (tgt, fn.qname)
                    queue.append(tgt)
        return out

    def call_path(self, reach, qname: str) -> str:
        """`root -> a -> b` chain text from a `reachable` map."""
        parts = []
        cur: Optional[str] = qname
        while cur is not None:
            parts.append(cur.split("::")[-1])
            cur = reach[cur][1]
        return " -> ".join(reversed(parts))

    # -- per-function enclosing lookup ---------------------------------------

    def enclosing_function(self, mod: SourceModule,
                           line: int) -> Optional[FunctionInfo]:
        best: Optional[FunctionInfo] = None
        for fn in self.functions.values():
            if fn.module is not mod:
                continue
            node = fn.node
            if node.lineno <= line <= (node.end_lineno or node.lineno):
                if best is None or node.lineno > best.node.lineno:
                    best = fn
        return best


def _iter_py_files(package_dir: str):
    for root, dirs, files in os.walk(package_dir):
        dirs[:] = sorted(d for d in dirs if d != "__pycache__")
        for f in sorted(files):
            if f.endswith(".py"):
                yield os.path.join(root, f)


def _collect_lock_sites(model: ProgramModel):
    """Every `with <lock-like>:` and `<lock-like>.acquire()` in every
    function — THE index the lock-order and event-loop rules share."""
    for fn in list(model.functions.values()):
        for node in iter_function_nodes(fn.node):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    d = dotted_name(item.context_expr)
                    if d and LOCKLIKE_RE.search(d.split(".")[-1]):
                        lock_id, reent = model.lock_identity(fn, d)
                        model.lock_sites.append(LockSite(
                            fn, lock_id, node.lineno, "with", reent))
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "acquire":
                d = dotted_name(node.func.value)
                if d and LOCKLIKE_RE.search(d.split(".")[-1]):
                    lock_id, reent = model.lock_identity(fn, d)
                    model.lock_sites.append(LockSite(
                        fn, lock_id, node.lineno, "acquire", reent))


def build_model(package_dir: str,
                repo_root: Optional[str] = None) -> ProgramModel:
    """Parse every .py under `package_dir` once and index it.

    `package_dir` is the package root (the directory whose name is the
    import name — `paimon_tpu/` in production, a tmp package in rule
    fixtures); `repo_root` defaults to its parent and only affects the
    repo-relative display paths.
    """
    package_dir = os.path.abspath(package_dir)
    if repo_root is None:
        repo_root = os.path.dirname(package_dir)
    model = ProgramModel(repo_root, package_dir,
                         os.path.basename(package_dir))
    for path in _iter_py_files(package_dir):
        pkg_rel = os.path.relpath(path, package_dir).replace(os.sep, "/")
        rel = os.path.relpath(path, repo_root).replace(os.sep, "/")
        with open(path, encoding="utf-8") as fh:
            source = fh.read()
        mod = SourceModule(rel, pkg_rel, path, source,
                           ast.parse(source, rel))
        model.modules[pkg_rel] = mod
    for mod in model.modules.values():
        model._index_module(mod)
    _collect_lock_sites(model)
    return model
