"""Whole-program analysis plane.

One shared parse of the package (`model.build_model`), a pluggable
rule engine (`engine.run`) with uniform `# lint-ok: <rule> <reason>`
suppressions and stale-marker detection, and a rule catalog spanning
the migrated hygiene lints, the docs/metrics drift checks, and the
four whole-program checkers (lock-order, loop-blocking,
deadline-wait, fault-taxonomy).  `paimon lint` on the CLI and the
tier-1 tests run the SAME pass — see docs/static_analysis.md.
"""

from paimon_tpu.analysis.engine import (
    META_RULES, Finding, Report, all_rules, get_rule, run,
    run_package, rule,
)
from paimon_tpu.analysis.model import ProgramModel, build_model

__all__ = ["Finding", "META_RULES", "Report", "ProgramModel",
           "all_rules", "build_model", "get_rule", "rule", "run",
           "run_package", "default_report"]

_CACHED = {}


def default_report(package_dir=None):
    """The full-rule report over the installed paimon_tpu package,
    cached per process — tier-1's seven-plus lint tests share ONE
    parse+run instead of re-walking the tree per test."""
    import os
    if package_dir is None:
        package_dir = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
    key = os.path.abspath(package_dir)
    if key not in _CACHED:
        _CACHED[key] = run_package(key)
    return _CACHED[key]
