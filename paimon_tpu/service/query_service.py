"""KV query service over LocalTableQuery.

reference: paimon-service/.../KvQueryServer.java + KvQueryClient.java +
ServiceManager.java ('primary-key-lookup' address files under
`<table>/service/`). Powers remote lookup joins
(PrimaryKeyPartialLookupTable remote mode).

Serving plane (PR 7): the server is MULTI-TENANT and cross-request —

* one shared LocalTableQuery (lookup/local_query.py) with a
  snapshot-refresh TTL serves every /lookup, probing per-file SSTs
  against the pinned block cache instead of rebuilding state per
  request;
* the table's FileIO joins the process-wide shared byte-cache tier
  (fs/caching.shared_cache_state), so concurrent /scan, /lookup and
  /changelog requests warm one footer/file/range cache
  (service.cache.shared);
* every request passes ADMISSION CONTROL (service/admission.py):
  an estimated byte cost is charged against the global and per-tenant
  in-flight budgets (service.max-inflight-bytes /
  service.tenant.max-inflight-bytes); requests queue bounded
  (service.queue.depth) with a timeout (service.queue.timeout) that
  answers HTTP 429 — the client raises ServiceBusyError;
* connections are KEEP-ALIVE (HTTP/1.1): KvQueryClient holds one
  persistent connection and reconnects on stale sockets — connection
  setup no longer dominates sub-ms point gets.

Web-scale serving plane (PR 13) — this server now rides the
EVENT-LOOP request engine (service/async_server.py, reference Paimon's
Netty KvQueryServer): one loop thread owns every socket, handlers run
on a bounded `service.workers` pool, pipelined HTTP/1.1 keep-alive
requests parse and answer in order, and 1k+ concurrent connections
cost file descriptors instead of OS threads.  Every answer carries an
`X-Replica-Id` debug header; /healthz reports the replica id, the
pinned snapshot, the delta tier's size and the event-loop lag.  Two
companions complete the plane:

* HORIZONTAL READ REPLICAS (service/router.py): N servers over one
  table — sharing the process byte-cache + SSD tiers — behind a
  consistent-hash router; `KvQueryClient` follows the router's
  /topology to talk to the owning replica directly;
* the HOT DELTA TIER (service/delta.py): a serving writer's unflushed
  rows merge into every /lookup newest-first (same tombstone/sequence
  semantics as the SST walk), so a freshly written key is readable in
  microseconds — before any flush or commit — and generations retire
  only once every replica's plan covers them.
"""

from __future__ import annotations

import http.client
import json
import threading
from typing import List, Optional

from paimon_tpu.lookup import LocalTableQuery
from paimon_tpu.options import CoreOptions
from paimon_tpu.service.admission import (
    AdmissionController, AdmissionRejected,
)
from paimon_tpu.service.async_server import (
    AsyncHttpServer, HttpRequest, HttpResponse,
)


def _encode_value(v):
    """JSON-safe encoding preserving types across the wire (datetime/
    date/time -> tagged ISO, Decimal -> tagged str, bytes -> tagged
    base64) so remote lookups return the same values as local ones."""
    import base64
    import datetime
    import decimal
    if isinstance(v, datetime.datetime):
        return {"__t": "dt", "v": v.isoformat()}
    if isinstance(v, datetime.date):
        return {"__t": "d", "v": v.isoformat()}
    if isinstance(v, datetime.time):
        return {"__t": "t", "v": v.isoformat()}
    if isinstance(v, decimal.Decimal):
        return {"__t": "dec", "v": str(v)}
    if isinstance(v, (bytes, bytearray)):
        return {"__t": "b", "v": base64.b64encode(v).decode()}
    if isinstance(v, list):
        return [_encode_value(x) for x in v]
    if isinstance(v, dict):
        return {k: _encode_value(x) for k, x in v.items()}
    return v


def _decode_value(v):
    import base64
    import datetime
    import decimal
    if isinstance(v, dict):
        tag = v.get("__t")
        if tag == "dt":
            return datetime.datetime.fromisoformat(v["v"])
        if tag == "d":
            return datetime.date.fromisoformat(v["v"])
        if tag == "t":
            return datetime.time.fromisoformat(v["v"])
        if tag == "dec":
            return decimal.Decimal(v["v"])
        if tag == "b":
            return base64.b64decode(v["v"])
        return {k: _decode_value(x) for k, x in v.items()}
    if isinstance(v, list):
        return [_decode_value(x) for x in v]
    return v

__all__ = ["KvQueryServer", "KvQueryClient", "ServiceManager",
           "ServiceBusyError"]

PRIMARY_KEY_LOOKUP = "primary-key-lookup"

from contextlib import nullcontext as _nullcontext  # noqa: E402

_NULLCTX = _nullcontext()


class ServiceBusyError(RuntimeError):
    """The service answered 429: admission queue full or byte budget
    exhausted within the queue timeout.  Retry with backoff."""


class ServiceManager:
    """Address registry in the table dir (reference ServiceManager)."""

    def __init__(self, file_io, table_path: str):
        self.file_io = file_io
        self.dir = f"{table_path.rstrip('/')}/service"

    def _path(self, service: str) -> str:
        return f"{self.dir}/{service}"

    def register(self, service: str, address: str):
        self.file_io.write_bytes(self._path(service),
                                 json.dumps([address]).encode(),
                                 overwrite=True)

    def unregister(self, service: str):
        self.file_io.delete_quietly(self._path(service))

    def addresses(self, service: str) -> List[str]:
        if not self.file_io.exists(self._path(service)):
            return []
        return json.loads(self.file_io.read_bytes(self._path(service)))


class KvQueryServer:
    def __init__(self, table, host: str = "127.0.0.1", port: int = 0,
                 replica_id: int = 0, delta=None):
        opts = table.options
        if opts.get(CoreOptions.SERVICE_CACHE_SHARED):
            table = self._join_shared_cache(table)
        self.table = table
        self.options = table.options
        self.replica_id = int(replica_id)
        # hot delta tier: unflushed serving-writer rows merged into
        # every /lookup (shared process-wide by table path, so N
        # in-process replicas and the serving writer see ONE tier)
        if delta is None and table.primary_keys and \
                opts.get(CoreOptions.SERVICE_DELTA_ENABLED):
            from paimon_tpu.service.delta import (
                delta_eligible, shared_delta_tier,
            )
            if delta_eligible(table):
                delta = shared_delta_tier(table)
        self._delta = delta
        # ONE LocalTableQuery shared by every /lookup (plan swaps
        # serialize; reads/builds/probes run concurrently across
        # handler threads).  Built lazily so non-pk tables can still
        # serve /scan and /changelog.
        self._query: Optional[LocalTableQuery] = None
        self._query_lock = threading.Lock()
        self.admission = AdmissionController(
            max_bytes=opts.get(CoreOptions.SERVICE_MAX_INFLIGHT_BYTES),
            tenant_max_bytes=opts.get(
                CoreOptions.SERVICE_TENANT_MAX_INFLIGHT_BYTES),
            queue_depth=opts.get(CoreOptions.SERVICE_QUEUE_DEPTH),
            queue_timeout_ms=opts.get(CoreOptions.SERVICE_QUEUE_TIMEOUT),
            table=table.name)
        self._scan_row_bytes = opts.get(CoreOptions.SERVICE_SCAN_ROW_BYTES)
        self._lookup_key_bytes = opts.get(
            CoreOptions.SERVICE_LOOKUP_KEY_BYTES)
        # tail tolerance: default end-to-end deadline (clients may
        # override per request with 'timeout_ms' / the
        # X-Request-Timeout-Ms header) + the brownout ladder
        self._request_timeout = opts.get(
            CoreOptions.SERVICE_REQUEST_TIMEOUT)
        from paimon_tpu.service.brownout import BrownoutController
        self.brownout = BrownoutController(self.admission, opts)
        # fleet observability: sync the process-global trace/flight
        # switches from this table's options (explicit keys win), tag
        # the trace spool with the replica id, and stand up the SLO
        # burn-rate evaluator every response feeds
        from paimon_tpu.obs import flight as _flight
        from paimon_tpu.obs import trace as _trace
        _trace.sync_from_options(opts)
        _flight.sync_from_options(opts)
        _trace.set_replica_id(f"r{self.replica_id}")
        from paimon_tpu.obs.slo import SloConfig, SloEvaluator
        self.slo = SloEvaluator(SloConfig.from_options(opts),
                                table=table.name)
        from paimon_tpu.metrics import (
            SERVICE_CHANGELOG_MS, SERVICE_CONNECTIONS,
            SERVICE_LOOKUP_CPU_MS, SERVICE_LOOKUP_KEYS,
            SERVICE_LOOKUP_MS, SERVICE_LOOP_LAG_MS,
            SERVICE_SCAN_CACHE_HITS, SERVICE_SCAN_CACHE_MISSES,
            SERVICE_SCAN_MS, global_registry,
        )
        g = global_registry().service_metrics(table.name)
        self._m_lookup_ms = g.histogram(SERVICE_LOOKUP_MS)
        self._m_scan_ms = g.histogram(SERVICE_SCAN_MS)
        self._m_changelog_ms = g.histogram(SERVICE_CHANGELOG_MS)
        self._m_lookup_keys = g.counter(SERVICE_LOOKUP_KEYS)
        # per-key handler CPU (thread_time): the honest denominator
        # behind qps headlines — wall latency can hide in IO waits,
        # CPU per key cannot
        self._m_lookup_cpu = g.histogram(SERVICE_LOOKUP_CPU_MS)
        # warm boot (service/warmboot.py): restore at query-engine
        # construction, persist on shutdown or explicit POST /warmboot
        from paimon_tpu.service import warmboot as _warmboot
        self._warmboot_dir = None
        if opts.get(CoreOptions.SERVICE_WARMBOOT_ENABLED):
            base = _warmboot.warmboot_dir(opts)
            if base:
                self._warmboot_dir = _warmboot.table_state_dir(
                    base, table)
        self.last_warm_restore: Optional[dict] = None
        # the event-loop engine (service/async_server.py): handlers
        # run on the bounded service.workers pool; the loop thread
        # owns every socket and pipelined keep-alive parse
        self.server = AsyncHttpServer(
            host, port, self._handle,
            workers=opts.get(CoreOptions.SERVICE_WORKERS),
            max_connections=opts.get(CoreOptions.SERVICE_MAX_CONNECTIONS),
            name=f"paimon-serve-r{self.replica_id}",
            lag_histogram=g.histogram(SERVICE_LOOP_LAG_MS),
            connections_gauge=g.gauge(SERVICE_CONNECTIONS))
        self.port = self.server.port
        self.address = f"http://{host}:{self.port}"
        self.services = ServiceManager(table.file_io, table.path)
        # per-consumer streaming changelog scans (/changelog): each
        # consumer id owns a DataTableStreamScan whose position only
        # advances when that consumer polls, plus a pending-rows
        # carryover so large batches stream out in bounded chunks.
        # LRU-bounded: a client cycling consumer ids cannot grow
        # server memory without bound (an evicted consumer restarts
        # from a fresh scan).  One lock serializes plan+read per
        # request — stream scans are stateful and the HTTP server is
        # threaded.
        from collections import OrderedDict
        self._streams = OrderedDict()
        self._streams_lock = threading.Lock()
        self.max_changelog_consumers = 256
        self.changelog_max_rows = 10_000
        # snapshot-keyed scan result cache: a bounded /scan is a PURE
        # function of (snapshot, limit, projection) — the same request
        # against the same snapshot merges the same runs to the same
        # rows, so serving plane scans pay the merge once per
        # snapshot, not once per request.  A commit changes the
        # snapshot id and therefore the key; LRU-bounded.  Disabled
        # under record-level expire: row visibility there changes
        # with the CLOCK, not the snapshot id, so the key would lie
        self._scan_cache = OrderedDict()
        self._scan_cache_lock = threading.Lock()
        self.max_scan_cache_entries = 64
        self._scan_cache_enabled = \
            not opts.record_level_expire_time_ms
        self._m_scan_cache_hits = g.counter(SERVICE_SCAN_CACHE_HITS)
        self._m_scan_cache_misses = g.counter(
            SERVICE_SCAN_CACHE_MISSES)

    @staticmethod
    def _join_shared_cache(table):
        """Rewrap the table over the process-wide shared byte-cache
        tier (whole-file + block-range), so every request this server
        — and every other server/table in the process — serves warms
        one bounded cache (tentpole 1: per-read scope -> process-wide
        shared tier)."""
        from paimon_tpu.fs.caching import (
            CachingFileIO, shared_cache_state, shared_disk_tier,
        )
        # grow the shared tier FIRST: a table already wrapped by
        # read.cache.range rides the shared state with whole-file
        # capacity 0 — the serving plane's whole-file tier must turn
        # on for it too, not only for unwrapped tables
        state = shared_cache_state(
            256 << 20,
            table.options.get(CoreOptions.READ_CACHE_RANGE_MAX_BYTES))
        disk_dir = table.options.get(CoreOptions.CACHE_DISK_DIR)
        if disk_dir:
            # the serving plane rides the host-SSD second tier too:
            # memory-LRU demotions land on disk and cold requests are
            # answered from SSD before the object store
            state.attach_disk(
                shared_disk_tier(disk_dir, table.options.get(
                    CoreOptions.CACHE_DISK_MAX_BYTES)),
                promote_hits=table.options.get(
                    CoreOptions.CACHE_DISK_PROMOTE_HITS))
        if isinstance(table.file_io, CachingFileIO):
            # already caching (shared state grown above if it rides
            # it; an explicitly-constructed private wrapper keeps its
            # own configuration)
            return table
        wrapped = CachingFileIO(table.file_io, state=state)
        return type(table)(wrapped, table.path, table.schema,
                           branch=table.branch)

    def query(self) -> LocalTableQuery:
        """The shared serving-side point-lookup engine (pk tables)."""
        with self._query_lock:
            if self._query is None:
                q = LocalTableQuery(
                    self.table,
                    refresh_interval_ms=self.options.get(
                        CoreOptions.SERVICE_LOOKUP_REFRESH_INTERVAL),
                    delta=self._delta)
                if self._warmboot_dir is not None:
                    # adopt persisted SSTs + plan state BEFORE the
                    # first lookup: a warm replica's first batch runs
                    # with reader_builds == 0 and no cold manifest walk
                    from paimon_tpu.service import warmboot
                    self.last_warm_restore = \
                        warmboot.restore_serving_state(
                            q, self._warmboot_dir)
                self._query = q
            return self._query

    def persist_warm_state(self) -> dict:
        """Persist the current serving state (built SSTs + plan-cache
        state) for warm boot; {"ssts": 0, ...} when warm boot is off
        or nothing is built yet."""
        with self._query_lock:
            q = self._query
        if q is None or self._warmboot_dir is None:
            return {"ssts": 0, "snapshot_id": None, "plan": False}
        from paimon_tpu.service import warmboot
        return warmboot.persist_serving_state(q, self._warmboot_dir)

    def new_serving_writer(self, commit_user: Optional[str] = None):
        """A writer whose rows are readable via /lookup IMMEDIATELY —
        before any flush or commit — through the hot delta tier
        (service/delta.py).  One serving writer per table: delta
        visibility assumes its per-bucket sequence numbers are the
        newest in flight."""
        if self._delta is None:
            from paimon_tpu.service.delta import delta_ineligible_reason
            raise ValueError(
                "delta tier unavailable: "
                + (delta_ineligible_reason(self.table)
                   or "service.delta.enabled=false"))
        from paimon_tpu.service.delta import ServingWriter
        return ServingWriter(self.table, self._delta,
                             commit_user=commit_user)

    def start(self) -> "KvQueryServer":
        self.server.start()
        self.services.register(PRIMARY_KEY_LOOKUP, self.address)
        return self

    def register_with_router(self, router_address: str) -> dict:
        """Join a (possibly cross-machine) router's hash ring: POST
        this replica's (id, address) to the router's /register.  The
        router health-checks us from then on; pair with a warm-boot
        restore for a joiner that serves its first lookup hot."""
        import http.client
        host, port = KvQueryClient._hostport(router_address)
        conn = http.client.HTTPConnection(host, port, timeout=30)
        try:
            conn.request(
                "POST", "/register",
                json.dumps({"id": self.replica_id,
                            "address": self.address}).encode(),
                {"Content-Type": "application/json"})
            resp = conn.getresponse()
            body = json.loads(resp.read() or b"{}")
            if resp.status != 200:
                raise RuntimeError(
                    f"router refused registration: {body}")
            return body
        finally:
            conn.close()

    def stop(self):
        self.services.unregister(PRIMARY_KEY_LOOKUP)
        self.shutdown()

    def shutdown(self):
        """Teardown minus the service-registry unregister (ReplicaSet
        replicas never registered — the router did): stop the engine,
        restore the process-wide degraded switch, drop lookup state."""
        self.server.stop()
        # the process-wide degraded switch must not outlive the server
        self.brownout.reset()
        # flush the trace spool/export (fleet merge must include a
        # replica's last serving spans even when it exits cleanly
        # between pipeline completion points)
        from paimon_tpu.obs.trace import maybe_export
        maybe_export()
        # persist BEFORE close drops the SST store: a restarting
        # replica finds this one's warm state on the shared SSD tier
        if self._warmboot_dir is not None:
            try:
                self.persist_warm_state()
            except Exception:  # lint-ok: swallow warm-state persist is advisory — a failed snapshot must not block shutdown; next boot is simply cold
                pass
        with self._query_lock:
            if self._query is not None:
                self._query.close()
                self._query = None

    # -- request dispatch (runs on the engine's worker pool) -----------------

    def _json_response(self, status: int, obj,
                       headers: Optional[dict] = None) -> HttpResponse:
        hdrs = {"X-Replica-Id": str(self.replica_id)}
        if headers:
            hdrs.update(headers)
        return HttpResponse(status, json.dumps(obj).encode(),
                            headers=hdrs)

    def _handle(self, req: HttpRequest) -> HttpResponse:
        if req.method == "GET":
            return self._handle_get(req)
        if req.method == "POST":
            return self._handle_post(req)
        return self._json_response(405, {"error": "method not allowed"})

    def _handle_get(self, req: HttpRequest) -> HttpResponse:
        """GET /metrics (Prometheus text exposition of the whole
        process registry, rendered from MetricRegistry.snapshot_rows —
        the same serialization the $metrics system table queries),
        GET /healthz (brownout + engine + delta introspection) and
        GET /stats (per-replica obs summary as JSON — what the router
        aggregates)."""
        if req.path == "/healthz":
            # tail-tolerance introspection: brownout rung, breaker
            # states, queue pressure, recent 429/504 rates — plus the
            # replica id, pinned snapshot, delta-tier size and
            # event-loop lag: the operator's one-glance view of HOW
            # degraded the plane currently is and WHO answered
            try:
                self.brownout.observe()
                return self._json_response(200, self.healthz())
            except Exception as e:      # noqa: BLE001
                return self._json_response(500, {"error": str(e)})
        if req.path == "/stats":
            try:
                return self._json_response(200, self.stats())
            except Exception as e:      # noqa: BLE001
                return self._json_response(500, {"error": str(e)})
        if req.path == "/slo":
            # burn rates + alert state NOW (also refreshes the `slo`
            # Prometheus gauges, so a scrape can't disagree)
            try:
                return self._json_response(200, self.slo.evaluate())
            except Exception as e:      # noqa: BLE001
                return self._json_response(500, {"error": str(e)})
        if req.path != "/metrics":
            return self._json_response(404, {"error": "not found"})
        try:
            from paimon_tpu.obs.export import render_prometheus
            return HttpResponse(
                200, render_prometheus().encode(),
                content_type="text/plain; version=0.0.4; charset=utf-8",
                headers={"X-Replica-Id": str(self.replica_id)})
        except Exception as e:      # noqa: BLE001
            return HttpResponse(500, str(e).encode(),
                                content_type="text/plain")

    def healthz(self) -> dict:
        """The /healthz body: the brownout controller's view plus the
        serving-engine vitals this replica owns."""
        body = self.brownout.healthz()
        with self._query_lock:
            snap = self._query.snapshot_id \
                if self._query is not None else None
        body.update({
            "replica_id": self.replica_id,
            "snapshot_id": snap,
            "delta": None if self._delta is None
            else self._delta.stats(),
            "event_loop": {
                "recent_lag_ms": round(self.server.recent_lag_ms, 3),
                "connections": self.server.connection_count,
            },
        })
        return body

    def stats(self) -> dict:
        """Per-replica obs-plane summary (request-latency histograms
        as percentiles) — the router's /healthz aggregation and the
        multi-replica bench read THIS instead of re-parsing the
        Prometheus text."""
        def h(hist):
            return {"count": hist.total_count,
                    "p50": round(hist.percentile(50), 4),
                    "p95": round(hist.percentile(95), 4),
                    "p99": round(hist.percentile(99), 4),
                    # trailing window samples: the router/bench pool
                    # these across replicas for a TRUE fleet
                    # percentile (per-replica p95s cannot be merged)
                    "window": [round(v, 4)
                               for v in hist.window_values()]}
        with self._query_lock:
            snap = self._query.snapshot_id \
                if self._query is not None else None
        from paimon_tpu.metrics import (
            LOOKUP_NATIVE_FALLBACKS, LOOKUP_NATIVE_PROBES,
            LOOKUP_READER_BUILDS, LOOKUP_READER_REUSES,
            global_registry,
        )
        lg = global_registry().lookup_metrics()
        return {"replica_id": self.replica_id,
                "snapshot_id": snap,
                "lookup_ms": h(self._m_lookup_ms),
                "scan_ms": h(self._m_scan_ms),
                "lookup_keys": self._m_lookup_keys.count,
                "lookup_cpu_per_key_ms": h(self._m_lookup_cpu),
                # process-global lookup-plane counters: the warm-boot
                # proof (reader_builds == 0) and the native-probe
                # health (fallbacks must not move in steady state)
                "lookup": {
                    "reader_builds":
                        lg.counter(LOOKUP_READER_BUILDS).count,
                    "reader_reuses":
                        lg.counter(LOOKUP_READER_REUSES).count,
                    "native_probes":
                        lg.counter(LOOKUP_NATIVE_PROBES).count,
                    "native_fallbacks":
                        lg.counter(LOOKUP_NATIVE_FALLBACKS).count,
                },
                "warm_restore": self.last_warm_restore,
                "delta": None if self._delta is None
                else self._delta.stats()}

    def _handle_post(self, req: HttpRequest) -> HttpResponse:
        if req.path == "/warmboot":
            # explicit persist (admin/bench): hard-link the built SSTs
            # + plan state onto the shared SSD tier NOW, so replicas
            # registered after this call boot warm
            try:
                return self._json_response(200,
                                           self.persist_warm_state())
            except Exception as e:      # noqa: BLE001
                return self._json_response(500, {"error": str(e)})
        if req.path == "/lookup":
            handle, timer = self._lookup, self._m_lookup_ms
        elif req.path == "/scan":
            handle, timer = self._scan, self._m_scan_ms
        elif req.path == "/changelog":
            handle, timer = self._changelog, self._m_changelog_ms
        else:
            return self._json_response(404, {"error": "not found"})
        try:
            body = json.loads(req.body or b"{}")
        except ValueError:
            return self._json_response(400, {"error": "invalid JSON"})
        import time as _time

        from paimon_tpu.utils.deadline import (
            DeadlineExceededError, deadline_scope,
        )
        # end-to-end deadline: client-supplied per request (body
        # 'timeout_ms' or X-Request-Timeout-Ms header) else
        # service.request.timeout; every blocking wait downstream
        # (admission queue, prefetch byte budget, retry sleeps, store
        # IO) honors it
        timeout_ms = body.get("timeout_ms")
        if timeout_ms is None:
            timeout_ms = req.headers.get("x-request-timeout-ms")
        if timeout_ms is None:
            timeout_ms = self._request_timeout
        # NOTE explicit None checks, not `or`: timeout_ms=0 is a real
        # (already-expired) deadline the caller asked for, not an
        # absent one
        if timeout_ms is not None:
            try:
                timeout_ms = float(timeout_ms)
            except (TypeError, ValueError):
                # malformed CLIENT input is a 400, not a 500
                return self._json_response(
                    400, {"error": f"invalid timeout_ms: "
                                   f"{timeout_ms!r}"})
        self.brownout.observe()
        t0 = _time.perf_counter()
        try:
            with deadline_scope(timeout_ms):
                out = handle(body)
            status, payload = 200, out
        except DeadlineExceededError as e:
            # the request's budget is spent: in-flight work for it was
            # cancelled/abandoned downstream; tell the caller the
            # truth with a 504
            status, payload = 504, {"error": str(e), "deadline": True}
        except AdmissionRejected as e:
            status, payload = 429, {"error": str(e), "busy": True}
        except Exception as e:      # noqa: BLE001
            status, payload = 500, {"error": str(e)}
        self.brownout.record_outcome(status)
        # every data-path response is an SLO event — INCLUDING sheds
        # and deadline misses; that is exactly what the availability
        # objective counts
        self.slo.observe(status, (_time.perf_counter() - t0) * 1000.0)
        if status not in (429, 504):
            # 429s spent their time in the admission queue and 504s
            # are deadline-bounded by construction —
            # admission_wait_ms / rejected / deadline_exceeded tell
            # those stories; folding them into the service-time
            # histograms would corrupt p95/p99
            timer.update((_time.perf_counter() - t0) * 1000.0)
        return self._json_response(status, payload)

    @staticmethod
    def _tenant(req) -> str:
        return str(req.get("tenant") or "default")

    @staticmethod
    def _priority(req) -> int:
        from paimon_tpu.service.admission import DEFAULT_PRIORITY
        try:
            return int(req.get("priority", DEFAULT_PRIORITY))
        except (TypeError, ValueError):
            return DEFAULT_PRIORITY

    def _lookup(self, req):
        import time as _time
        keys = req["keys"]
        est = max(1, len(keys)) * self._lookup_key_bytes
        # thread CPU, not wall: admission-queue and IO waits burn no
        # CPU on this thread, so the quotient is honest handler cost
        cpu0 = _time.thread_time()
        with self.admission.acquire(self._tenant(req), est,
                                    self._priority(req)):
            rows = self.query().lookup(
                [{k: _decode_value(v) for k, v in d.items()}
                 for d in keys],
                partition=tuple(_decode_value(v)
                                for v in req.get("partition") or ()))
        self._m_lookup_cpu.update(
            (_time.thread_time() - cpu0) * 1000.0 / max(1, len(keys)))
        self._m_lookup_keys.inc(len(keys))
        return {"rows": [None if r is None else
                         {k: _encode_value(x) for k, x in r.items()}
                         for r in rows]}

    def _changelog(self, req):
        """Streaming changelog poll (table/stream_scan.py): each
        consumer id resumes its own follow-up scan, so repeated polls
        stream snapshot-by-snapshot changes with row kinds
        (`_ROW_KIND`).  `caught_up` signals 'poll again later' — the
        stream never ends.  Serving is read-only on committed
        snapshots: it stays available while ingest or compaction are
        down (the daemon's degradation contract)."""
        consumer = str(req.get("consumer") or "default")
        limit = int(req.get("max_rows") or self.changelog_max_rows)
        est = max(1, limit) * self._scan_row_bytes
        with self.admission.acquire(self._tenant(req), est,
                                    self._priority(req)), \
                self._streams_lock:
            entry = self._streams.get(consumer)
            if entry is None:
                entry = {"scan": self.table
                         .new_read_builder().new_stream_scan(),
                         "pending": [], "plan": None}
                self._streams[consumer] = entry
                while len(self._streams) > \
                        self.max_changelog_consumers:
                    self._streams.popitem(last=False)
            self._streams.move_to_end(consumer)
            snapshot_id = None
            if not entry["pending"]:
                # a plan may be PARKED from a prior poll whose
                # materialization ticket 429'd — the stream scan has
                # already advanced past it, so it must be retried,
                # never re-planned (rows would be lost)
                plan = entry.get("plan") or entry["scan"].plan()
                if plan is None:
                    return {"rows": [], "snapshot_id": None,
                            "caught_up": True, "more": False}
                entry["plan"] = plan
                # the initial ticket only covers the poll;
                # materializing the snapshot delta is the real
                # allocation — charge its on-disk bytes before reading
                # (AdmissionRejected -> 429 with the plan parked for
                # the consumer's retry)
                delta = sum(f.file_size for s in plan.splits
                            for f in s.data_files)
                extra = max(0, delta - est)
                with self.admission.acquire(
                        self._tenant(req), extra,
                        self._priority(req)) if extra else _NULLCTX:
                    entry["pending"] = self.table \
                        .new_read_builder().new_read() \
                        .to_arrow(plan).to_pylist()
                snapshot_id = plan.snapshot_id
                entry["plan"] = None
            rows = entry["pending"][:limit]
            entry["pending"] = entry["pending"][limit:]
            more = bool(entry["pending"])
        return {"rows": [{k: _encode_value(v) for k, v in r.items()}
                         for r in rows],
                "snapshot_id": snapshot_id,
                "caught_up": False, "more": more}

    def _scan(self, req):
        """Bounded table scan through the pipelined split reader
        (parallel/scan_pipeline.py): splits stream through the
        prefetch pipeline and admission stops as soon as `limit` rows
        are buffered.  The admission charge is limit x
        service.scan.row-bytes-estimate — known BEFORE the plan, so
        even the manifest walk (heavy fan-in on large tables) runs
        under the ticket, never ahead of the byte budget."""
        limit = req.get("limit")
        limit = 10_000 if limit is None else int(limit)
        est = max(1, limit) * self._scan_row_bytes
        projection = tuple(req.get("projection") or ())
        with self.admission.acquire(self._tenant(req), est,
                                    self._priority(req)):
            rb = self.table.new_read_builder()
            if projection:
                rb = rb.with_projection(list(projection))
            rb = rb.with_limit(limit)
            plan = rb.new_scan().plan()
            # snapshot-keyed result cache: same snapshot + same args
            # = same rows (the plan above re-checks the snapshot, so
            # a commit invalidates by changing the key); bypassed
            # when row visibility is clock-dependent (record-level
            # expire)
            key = (plan.snapshot_id, limit, projection)
            if self._scan_cache_enabled:
                with self._scan_cache_lock:
                    cached = self._scan_cache.get(key)
                    if cached is not None:
                        self._scan_cache.move_to_end(key)
                if cached is not None:
                    self._m_scan_cache_hits.inc()
                    return cached
                self._m_scan_cache_misses.inc()
            t = rb.new_read().to_arrow(plan.splits)
        out = {"rows": [{k: _encode_value(v) for k, v in r.items()}
                        for r in t.to_pylist()],
               "snapshot_id": plan.snapshot_id}
        if self._scan_cache_enabled:
            with self._scan_cache_lock:
                self._scan_cache[key] = out
                while len(self._scan_cache) > \
                        self.max_scan_cache_entries:
                    self._scan_cache.popitem(last=False)
        return out


class KvQueryClient:
    """Remote point lookups; resolves the server address from the
    table's service registry (reference KvQueryClient + ServiceManager
    discovery).

    Holds persistent keep-alive connections (http.client) —
    reconnecting per request used to dominate sub-ms point-get latency
    — and transparently reopens one when the server or an idle timeout
    dropped the socket (one retry, then the error surfaces).
    Thread-safe: a lock serializes requests on the shared connections.

    FOLLOWS THE ROUTER (service/router.py): on first use the client
    probes GET /topology once; against a ReplicaRouter it builds the
    SAME consistent-hash ring and talks to this tenant's owning
    replica DIRECTLY (one connection per replica), skipping the proxy
    hop.  Against a plain replica the probe 404s and the classic
    single-address path runs.  `last_replica` surfaces which replica
    answered the most recent request (the X-Replica-Id debug header —
    what the torn-batch and coherence tests key on)."""

    def __init__(self, table=None, address: Optional[str] = None,
                 tenant: str = "default",
                 priority: Optional[int] = None,
                 timeout_ms: Optional[float] = None,
                 follow_topology: bool = True):
        if address is None:
            if table is None:
                raise ValueError("need a table or an address")
            addrs = ServiceManager(table.file_io, table.path) \
                .addresses(PRIMARY_KEY_LOOKUP)
            if not addrs:
                raise RuntimeError(
                    "no primary-key-lookup service registered")
            address = addrs[0]
        self.address = address.rstrip("/")
        self.tenant = tenant
        self.priority = priority          # None = server default (100)
        self.timeout_ms = timeout_ms      # per-request deadline -> 504
        self._follow = follow_topology
        self._ring = None                 # HashRing once discovered
        self._topology_checked = False
        self._conns: dict = {}            # address -> HTTPConnection
        self._lock = threading.Lock()
        self.reconnects = 0          # observable: stale-socket reopens
        self.last_replica: Optional[str] = None   # X-Replica-Id

    @staticmethod
    def _hostport(address: str):
        hostport = address.rstrip("/").split("://", 1)[-1]
        host, _, port = hostport.partition(":")
        return host, int(port) if port else 80

    @property
    def _conn(self):
        """The base-address connection (kept for introspection: tests
        kill its socket to exercise the stale-reconnect path)."""
        return self._conns.get(self.address)

    def close(self):
        with self._lock:
            for c in self._conns.values():
                c.close()
            self._conns.clear()

    def __enter__(self) -> "KvQueryClient":
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def _ensure_topology_locked(self, timeout: int):
        """One-shot router discovery: a ReplicaRouter answers
        /topology with the ring; a plain replica 404s (or refuses) and
        the classic single-address path stays."""
        if self._topology_checked or not self._follow:
            return
        self._topology_checked = True
        host, port = self._hostport(self.address)
        conn = http.client.HTTPConnection(host, port, timeout=timeout)
        try:
            conn.request("GET", "/topology")
            resp = conn.getresponse()
            data = resp.read()
            if resp.status != 200:
                return
            topo = json.loads(data)
            if not topo.get("router"):
                return
            from paimon_tpu.service.router import HashRing
            self._ring = HashRing(topo["replicas"],
                                  topo.get("virtual_nodes", 64))
        except (http.client.HTTPException, ConnectionError, OSError,
                ValueError, KeyError):
            pass          # no topology: single-address path
        finally:
            conn.close()

    def _target_address(self) -> str:
        if self._ring is None:
            return self.address
        return self._ring.pick(self.tenant)["address"].rstrip("/")

    def _post(self, endpoint: str, body: dict, timeout: int,
              idempotent: bool = True) -> dict:
        """POST json on the persistent connection to this tenant's
        target (the owning replica when a ring is known).  429 raises
        ServiceBusyError (admission control pushed back); other
        server-side errors ({"error"} bodies) surface as RuntimeError
        with the server's message.

        Stale-socket handling: a reused keep-alive socket that dies
        while SENDING the request reconnects and resends once (the
        server saw nothing).  A death AFTER the request was sent is
        ambiguous — the server may have processed it — so only
        `idempotent` endpoints (lookup/scan: re-execution is wasted
        work, never wrong) resend; /changelog advances per-consumer
        server state, so its ambiguous failures surface to the caller
        instead of silently skipping a batch."""
        body = dict(body)
        body.setdefault("tenant", self.tenant)
        if self.priority is not None:
            body.setdefault("priority", self.priority)
        if self.timeout_ms is not None:
            body.setdefault("timeout_ms", self.timeout_ms)
        payload = json.dumps(body).encode()
        headers = {"Content-Type": "application/json"}
        from paimon_tpu.obs.trace import (
            STAGE_CLIENT_REQUEST, inject_headers, span,
        )
        # the client-side hop span: inject_headers mints the 128-bit
        # trace id (first hop) and stamps X-Trace-Id/X-Parent-Span so
        # the server's serve.request span records this one as its
        # remote parent — the merged fleet trace draws the arrow
        with span(STAGE_CLIENT_REQUEST, cat="serve",
                  endpoint=endpoint):
            inject_headers(headers)
            return self._post_conn(endpoint, payload, headers, timeout,
                                   idempotent)

    def _post_conn(self, endpoint: str, payload: bytes, headers: dict,
                   timeout: int, idempotent: bool) -> dict:
        with self._lock:
            self._ensure_topology_locked(timeout)
            address = self._target_address()
            host, port = self._hostport(address)
            for attempt in (0, 1):
                conn = self._conns.get(address)
                fresh = conn is None
                if fresh:
                    conn = http.client.HTTPConnection(
                        host, port, timeout=timeout)
                sent = False
                try:
                    if not fresh:
                        conn.timeout = timeout
                        if conn.sock is not None:
                            conn.sock.settimeout(timeout)
                    conn.request("POST", f"/{endpoint}", payload,
                                 headers)
                    sent = True
                    resp = conn.getresponse()
                    data = resp.read()
                    status = resp.status
                    replica = resp.getheader("X-Replica-Id")
                # lint-ok: fault-taxonomy stale keep-alive reconnect,
                # deliberately narrower than the store ladder: exactly
                # one resend, only for idempotent work on a reused
                # socket, never on timeout (see the guard below)
                except (http.client.HTTPException, ConnectionError,
                        BrokenPipeError, OSError) as e:
                    conn.close()
                    self._conns.pop(address, None)
                    # a FRESH connection that fails is a real error;
                    # only a reused socket gets the stale-retry, and
                    # only when resending cannot double-execute
                    # non-idempotent server work.  A TIMEOUT is not a
                    # stale socket: the server is still processing —
                    # resending would double both the work and the
                    # effective wait exactly when it is saturated
                    if fresh or attempt or isinstance(e, TimeoutError) \
                            or (sent and not idempotent):
                        raise RuntimeError(
                            f"{endpoint} failed: {e}") from e
                    self.reconnects += 1
                    continue
                self._conns[address] = conn
                if replica is not None:
                    self.last_replica = replica
                if status == 200:
                    return json.loads(data)
                try:
                    detail = json.loads(data).get("error", "")
                except ValueError:
                    detail = data.decode(errors="replace")
                if status == 429:
                    raise ServiceBusyError(
                        f"{endpoint} rejected: {detail}")
                if status == 504:
                    from paimon_tpu.utils.deadline import (
                        DeadlineExceededError,
                    )
                    raise DeadlineExceededError(
                        f"{endpoint} timed out server-side: {detail}")
                raise RuntimeError(f"{endpoint} failed: {detail}")

    def healthz(self) -> dict:
        """GET /healthz: brownout rung, breaker states, queue depth
        and recent 429/504 rates (one-shot connection — health checks
        must not contend on the request socket).  Against a router
        this is the AGGREGATED fleet health."""
        host, port = self._hostport(self.address)
        conn = http.client.HTTPConnection(host, port, timeout=10)
        try:
            conn.request("GET", "/healthz")
            resp = conn.getresponse()
            data = resp.read()
            if resp.status != 200:
                raise RuntimeError(
                    f"healthz failed: {resp.status} "
                    f"{data.decode(errors='replace')}")
            return json.loads(data)
        finally:
            conn.close()

    def slo(self) -> dict:
        """GET /slo: multi-window burn rates + alert state for the
        replica's declared objectives (one-shot connection, like
        healthz).  Against a router this is the fleet-wide aggregate
        (worst replica burn; alert if any replica alerts)."""
        host, port = self._hostport(self.address)
        conn = http.client.HTTPConnection(host, port, timeout=10)
        try:
            conn.request("GET", "/slo")
            resp = conn.getresponse()
            data = resp.read()
            if resp.status != 200:
                raise RuntimeError(
                    f"slo failed: {resp.status} "
                    f"{data.decode(errors='replace')}")
            return json.loads(data)
        finally:
            conn.close()

    def lookup(self, keys: List[dict],
               partition: tuple = ()) -> List[Optional[dict]]:
        payload = self._post(
            "lookup",
            {"keys": [{k: _encode_value(v) for k, v in d.items()}
                      for d in keys],
             "partition": [_encode_value(v) for v in partition]},
            timeout=30)
        return [None if r is None else
                {k: _decode_value(v) for k, v in r.items()}
                for r in payload["rows"]]

    def lookup_row(self, key: dict,
                   partition: tuple = ()) -> Optional[dict]:
        return self.lookup([key], partition)[0]

    def scan(self, projection: Optional[List[str]] = None,
             limit: int = 10_000) -> List[dict]:
        """Bounded remote scan (served by the pipelined reader)."""
        payload = self._post("scan", {"projection": projection,
                                      "limit": limit}, timeout=60)
        return [{k: _decode_value(v) for k, v in r.items()}
                for r in payload["rows"]]

    def changelog(self, consumer: str = "default",
                  max_rows: Optional[int] = None) -> dict:
        """Poll the next changelog batch for `consumer` (rows carry
        `_ROW_KIND`); {"caught_up": True} means poll again later, and
        {"more": True} means the current snapshot has further chunks —
        poll immediately (large batches stream out bounded;
        `snapshot_id` is reported on a chunk's first page only)."""
        payload = self._post("changelog",
                             {"consumer": consumer,
                              "max_rows": max_rows}, timeout=60,
                             idempotent=False)
        payload["rows"] = [{k: _decode_value(v) for k, v in r.items()}
                           for r in payload["rows"]]
        return payload
