"""KV query service over LocalTableQuery.

reference: paimon-service/.../KvQueryServer.java + KvQueryClient.java +
ServiceManager.java ('primary-key-lookup' address files under
`<table>/service/`). Powers remote lookup joins
(PrimaryKeyPartialLookupTable remote mode).
"""

from __future__ import annotations

import json
import threading
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional

from paimon_tpu.lookup import LocalTableQuery


def _encode_value(v):
    """JSON-safe encoding preserving types across the wire (datetime/
    date/time -> tagged ISO, Decimal -> tagged str, bytes -> tagged
    base64) so remote lookups return the same values as local ones."""
    import base64
    import datetime
    import decimal
    if isinstance(v, datetime.datetime):
        return {"__t": "dt", "v": v.isoformat()}
    if isinstance(v, datetime.date):
        return {"__t": "d", "v": v.isoformat()}
    if isinstance(v, datetime.time):
        return {"__t": "t", "v": v.isoformat()}
    if isinstance(v, decimal.Decimal):
        return {"__t": "dec", "v": str(v)}
    if isinstance(v, (bytes, bytearray)):
        return {"__t": "b", "v": base64.b64encode(v).decode()}
    if isinstance(v, list):
        return [_encode_value(x) for x in v]
    if isinstance(v, dict):
        return {k: _encode_value(x) for k, x in v.items()}
    return v


def _decode_value(v):
    import base64
    import datetime
    import decimal
    if isinstance(v, dict):
        tag = v.get("__t")
        if tag == "dt":
            return datetime.datetime.fromisoformat(v["v"])
        if tag == "d":
            return datetime.date.fromisoformat(v["v"])
        if tag == "t":
            return datetime.time.fromisoformat(v["v"])
        if tag == "dec":
            return decimal.Decimal(v["v"])
        if tag == "b":
            return base64.b64decode(v["v"])
        return {k: _decode_value(x) for k, x in v.items()}
    if isinstance(v, list):
        return [_decode_value(x) for x in v]
    return v

__all__ = ["KvQueryServer", "KvQueryClient", "ServiceManager"]

PRIMARY_KEY_LOOKUP = "primary-key-lookup"


class ServiceManager:
    """Address registry in the table dir (reference ServiceManager)."""

    def __init__(self, file_io, table_path: str):
        self.file_io = file_io
        self.dir = f"{table_path.rstrip('/')}/service"

    def _path(self, service: str) -> str:
        return f"{self.dir}/{service}"

    def register(self, service: str, address: str):
        self.file_io.write_bytes(self._path(service),
                                 json.dumps([address]).encode(),
                                 overwrite=True)

    def unregister(self, service: str):
        self.file_io.delete_quietly(self._path(service))

    def addresses(self, service: str) -> List[str]:
        if not self.file_io.exists(self._path(service)):
            return []
        return json.loads(self.file_io.read_bytes(self._path(service)))


class KvQueryServer:
    def __init__(self, table, host: str = "127.0.0.1", port: int = 0):
        self.table = table
        self.query = LocalTableQuery(table)
        handler = self._make_handler()
        self.httpd = ThreadingHTTPServer((host, port), handler)
        self.port = self.httpd.server_address[1]
        self.address = f"http://{host}:{self.port}"
        self.services = ServiceManager(table.file_io, table.path)
        self._thread: Optional[threading.Thread] = None
        # per-consumer streaming changelog scans (/changelog): each
        # consumer id owns a DataTableStreamScan whose position only
        # advances when that consumer polls, plus a pending-rows
        # carryover so large batches stream out in bounded chunks.
        # LRU-bounded: a client cycling consumer ids cannot grow
        # server memory without bound (an evicted consumer restarts
        # from a fresh scan).  One lock serializes plan+read per
        # request — stream scans are stateful and the HTTP server is
        # threaded.
        from collections import OrderedDict
        self._streams = OrderedDict()
        self._streams_lock = threading.Lock()
        self.max_changelog_consumers = 256
        self.changelog_max_rows = 10_000

    def start(self) -> "KvQueryServer":
        from paimon_tpu.parallel.executors import spawn_thread
        self._thread = spawn_thread(self.httpd.serve_forever,
                                    name="paimon-query-server")
        self.services.register(PRIMARY_KEY_LOOKUP, self.address)
        return self

    def stop(self):
        self.services.unregister(PRIMARY_KEY_LOOKUP)
        self.httpd.shutdown()
        self.httpd.server_close()

    def _make_handler(self):
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                """Prometheus scrape endpoint: the whole process
                registry (scan/write/compaction/commit groups + stage
                latency histograms) in text exposition 0.0.4, rendered
                from MetricRegistry.snapshot_rows — the same
                serialization the $metrics system table queries."""
                if self.path != "/metrics":
                    self.send_error(404)
                    return
                try:
                    from paimon_tpu.obs.export import render_prometheus
                    body = render_prometheus().encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type",
                        "text/plain; version=0.0.4; charset=utf-8")
                except Exception as e:      # noqa: BLE001
                    body = str(e).encode()
                    self.send_response(500)
                    self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                if self.path == "/lookup":
                    handle = self._lookup
                elif self.path == "/scan":
                    handle = self._scan
                elif self.path == "/changelog":
                    handle = self._changelog
                else:
                    self.send_error(404)
                    return
                n = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(n))
                try:
                    body = json.dumps(handle(req)).encode()
                    self.send_response(200)
                except Exception as e:      # noqa: BLE001
                    body = json.dumps({"error": str(e)}).encode()
                    self.send_response(500)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _lookup(self, req):
                rows = server.query.lookup(
                    req["keys"],
                    partition=tuple(req.get("partition") or ()))
                return {"rows": [None if r is None else
                                 {k: _encode_value(x)
                                  for k, x in r.items()}
                                 for r in rows]}

            def _changelog(self, req):
                """Streaming changelog poll (table/stream_scan.py):
                each consumer id resumes its own follow-up scan, so
                repeated polls stream snapshot-by-snapshot changes with
                row kinds (`_ROW_KIND`).  `caught_up` signals 'poll
                again later' — the stream never ends.  Serving is
                read-only on committed snapshots: it stays available
                while ingest or compaction are down (the daemon's
                degradation contract)."""
                consumer = str(req.get("consumer") or "default")
                limit = int(req.get("max_rows")
                            or server.changelog_max_rows)
                with server._streams_lock:
                    entry = server._streams.get(consumer)
                    if entry is None:
                        entry = {"scan": server.table
                                 .new_read_builder().new_stream_scan(),
                                 "pending": []}
                        server._streams[consumer] = entry
                        while len(server._streams) > \
                                server.max_changelog_consumers:
                            server._streams.popitem(last=False)
                    server._streams.move_to_end(consumer)
                    snapshot_id = None
                    if not entry["pending"]:
                        plan = entry["scan"].plan()
                        if plan is None:
                            return {"rows": [], "snapshot_id": None,
                                    "caught_up": True, "more": False}
                        snapshot_id = plan.snapshot_id
                        entry["pending"] = server.table \
                            .new_read_builder().new_read() \
                            .to_arrow(plan).to_pylist()
                    rows = entry["pending"][:limit]
                    entry["pending"] = entry["pending"][limit:]
                    more = bool(entry["pending"])
                return {"rows": [{k: _encode_value(v)
                                  for k, v in r.items()}
                                 for r in rows],
                        "snapshot_id": snapshot_id,
                        "caught_up": False, "more": more}

            def _scan(self, req):
                """Bounded table scan through the pipelined split
                reader (parallel/scan_pipeline.py): splits stream
                through the prefetch pipeline and admission stops as
                soon as `limit` rows are buffered."""
                limit = req.get("limit")
                limit = 10_000 if limit is None else int(limit)
                rb = server.table.new_read_builder()
                if req.get("projection"):
                    rb = rb.with_projection(list(req["projection"]))
                rb = rb.with_limit(limit)
                plan = rb.new_scan().plan()
                t = rb.new_read().to_arrow(plan.splits)
                return {"rows": [{k: _encode_value(v)
                                  for k, v in r.items()}
                                 for r in t.to_pylist()],
                        "snapshot_id": plan.snapshot_id}

        return Handler


class KvQueryClient:
    """Remote point lookups; resolves the server address from the
    table's service registry (reference KvQueryClient + ServiceManager
    discovery)."""

    def __init__(self, table=None, address: Optional[str] = None):
        if address is None:
            if table is None:
                raise ValueError("need a table or an address")
            addrs = ServiceManager(table.file_io, table.path) \
                .addresses(PRIMARY_KEY_LOOKUP)
            if not addrs:
                raise RuntimeError(
                    "no primary-key-lookup service registered")
            address = addrs[0]
        self.address = address.rstrip("/")

    def _post(self, endpoint: str, body: dict, timeout: int) -> dict:
        """POST json; server-side errors (HTTP 500 with an {"error"}
        body) surface as RuntimeError with the server's message —
        urlopen raises HTTPError before the body would be parsed."""
        req = urllib.request.Request(
            f"{self.address}/{endpoint}",
            data=json.dumps(body).encode(), method="POST")
        req.add_header("Content-Type", "application/json")
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                return json.loads(resp.read())
        except urllib.error.HTTPError as e:
            try:
                detail = json.loads(e.read()).get("error", str(e))
            except ValueError:
                detail = str(e)
            raise RuntimeError(
                f"{endpoint} failed: {detail}") from e

    def lookup(self, keys: List[dict],
               partition: tuple = ()) -> List[Optional[dict]]:
        payload = self._post("lookup",
                             {"keys": keys,
                              "partition": list(partition)}, timeout=30)
        return [None if r is None else
                {k: _decode_value(v) for k, v in r.items()}
                for r in payload["rows"]]

    def lookup_row(self, key: dict,
                   partition: tuple = ()) -> Optional[dict]:
        return self.lookup([key], partition)[0]

    def scan(self, projection: Optional[List[str]] = None,
             limit: int = 10_000) -> List[dict]:
        """Bounded remote scan (served by the pipelined reader)."""
        payload = self._post("scan", {"projection": projection,
                                      "limit": limit}, timeout=60)
        return [{k: _decode_value(v) for k, v in r.items()}
                for r in payload["rows"]]

    def changelog(self, consumer: str = "default",
                  max_rows: Optional[int] = None) -> dict:
        """Poll the next changelog batch for `consumer` (rows carry
        `_ROW_KIND`); {"caught_up": True} means poll again later, and
        {"more": True} means the current snapshot has further chunks —
        poll immediately (large batches stream out bounded;
        `snapshot_id` is reported on a chunk's first page only)."""
        payload = self._post("changelog",
                             {"consumer": consumer,
                              "max_rows": max_rows}, timeout=60)
        payload["rows"] = [{k: _decode_value(v) for k, v in r.items()}
                           for r in payload["rows"]]
        return payload
