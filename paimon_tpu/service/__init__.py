"""Query service: remote point lookups.

reference: paimon-service/ (KvQueryServer/KvServerHandler over a Netty
binary protocol, ServiceManager registering 'primary-key-lookup'
addresses in the table directory, KvQueryClient). The transport here is
HTTP+JSON over the same LocalTableQuery engine — the service plane is
the capability, not the wire bytes.
"""

from paimon_tpu.service.admission import (  # noqa: F401
    AdmissionController, AdmissionRejected,
)
from paimon_tpu.service.delta import (  # noqa: F401
    DeltaTier, ServingWriter,
)
from paimon_tpu.service.query_service import (  # noqa: F401
    KvQueryClient, KvQueryServer, ServiceBusyError, ServiceManager,
)
from paimon_tpu.service.router import (  # noqa: F401
    ReplicaRouter, ReplicaSet,
)
from paimon_tpu.service.stream_daemon import (  # noqa: F401
    StreamDaemon, checkpoint_once, recover_checkpoint,
)
