"""Brownout: graceful degradation for the query-serving plane.

When the store is sick (circuit breakers open) or the service is
saturated (admission queue filling, 504/429s climbing), failing ALL
traffic is the worst answer.  This controller climbs a small, fully
observable degradation ladder instead:

    rung 0  normal      full prefetch, hedging as configured
    rung 1  degrade     hedging disabled process-wide + scan prefetch
                        windows shrunk (fs/resilience.set_degraded):
                        shed our own speculative store load first
    rung 2  shed        rung 1 + lowest-priority requests rejected
                        immediately with HTTP 429
                        (AdmissionController.set_shed_below)

Signals, recomputed on every observe() (each request) with an
injectable clock:

* any breaker open        (fs/resilience.breaker_states)
* queue pressure          (admission.queued / queue_depth >=
                           service.brownout.queue-ratio)
* recent failure rate     (429s + 504s in the trailing window)

The rung is the COUNT of firing signals (capped at 2) — one bad sign
degrades, two shed.  Once climbed, a rung holds for
`service.brownout.hold-ms` before it may step back down (hysteresis:
the boundary between shed and un-shed must not flap at request rate).
Everything lands on /healthz (query_service) and the `resilience`
metric group (`brownout_level` gauge, `brownout_sheds` counter).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, Optional

from paimon_tpu.options import CoreOptions

__all__ = ["BrownoutController", "RateWindow"]


class RateWindow:
    """Events-per-second over a trailing window (injectable clock);
    O(1) amortized — old timestamps evict on record/rate."""

    def __init__(self, window_s: float = 10.0,
                 clock: Callable[[], float] = time.monotonic):
        self.window_s = window_s
        self._clock = clock
        self._events: deque = deque()
        self._lock = threading.Lock()

    def record(self):
        now = self._clock()
        with self._lock:
            self._events.append(now)
            self._trim(now)

    def _trim(self, now: float):
        horizon = now - self.window_s
        while self._events and self._events[0] < horizon:
            self._events.popleft()

    def rate_per_s(self) -> float:
        now = self._clock()
        with self._lock:
            self._trim(now)
            return len(self._events) / self.window_s


class BrownoutController:
    """One per KvQueryServer; owns the process 'degraded' switch and
    the admission shed threshold while active."""

    # recent 429+504 rate that counts as a pressure signal (per
    # second over the trailing window; saturation shows up here long
    # before averages move)
    FAILURE_RATE_PER_S = 1.0

    def __init__(self, admission, options: CoreOptions, *,
                 clock: Callable[[], float] = time.monotonic):
        self.admission = admission
        self.enabled = options.get(CoreOptions.SERVICE_BROWNOUT_ENABLED)
        self.queue_ratio = options.get(
            CoreOptions.SERVICE_BROWNOUT_QUEUE_RATIO)
        self.shed_priority = options.get(
            CoreOptions.SERVICE_BROWNOUT_SHED_PRIORITY)
        self.hold_ms = options.get(CoreOptions.SERVICE_BROWNOUT_HOLD_MS)
        self._clock = clock
        self._lock = threading.Lock()
        self._level = 0
        self._held_until = 0.0
        self.rejected = RateWindow(clock=clock)     # 429s
        self.timeouts = RateWindow(clock=clock)     # 504s
        from paimon_tpu.metrics import (
            RESILIENCE_BROWNOUT_LEVEL, global_registry,
        )
        self._g_level = global_registry().resilience_metrics() \
            .gauge(RESILIENCE_BROWNOUT_LEVEL)
        self._g_level.set(0)

    @property
    def level(self) -> int:
        return self._level

    def record_outcome(self, status: int):
        """Feed one finished request's HTTP status into the failure-
        rate signal (called by the server for every response)."""
        if status == 429:
            self.rejected.record()
            from paimon_tpu.obs.flight import EV_HTTP_429, record
            record(EV_HTTP_429)
        elif status == 504:
            self.timeouts.record()
            from paimon_tpu.obs.flight import EV_HTTP_504, record
            record(EV_HTTP_504)

    def signals(self) -> Dict[str, object]:
        """The three pressure signals, as /healthz reports them."""
        from paimon_tpu.fs.resilience import breaker_states
        states = breaker_states()
        depth = self.admission.queued
        cap = max(1, self.admission.queue_depth)
        fail_rate = self.rejected.rate_per_s() + \
            self.timeouts.rate_per_s()
        return {
            "breakers_open": any(s != "closed" for s in states.values()),
            "breaker_states": states,
            "queue_ratio": depth / cap,
            "queue_pressure": depth / cap >= self.queue_ratio,
            "failure_rate_per_s": fail_rate,
            "failure_pressure": fail_rate >= self.FAILURE_RATE_PER_S,
        }

    def observe(self) -> int:
        """Recompute the rung and apply its actions; returns the
        level.  Cheap enough to call per request."""
        if not self.enabled:
            return 0
        sig = self.signals()
        target = min(2, int(sig["breakers_open"])
                     + int(sig["queue_pressure"])
                     + int(sig["failure_pressure"]))
        with self._lock:
            now = self._clock()
            if target > self._level:
                self._apply_locked(target, now)
            elif target < self._level and now >= self._held_until:
                self._apply_locked(target, now)
            return self._level

    def _apply_locked(self, level: int, now: float):
        from paimon_tpu.fs.resilience import set_degraded_for
        from paimon_tpu.obs.flight import EV_BROWNOUT, record
        if level != self._level:
            # flight-recorder: rung transitions are exactly the
            # "what changed right before it broke" an operator wants
            record(EV_BROWNOUT, frm=self._level, to=level)
        self._level = level
        self._held_until = now + self.hold_ms / 1000.0
        self._g_level.set(level)
        # per-SOURCE: several servers in one process each vote; the
        # process degrades while any of them is browned out
        set_degraded_for(self, level >= 1)
        self.admission.set_shed_below(
            self.shed_priority if level >= 2 else 0)

    def reset(self):
        """Restore rung 0 unconditionally (server shutdown: the
        process-wide degraded switch must not outlive the server that
        set it)."""
        with self._lock:
            self._apply_locked(0, self._clock())
            self._held_until = 0.0

    def healthz(self) -> Dict[str, object]:
        """The /healthz body: brownout rung, signals, admission
        pressure and hedging state in one place."""
        sig = self.signals()
        return {
            "status": "ok" if self._level == 0 else "brownout",
            "brownout_level": self._level,
            "breakers": sig["breaker_states"],
            "queue_depth": self.admission.queued,
            "queue_capacity": self.admission.queue_depth,
            "inflight_bytes": self.admission.inflight_bytes,
            "recent_429_per_s": self.rejected.rate_per_s(),
            "recent_504_per_s": self.timeouts.rate_per_s(),
            "hedging_enabled": _hedging_on(),
            "shedding_below_priority":
                self.shed_priority if self._level >= 2 else None,
        }


def _hedging_on() -> bool:
    from paimon_tpu.fs.resilience import hedging_allowed
    return hedging_allowed()
