"""Hot in-memory delta tier: serve unflushed writes in microseconds.

The delta/main split of "Fast Updates on Read-Optimized Databases
Using Multi-Core CPUs" (arxiv 1109.6885) applied to the serving
plane: the LSM ("main") is read-optimized and advances only at
flush+commit+snapshot cadence, so a freshly written key is otherwise
invisible until a whole commit lands.  This module keeps the serving
writer's UNFLUSHED rows in a small in-memory index ("delta") that
`LocalTableQuery` merges into every point lookup NEWEST-FIRST, with
the same tombstone/sequence semantics as the SST walk — a write is
readable before any flush or commit, and becomes byte-identical to
the post-flush answer once the snapshot covers it.

Shape:

* the tier holds GENERATIONS: one OPEN generation receives writes
  (per-(partition,bucket) maps of key tuple -> newest (seq, kind,
  row)); `seal(snapshot_id)` moves it — atomically, the generation
  dict itself is never copied — into the SEALED list when the commit
  that durably published those rows succeeds;
* a lookup batch captures an immutable VIEW (open + sealed refs)
  BEFORE it captures its plan; probes walk open-then-sealed newest
  first, so the newest write for a key always wins and a DELETE
  tombstone answers None without touching the LSM;
* sealed generations retire only once EVERY attached reader's plan
  has advanced past their snapshot (min-floor pruning): replica A
  refreshing to snapshot S must not un-publish rows replica B still
  serves from plan S-1.  A captured view keeps pruned generations
  alive for its own batch — pruning swaps lists, never mutates them;
* eligibility is exactly the LSM fast path's (deduplicate merge, no
  sequence.field / record-level expire / DVs / cross-partition /
  local-merge, fixed buckets): those are the configurations where
  "newest write wins" IS the merge, so overlaying the delta cannot
  change semantics.  One serving writer per table — delta visibility
  assumes its per-bucket sequence numbers are the newest in flight.

`service.delta.max-bytes` is a SOFT bound: crossing it counts
`delta_overflow` (the "commit now" signal).  Uncommitted rows are
never dropped — dropping them would un-publish an acknowledged
write; an abandoned writer (`close()` without commit) discards its
open generation instead, the same contract as dropping an
uncommitted write buffer.
"""

from __future__ import annotations

import json
import threading
from typing import Dict, List, Optional, Tuple

from paimon_tpu.types import RowKind

__all__ = ["DeltaTier", "DeltaView", "ServingWriter",
           "delta_eligible", "delta_ineligible_reason",
           "shared_delta_tier", "reset_delta_tiers"]

_MISS = object()          # probe sentinel: key not in the delta


def delta_ineligible_reason(table) -> Optional[str]:
    """Why this table cannot ride the delta tier (None = eligible).
    The gate mirrors LocalTableQuery._fast_path_ok plus the write-side
    configurations that defer or re-route rows."""
    from paimon_tpu.options import CoreOptions, MergeEngine
    opts = table.options
    if not table.primary_keys:
        return "delta tier requires a primary-key table"
    if opts.merge_engine != MergeEngine.DEDUPLICATE:
        return (f"delta tier requires deduplicate merge semantics "
                f"(merge-engine={opts.merge_engine})")
    if opts.sequence_field:
        return "sequence.field orders rows by value, not write time"
    if opts.record_level_expire_time_ms:
        return "record-level expire changes visibility over time"
    if opts.get(CoreOptions.DELETION_VECTORS_ENABLED):
        return "deletion-vectors maintenance rewrites row visibility"
    if opts.bucket < 1:
        return (f"delta tier requires fixed buckets "
                f"(bucket={opts.bucket})")
    if table.schema.cross_partition_update():
        return "cross-partition upsert re-routes rows at flush time"
    if opts.get(CoreOptions.LOCAL_MERGE_BUFFER_SIZE):
        return "local-merge buffers rows past the write() hook"
    return None


def delta_eligible(table) -> bool:
    return delta_ineligible_reason(table) is None


# -- process-wide tier registry (replicas + the serving writer over one
#    table must see ONE tier) ------------------------------------------------

_TIERS: Dict[str, "DeltaTier"] = {}
_TIERS_LOCK = threading.Lock()


def shared_delta_tier(table) -> "DeltaTier":
    """One DeltaTier per table path per process: every in-process
    replica server and the serving writer share it (the cross-replica
    analog of fs/caching.shared_cache_state)."""
    key = str(table.path)
    with _TIERS_LOCK:
        tier = _TIERS.get(key)
        if tier is None:
            tier = DeltaTier(table)
            _TIERS[key] = tier
        return tier


def reset_delta_tiers():
    """Test hook: drop every registered tier."""
    with _TIERS_LOCK:
        _TIERS.clear()


class DeltaView:
    """Immutable capture of the tier for ONE lookup batch: the open
    generation ref plus the sealed list ref at capture time.  Pruning
    replaces lists, never mutates them, so a captured view keeps its
    generations alive for the whole batch."""

    __slots__ = ("_gens",)

    def __init__(self, gens: Tuple[dict, ...]):
        self._gens = gens          # newest first

    @property
    def empty(self) -> bool:
        return not any(self._gens)

    def touches(self, pkey: str, buckets) -> bool:
        """Whether ANY of the batch's (pkey, bucket) groups exists in
        any generation — the cheap gate before a lookup batch pays
        for per-key materialization and probing."""
        for gen in self._gens:
            if not gen:
                continue
            for b in buckets:
                if (pkey, b) in gen:
                    return True
        return False

    def probe(self, pkey: str, bucket: int, key_tuple: Tuple):
        """Newest delta entry for the key: the stored row dict, None
        for a tombstone, or the _MISS sentinel (fall through to the
        LSM walk)."""
        gkey = (pkey, bucket)
        for gen in self._gens:
            m = gen.get(gkey)
            if m is None:
                continue
            hit = m.get(key_tuple)
            if hit is None:
                continue
            _seq, kind, row = hit
            if kind in (RowKind.DELETE, RowKind.UPDATE_BEFORE):
                return None        # tombstone: the key is deleted
            return row
        return _MISS

    @staticmethod
    def is_miss(result) -> bool:
        return result is _MISS


class DeltaTier:
    """The shared per-table delta index (see module docstring)."""

    def __init__(self, table):
        from paimon_tpu.metrics import (
            SERVICE_DELTA_BYTES, SERVICE_DELTA_OVERFLOWS,
            SERVICE_DELTA_ROWS, global_registry,
        )
        from paimon_tpu.options import CoreOptions
        self.pk = table.schema.trimmed_primary_keys()
        self.max_bytes = table.options.get(
            CoreOptions.SERVICE_DELTA_MAX_BYTES)
        self._lock = threading.Lock()
        # open generation: {(pkey, bucket): {key_tuple: (seq, kind,
        # row)}}; sealed: ((snapshot_id, gen, rows, bytes), ...)
        # oldest first — both REPLACED, never mutated, on seal/prune
        self._open: dict = {}
        self._open_rows = 0
        self._open_bytes = 0
        self._sealed: Tuple[Tuple[int, dict, int, int], ...] = ()
        # reader -> last served plan snapshot (None = never loaded);
        # pruning floors on the min over loaded readers
        self._readers: Dict[int, Tuple[object, Optional[int]]] = {}
        g = global_registry().service_metrics(table.name)
        self._g_rows = g.gauge(SERVICE_DELTA_ROWS)
        self._g_bytes = g.gauge(SERVICE_DELTA_BYTES)
        self._m_overflow = g.counter(SERVICE_DELTA_OVERFLOWS)

    # -- stats ---------------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            rows = self._open_rows + sum(s[2] for s in self._sealed)
            nbytes = self._open_bytes + sum(s[3] for s in self._sealed)
            return {"rows": rows, "bytes": nbytes,
                    "open_rows": self._open_rows,
                    "sealed_generations": len(self._sealed),
                    "max_bytes": self.max_bytes}

    def _set_gauges_locked(self):
        self._g_rows.set(self._open_rows
                         + sum(s[2] for s in self._sealed))
        self._g_bytes.set(self._open_bytes
                          + sum(s[3] for s in self._sealed))

    # -- write side (the core/write.py delta_listener hook) ------------------

    @staticmethod
    def _pkey(partition: Tuple) -> str:
        # MUST match LocalTableQuery._pkey: the probe keys by the same
        # composite string
        return json.dumps([repr(v) for v in tuple(partition)])

    def on_write(self, partition: Tuple, bucket: int, table, kinds,
                 seqs):
        """Publish one written batch into the open generation (called
        from _BucketWriter.write on the single-threaded writer, AFTER
        sequence reservation — so seq order here is write order)."""
        rows = table.to_pylist()
        pkey = self._pkey(partition)
        per_row = max(64, table.nbytes // max(1, table.num_rows))
        with self._lock:
            bucket_map = self._open.setdefault((pkey, int(bucket)), {})
            for row, kind, seq in zip(rows, kinds, seqs):
                kt = tuple(row[k] for k in self.pk)
                prev = bucket_map.get(kt)
                if prev is None:
                    self._open_rows += 1
                    self._open_bytes += per_row
                elif prev[0] > seq:
                    continue       # an even newer write already landed
                bucket_map[kt] = (int(seq), int(kind), row)
            if self._open_bytes + sum(s[3] for s in self._sealed) \
                    > self.max_bytes:
                self._m_overflow.inc()
            self._set_gauges_locked()

    def seal(self, snapshot_id: int):
        """The open generation's rows are durably committed as
        `snapshot_id`: move it to the sealed list (the dict object
        itself — a concurrent batch's captured view keeps serving it)
        and open a fresh one.  Prunes what the readers allow."""
        with self._lock:
            if self._open:
                self._sealed = self._sealed + (
                    (int(snapshot_id), self._open, self._open_rows,
                     self._open_bytes),)
                self._open = {}
                self._open_rows = 0
                self._open_bytes = 0
            self._prune_locked()
            self._set_gauges_locked()

    def discard_open(self):
        """Abandoned serving writer: its uncommitted rows must stop
        being served (they were never durably published — exactly like
        dropping an uncommitted write buffer)."""
        with self._lock:
            self._open = {}
            self._open_rows = 0
            self._open_bytes = 0
            self._set_gauges_locked()

    # -- read side -----------------------------------------------------------

    def view(self) -> DeltaView:
        """Capture for one lookup batch.  Callers MUST capture the
        view BEFORE capturing their plan: view-then-plan means every
        generation the plan does not cover is still in the view (the
        reverse order could miss a generation pruned between the plan
        capture and the view capture)."""
        with self._lock:
            gens: List[dict] = [self._open]
            for _sid, gen, _r, _b in reversed(self._sealed):
                gens.append(gen)
            return DeltaView(tuple(gens))

    def register_reader(self, reader):
        with self._lock:
            self._readers[id(reader)] = (reader, None)

    def unregister_reader(self, reader):
        with self._lock:
            self._readers.pop(id(reader), None)
            self._prune_locked()
            self._set_gauges_locked()

    def reader_advanced(self, reader, snapshot_id: Optional[int]):
        """A reader installed a plan at `snapshot_id`; sealed
        generations at or below the MIN across all loaded readers are
        covered by every plan and can retire."""
        with self._lock:
            if id(reader) in self._readers:
                self._readers[id(reader)] = (reader, snapshot_id)
            self._prune_locked()
            self._set_gauges_locked()

    def _prune_locked(self):
        if not self._sealed:
            return
        if not self._readers:
            # nobody can serve the delta: retire everything (a reader
            # registering LATER loads the latest snapshot, which
            # covers every sealed generation — their commits
            # completed before seal)
            self._sealed = ()
            return
        floors = [sid for _r, sid in self._readers.values()]
        if any(sid is None for sid in floors):
            # a registered reader has not loaded (or is MID-first-load
            # having already sampled an older snapshot id): its floor
            # is unknown — pruning now could un-publish rows its
            # about-to-install plan does not cover.  Keep everything
            # until it reports in (readers unregister on close, so
            # this cannot pin generations forever)
            return
        floor = min(floors)
        self._sealed = tuple(s for s in self._sealed if s[0] > floor)


class ServingWriter:
    """A TableWrite + TableCommit pair wired into the delta tier: every
    written row is readable via the serving plane's /lookup BEFORE any
    flush or commit, and `commit()` seals the generation with the
    published snapshot id so it retires once every replica's plan
    covers it.

        sw = server.new_serving_writer()
        sw.write_dicts([{"id": 7, "v": 1.5}])   # readable NOW
        sw.commit()                             # durable; delta retires

    One serving writer per table (see module docstring)."""

    def __init__(self, table, delta: DeltaTier,
                 commit_user: Optional[str] = None):
        reason = delta_ineligible_reason(table)
        if reason is not None:
            raise ValueError(f"table not delta-eligible: {reason}")
        self.table = table
        self.delta = delta
        if commit_user:
            wb = table.new_stream_write_builder() \
                .with_commit_user(commit_user)
        else:
            wb = table.new_batch_write_builder()
        self._builder = wb
        self._write = wb.new_write()
        self._write.set_delta_listener(delta.on_write)
        self._commit = wb.new_commit()
        self._closed = False

    # -- writes (delegate; the delta listener fires inside) ------------------

    def write_arrow(self, data, row_kinds=None):
        self._write.write_arrow(data, row_kinds)

    def write_dicts(self, rows, row_kinds=None):
        self._write.write_dicts(rows, row_kinds)

    def write_pandas(self, df):
        self._write.write_pandas(df)

    def commit(self, commit_identifier: Optional[int] = None,
               properties: Optional[dict] = None) -> Optional[int]:
        """Flush + commit + seal: after this returns, the generation's
        rows are durable AND still served from the delta until every
        attached reader's plan covers the new snapshot — there is no
        visibility gap at the handoff."""
        msgs = self._write.prepare_commit()
        kwargs = {}
        if commit_identifier is not None:
            kwargs["commit_identifier"] = commit_identifier
        if properties is not None:
            kwargs["properties"] = properties
        sid = self._commit.commit(msgs, **kwargs)
        if sid is not None:
            self.delta.seal(sid)
        return sid

    def close(self):
        """Close the writer; uncommitted (never-sealed) rows stop
        being served — an abandoned open generation must not outlive
        the writer that could have committed it."""
        if self._closed:
            return
        self._closed = True
        try:
            self._write.close()
        finally:
            self.delta.discard_open()

    def __enter__(self) -> "ServingWriter":
        return self

    def __exit__(self, *exc):
        self.close()
        return False
