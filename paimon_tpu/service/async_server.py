"""Event-loop request engine for the query-serving plane.

The PR-7 serving plane rode `ThreadingHTTPServer`: one OS thread per
connection.  At 64 keep-alive clients that is 64 server threads
fighting the GIL with every worker pool in the process; at 1k+
connections it is 1k+ stacks for mostly-idle sockets.  This module
replaces it with the classic event-loop shape (reference Paimon's
query service is a Netty server — same architecture, one accept/IO
loop + a bounded worker pool):

* ONE loop thread owns a `selectors.DefaultSelector` over non-blocking
  sockets: it accepts, reads, parses and writes — a connection costs a
  file descriptor plus a small parse buffer, never a thread;
* the HTTP/1.1 parser understands PIPELINED keep-alive requests: every
  complete request in the read buffer dispatches immediately (a client
  may send N requests back-to-back without waiting), and responses are
  written strictly in request order per connection (slot queue), as
  HTTP pipelining requires;
* request HANDLERS run on a bounded worker pool
  (`parallel/executors.new_thread_pool`) — they may block (admission
  queues, store IO, retry ladders, deadline waits) without ever
  stalling the loop; completions hand the response back to the loop
  through a self-wake socketpair;
* EVENT-LOOP LAG — the time a finished response waits before the loop
  picks it up — is measured per completion into the service metric
  group (`loop_lag_ms`) and surfaced on /healthz: it is THE canary for
  a starved loop (too few loop cycles per second means reads, writes
  and accepts are all late);
* per-connection pipelining is bounded (`MAX_PIPELINED`): a client
  flooding requests down one socket gets its reads paused (the socket
  simply stops being polled for READ) until responses drain —
  backpressure by TCP, no unbounded queue;
* the connection count is bounded (`max_connections`): beyond it,
  accepts answer `503` and close — file descriptors are the resource
  this engine spends, and even those are budgeted.

The tier-1 lint (tests/test_lint_swallow.py) bans raw `socket` /
`selectors` imports outside this module: ad-hoc network loops must not
creep back into the codebase — this is the one reviewed home of
non-blocking socket code, the same discipline as threads
(parallel/executors.py) and sleeps (utils/backoff.py).
"""

from __future__ import annotations

import json
import selectors
import socket
import threading
import time
from collections import deque
from typing import Callable, Dict, Optional, Tuple

from paimon_tpu.obs.trace import server_span

__all__ = ["AsyncHttpServer", "HttpRequest", "HttpResponse"]

# request-line + headers must fit here; a client that cannot finish its
# headers in 64 KiB is not speaking our protocol
MAX_HEADER_BYTES = 64 * 1024
# request bodies are JSON key/scan specs — 64 MiB is already generous
MAX_BODY_BYTES = 64 << 20
# in-flight pipelined requests per connection before its reads pause
MAX_PIPELINED = 64

_REASONS = {
    200: "OK", 400: "Bad Request", 404: "Not Found",
    405: "Method Not Allowed", 413: "Payload Too Large",
    429: "Too Many Requests", 500: "Internal Server Error",
    502: "Bad Gateway", 503: "Service Unavailable",
    504: "Gateway Timeout",
}


class HttpRequest:
    """One parsed request (headers lower-cased; body raw bytes)."""

    __slots__ = ("method", "path", "headers", "body", "keep_alive")

    def __init__(self, method: str, path: str, headers: Dict[str, str],
                 body: bytes, keep_alive: bool):
        self.method = method
        self.path = path
        self.headers = headers
        self.body = body
        self.keep_alive = keep_alive


class HttpResponse:
    __slots__ = ("status", "body", "content_type", "headers")

    def __init__(self, status: int, body: bytes,
                 content_type: str = "application/json",
                 headers: Optional[Dict[str, str]] = None):
        self.status = status
        self.body = body
        self.content_type = content_type
        self.headers = headers or {}

    def encode(self, keep_alive: bool) -> bytes:
        reason = _REASONS.get(self.status, "Unknown")
        lines = [f"HTTP/1.1 {self.status} {reason}",
                 f"Content-Type: {self.content_type}",
                 f"Content-Length: {len(self.body)}",
                 "Connection: " + ("keep-alive" if keep_alive
                                   else "close")]
        for k, v in self.headers.items():
            lines.append(f"{k}: {v}")
        head = ("\r\n".join(lines) + "\r\n\r\n").encode("latin-1")
        return head + self.body


class _ParseError(ValueError):
    pass


class _Slot:
    """One dispatched request's response seat: filled by a worker,
    drained by the loop in request order."""

    __slots__ = ("response", "keep_alive", "done_at")

    def __init__(self, keep_alive: bool):
        self.response: Optional[HttpResponse] = None
        self.keep_alive = keep_alive
        self.done_at = 0.0


class _Conn:
    __slots__ = ("sock", "rbuf", "wbuf", "slots", "eof", "close_after",
                 "paused", "events")

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.rbuf = bytearray()
        self.wbuf = bytearray()
        self.slots: deque = deque()      # _Slot, request order
        self.eof = False                 # peer closed its write side
        self.close_after = False         # close once wbuf drains
        self.paused = False              # reads off: pipeline full
        self.events = 0                  # currently registered mask


def _parse_one(rbuf: bytearray) -> Optional[Tuple[HttpRequest, int]]:
    """Parse one complete request off the front of `rbuf`; returns
    (request, consumed_bytes) or None if more bytes are needed.
    Raises _ParseError on malformed input."""
    head_end = rbuf.find(b"\r\n\r\n")
    if head_end < 0:
        if len(rbuf) > MAX_HEADER_BYTES:
            raise _ParseError("headers too large")
        return None
    head = bytes(rbuf[:head_end]).decode("latin-1")
    lines = head.split("\r\n")
    parts = lines[0].split()
    if len(parts) != 3 or not parts[2].startswith("HTTP/"):
        raise _ParseError(f"bad request line: {lines[0]!r}")
    method, path, version = parts
    headers: Dict[str, str] = {}
    for ln in lines[1:]:
        if not ln:
            continue
        name, sep, value = ln.partition(":")
        if not sep:
            raise _ParseError(f"bad header line: {ln!r}")
        headers[name.strip().lower()] = value.strip()
    try:
        length = int(headers.get("content-length", "0") or "0")
    except ValueError as e:
        raise _ParseError("bad content-length") from e
    if length < 0 or length > MAX_BODY_BYTES:
        raise _ParseError(f"body too large: {length}")
    total = head_end + 4 + length
    if len(rbuf) < total:
        return None
    body = bytes(rbuf[head_end + 4:total])
    conn_hdr = headers.get("connection", "").lower()
    keep_alive = conn_hdr != "close" and version != "HTTP/1.0"
    return HttpRequest(method, path, headers, body, keep_alive), total


class AsyncHttpServer:
    """selectors-based HTTP/1.1 server: one event-loop thread, a
    bounded handler pool, pipelined keep-alive connections.

    `handler(HttpRequest) -> HttpResponse` runs on the worker pool and
    may block; everything socket-side runs on the loop thread."""

    def __init__(self, host: str, port: int,
                 handler: Callable[[HttpRequest], HttpResponse],
                 *, workers: int = 16, max_connections: int = 1024,
                 name: str = "paimon-serve",
                 lag_histogram=None, connections_gauge=None):
        self._handler = handler
        self._name = name
        self._workers = max(1, int(workers))
        self.max_connections = max(1, int(max_connections))
        self._m_lag = lag_histogram
        self._g_conns = connections_gauge
        self._sel = selectors.DefaultSelector()
        self._listener = socket.create_server(
            (host, port), backlog=512, reuse_port=False)
        self._listener.setblocking(False)
        self.host = host
        self.port = self._listener.getsockname()[1]
        # self-wake channel: workers nudge the loop when a response is
        # ready (the loop may be parked in select())
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._wake_w.setblocking(False)
        self._done: deque = deque()      # (conn,) completions to flush
        self._done_lock = threading.Lock()
        self._conns: Dict[socket.socket, _Conn] = {}
        self._stop = threading.Event()
        self._pool_done = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._pool = None
        self.recent_lag_ms = 0.0         # last observed completion lag

    # -- lifecycle -----------------------------------------------------------

    @property
    def connection_count(self) -> int:
        return len(self._conns)

    def start(self) -> "AsyncHttpServer":
        from paimon_tpu.parallel.executors import (
            new_thread_pool, spawn_thread,
        )
        self._pool = new_thread_pool(self._workers,
                                     f"{self._name}-worker")
        self._sel.register(self._listener, selectors.EVENT_READ,
                           ("accept", None))
        self._sel.register(self._wake_r, selectors.EVENT_READ,
                           ("wake", None))
        self._thread = spawn_thread(self._loop,
                                    name=f"{self._name}-loop")
        return self

    def stop(self):
        """Graceful: stop accepting, let running handlers finish and
        their responses flush, then tear the loop down.  Safe on a
        never-started server (closes the bound listener)."""
        if self._thread is None:
            # constructed but never started: release the listener fd
            # and the wake pair
            try:
                if self._listener.fileno() >= 0:
                    self._listener.close()
                self._sel.close()
                self._wake_r.close()
                self._wake_w.close()
            except OSError:
                pass
            return
        self._stop.set()
        self._wake()
        # running handlers finish (their completions still flush: the
        # loop drains `_done` until after this join); queued-not-
        # started requests are cancelled — their slots never fill and
        # their connections just close, exactly like a server going
        # away mid-pipeline
        self._pool.shutdown(wait=True, cancel_futures=True)
        self._pool_done.set()
        self._wake()
        self._thread.join(timeout=30)
        self._thread = None

    # -- worker side ---------------------------------------------------------

    def _wake(self):
        try:
            self._wake_w.send(b"\0")
        except (BlockingIOError, OSError):
            pass          # pipe full = a wake is already pending

    def _run_handler(self, conn: _Conn, slot: _Slot, req: HttpRequest):
        try:
            # one flag check when tracing is off; when on, adopts the
            # caller's X-Trace-Id/X-Parent-Span as this request's
            # context — THE server-side hop boundary for every
            # AsyncHttpServer-based service (query server, router)
            with server_span(req.headers, method=req.method,
                             path=req.path):
                resp = self._handler(req)
        except Exception as e:      # noqa: BLE001 — must answer
            # json.dumps, never string splicing: exception text may
            # hold quotes/backslashes/control chars and the body must
            # stay parseable for the client's error decode
            resp = HttpResponse(500, json.dumps(
                {"error": f"internal: {str(e)[:512]}"}).encode())
        slot.response = resp
        slot.done_at = time.perf_counter()
        with self._done_lock:
            self._done.append(conn)
        self._wake()

    # -- loop side -----------------------------------------------------------

    def _loop(self):
        grace_until: Optional[float] = None
        try:
            while True:
                if self._stop.is_set():
                    # closed listener: no new connections; keep
                    # looping while responses are still in flight
                    if self._listener.fileno() >= 0:
                        self._sel.unregister(self._listener)
                        self._listener.close()
                    self._drain_done()
                    if self._pool_done.is_set():
                        # the pool is drained: every response that
                        # will ever exist is flushed or buffered —
                        # give buffered bytes a short grace to leave
                        if grace_until is None:
                            grace_until = time.perf_counter() + 1.0
                        if not any(c.wbuf
                                   for c in self._conns.values()) or \
                                time.perf_counter() >= grace_until:
                            break
                for key, events in self._sel.select(timeout=0.1):
                    kind, conn = key.data
                    if kind == "accept":
                        self._accept()
                    elif kind == "wake":
                        try:
                            while self._wake_r.recv(4096):
                                pass
                        except (BlockingIOError, OSError):
                            pass
                    else:
                        if events & selectors.EVENT_READ:
                            self._readable(conn)
                        if events & selectors.EVENT_WRITE and \
                                conn.sock in self._conns:
                            self._writable(conn)
                self._drain_done()
        finally:
            for conn in list(self._conns.values()):
                self._close(conn)
            try:
                if self._listener.fileno() >= 0:
                    self._listener.close()
            except OSError:
                pass
            self._sel.close()
            self._wake_r.close()
            self._wake_w.close()

    def _accept(self):
        while True:
            try:
                sock, _ = self._listener.accept()
            except (BlockingIOError, OSError):
                return
            if len(self._conns) >= self.max_connections:
                # over the fd budget: an honest, tiny 503 — never a
                # silent RST from a backlog overflow
                try:
                    sock.setblocking(False)
                    sock.send(HttpResponse(
                        503, b'{"error": "connection limit"}')
                        .encode(keep_alive=False))
                except OSError:
                    pass
                sock.close()
                continue
            sock.setblocking(False)
            try:
                sock.setsockopt(socket.IPPROTO_TCP,
                                socket.TCP_NODELAY, 1)
            # lint-ok: fault-taxonomy best-effort socket option on a
            # fresh connection, never re-attempted: losing TCP_NODELAY
            # degrades latency, not correctness — not a store retry
            except OSError:
                pass
            conn = _Conn(sock)
            self._conns[sock] = conn
            self._register(conn, selectors.EVENT_READ)
            if self._g_conns is not None:
                self._g_conns.set(len(self._conns))

    def _register(self, conn: _Conn, events: int):
        if events == conn.events:
            return
        if conn.events == 0:
            self._sel.register(conn.sock, events, ("conn", conn))
        elif events == 0:
            self._sel.unregister(conn.sock)
        else:
            self._sel.modify(conn.sock, events, ("conn", conn))
        conn.events = events

    def _wanted_events(self, conn: _Conn) -> int:
        ev = 0
        if not conn.eof and not conn.paused and not conn.close_after:
            ev |= selectors.EVENT_READ
        if conn.wbuf:
            ev |= selectors.EVENT_WRITE
        return ev

    def _readable(self, conn: _Conn):
        if conn.sock not in self._conns:
            return                        # closed earlier this cycle
        try:
            chunk = conn.sock.recv(256 * 1024)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            self._close(conn)
            return
        if not chunk:
            conn.eof = True
            if not conn.slots and not conn.wbuf:
                self._close(conn)
            else:
                self._register(conn, self._wanted_events(conn))
            return
        conn.rbuf += chunk
        self._parse_and_dispatch(conn)

    def _parse_and_dispatch(self, conn: _Conn):
        while len(conn.slots) < MAX_PIPELINED:
            try:
                parsed = _parse_one(conn.rbuf)
            except _ParseError as e:
                slot = _Slot(keep_alive=False)
                slot.response = HttpResponse(
                    400, json.dumps({"error": str(e)}).encode())
                slot.done_at = time.perf_counter()
                conn.slots.append(slot)
                conn.close_after = True
                conn.rbuf.clear()         # garbage past a parse error
                self._flush_ready(conn)
                break
            if parsed is None:
                break
            req, consumed = parsed
            del conn.rbuf[:consumed]
            slot = _Slot(req.keep_alive)
            conn.slots.append(slot)
            if not req.keep_alive:
                conn.close_after = True
            try:
                if self._stop.is_set() or self._pool is None:
                    raise RuntimeError("stopping")
                self._pool.submit(self._run_handler, conn, slot, req)
            except RuntimeError:
                # racing stop(): the pool may reject between the flag
                # check and the submit — answer 503 inline
                slot.response = HttpResponse(
                    503, b'{"error": "server stopping"}')
                slot.done_at = time.perf_counter()
                self._flush_ready(conn)
        # pipeline full -> pause reads (TCP backpressures the client)
        conn.paused = len(conn.slots) >= MAX_PIPELINED
        if conn.sock in self._conns:
            self._register(conn, self._wanted_events(conn))

    def _drain_done(self) -> bool:
        """Move completed responses (in request order per connection)
        into write buffers; records event-loop lag.  Returns whether
        anything was pending."""
        moved = False
        while True:
            # lint-ok: loop-blocking micro critical section shared
            # with workers: both sides only append/popleft under the
            # lock, never block inside it — the hand-off IS the
            # event-loop completion design (loop lag is measured one
            # line below to catch it regressing)
            with self._done_lock:
                if not self._done:
                    break
                conn = self._done.popleft()
            moved = True
            if conn.sock in self._conns:
                self._flush_ready(conn)
        return moved

    def _flush_ready(self, conn: _Conn):
        now = time.perf_counter()
        while conn.slots and conn.slots[0].response is not None:
            slot = conn.slots.popleft()
            if slot.done_at:
                lag_ms = (now - slot.done_at) * 1000.0
                self.recent_lag_ms = lag_ms
                if self._m_lag is not None:
                    self._m_lag.update(lag_ms)
            keep = slot.keep_alive and not conn.close_after
            conn.wbuf += slot.response.encode(keep_alive=keep)
        if conn.paused and len(conn.slots) < MAX_PIPELINED:
            conn.paused = False
            self._parse_and_dispatch(conn)
        if conn.wbuf:
            self._writable(conn)       # opportunistic immediate write
        elif conn.sock in self._conns:
            self._maybe_finish(conn)

    def _writable(self, conn: _Conn):
        try:
            while conn.wbuf:
                n = conn.sock.send(conn.wbuf[:256 * 1024])
                if n <= 0:
                    break
                del conn.wbuf[:n]
        except (BlockingIOError, InterruptedError):
            pass
        except OSError:
            self._close(conn)
            return
        self._maybe_finish(conn)

    def _maybe_finish(self, conn: _Conn):
        if not conn.wbuf and not conn.slots and \
                (conn.close_after or conn.eof):
            self._close(conn)
            return
        self._register(conn, self._wanted_events(conn))

    def _close(self, conn: _Conn):
        if self._conns.pop(conn.sock, None) is None:
            return
        if conn.events:
            try:
                self._sel.unregister(conn.sock)
            except (KeyError, ValueError):
                pass
            conn.events = 0
        try:
            conn.sock.close()
        except OSError:
            pass
        if self._g_conns is not None:
            self._g_conns.set(len(self._conns))
