"""Warm boot: persist serving state through the shared SSD tier so a
new replica's first lookup is served from recovered state with ZERO
rebuild.

The host-SSD collaborative-LSM design (PAPERS.md, arXiv 2410.21760)
pushes LSM serving state down to a shared SSD tier; the paimon-tpu
analog persists the two things a replica otherwise rebuilds per
process:

* the BUILT SST FILES of the point-lookup engine (lookup/sst.py),
  hard-linked under their STABLE store keys — `file|...` keys embed
  the immutable data-file name, `bucket|...` keys the bucket's file
  list digest, so any process over the same table computes the same
  keys and can adopt the files sight unseen (a key that stopped being
  live is reconciled away by the next plan load);
* the PLAN-CACHE live-entry state (core/plan_cache.py), serialized as
  a real avro container of manifest entries plus a JSON header — the
  restored replica's first plan is a delta-apply (or a pure cache
  hit), never a cold manifest walk.

Layout under `<service.warmboot.dir | cache.disk.dir/warmboot>/
<table digest>/`:

    manifest.json     {"snapshot_id", "ssts": {store_key: file},
                       "plan": {...} | null}   — published ATOMICALLY
                      last, so a reader never sees files without it
    plan.avro         live manifest entries (MANIFEST_ENTRY_AVRO_SCHEMA)
    <sha1(key)>.sst   the SST files themselves

The directory carries the same sharing contract as `cache.disk.dir`:
an SSD mount reachable by every machine's replicas.  Persisting is
idempotent (stable names, last writer wins) and restoring is advisory
— a vanished file or stale snapshot degrades to the normal cold path,
never to an error.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
from typing import Optional

__all__ = ["warmboot_dir", "table_state_dir", "persist_serving_state",
           "restore_serving_state"]

_MANIFEST = "manifest.json"
_PLAN = "plan.avro"


def warmboot_dir(options) -> Optional[str]:
    """The configured warm-boot root: `service.warmboot.dir`, else
    `<cache.disk.dir>/warmboot`, else None (warm boot unavailable)."""
    from paimon_tpu.options import CoreOptions
    d = options.get(CoreOptions.SERVICE_WARMBOOT_DIR)
    if d:
        return d
    disk = options.get(CoreOptions.CACHE_DISK_DIR)
    if disk:
        return os.path.join(disk, "warmboot")
    return None


def table_state_dir(base: str, table) -> str:
    """Per-(table, branch) subdirectory — replicas of different tables
    share one warm-boot root without colliding."""
    digest = hashlib.sha1(
        f"{table.path.rstrip('/')}|{table.branch or 'main'}"
        .encode()).hexdigest()[:16]
    return os.path.join(base, digest)


def _link_or_copy(src: str, dst: str):
    tmp = dst + f".tmp-{os.getpid()}"
    try:
        os.link(src, tmp)
    except OSError:
        shutil.copyfile(src, tmp)
    os.replace(tmp, dst)


def persist_serving_state(query, dest: str) -> dict:
    """Persist `query`'s warm serving state into `dest`: every built
    SST hard-links (or copies across filesystems) under its stable
    store key, and the table's plan-cache state serializes as an avro
    entry container.  The manifest publishes last by atomic rename —
    a concurrent restore sees either the previous complete state or
    this one."""
    os.makedirs(dest, exist_ok=True)
    store = query.store
    ssts = {}
    for key in store.keys():
        reader = store.get(key)
        if reader is None:
            continue
        fname = hashlib.sha1(key.encode()).hexdigest()[:24] + ".sst"
        try:
            _link_or_copy(reader.path, os.path.join(dest, fname))
        except OSError:
            continue          # evicted under us: skip, stay advisory
        ssts[key] = fname
    plan_meta = None
    from paimon_tpu.core.plan_cache import shared_plan_cache
    state = shared_plan_cache(query.table.path,
                              query.table.branch).state()
    if state is not None:
        from paimon_tpu.format import avro as avro_fmt
        from paimon_tpu.manifest.manifest_entry import (
            MANIFEST_ENTRY_AVRO_SCHEMA,
        )
        entries = [e for d in state.groups.values()
                   for e in d.values()]
        data = avro_fmt.write_container(
            MANIFEST_ENTRY_AVRO_SCHEMA,
            [e.to_avro() for e in entries])
        tmp = os.path.join(dest, _PLAN + f".tmp-{os.getpid()}")
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, os.path.join(dest, _PLAN))
        plan_meta = {"snapshot_id": state.snapshot_id,
                     "base_list": state.base_list,
                     "delta_list": state.delta_list,
                     "index_manifest": state.index_manifest,
                     "entry_count": state.entry_count}
    manifest = {"snapshot_id": query.snapshot_id, "ssts": ssts,
                "plan": plan_meta}
    tmp = os.path.join(dest, _MANIFEST + f".tmp-{os.getpid()}")
    with open(tmp, "w") as f:
        json.dump(manifest, f)
    os.replace(tmp, os.path.join(dest, _MANIFEST))
    return {"ssts": len(ssts), "snapshot_id": query.snapshot_id,
            "plan": plan_meta is not None}


def restore_serving_state(query, src: str) -> dict:
    """Adopt persisted state into `query` BEFORE its first lookup: the
    plan-cache state republishes (so the first plan is a cache hit or
    delta-apply instead of a cold walk) and every persisted SST is
    adopted under its store key with no reader build.  Advisory end to
    end: missing/corrupt state restores nothing and the cold path
    runs; state for keys no longer live is reconciled away by the
    first plan load."""
    out = {"ssts": 0, "plan": False}
    try:
        with open(os.path.join(src, _MANIFEST)) as f:
            manifest = json.load(f)
    except (OSError, ValueError):
        return out
    if manifest.get("plan"):
        try:
            from paimon_tpu.core.plan_cache import (
                PlanState, shared_plan_cache,
            )
            from paimon_tpu.format import avro as avro_fmt
            from paimon_tpu.manifest.manifest_entry import ManifestEntry
            with open(os.path.join(src, _PLAN), "rb") as f:
                _, records = avro_fmt.read_container(f.read())
            groups: dict = {}
            for r in records:
                e = ManifestEntry.from_avro(r)
                groups.setdefault((e.partition, e.bucket),
                                  {})[e.identifier()] = e
            pm = manifest["plan"]
            state = PlanState(pm["snapshot_id"], pm["base_list"],
                              pm["delta_list"], pm["index_manifest"],
                              groups,
                              sum(len(d) for d in groups.values()))
            cache = shared_plan_cache(query.table.path,
                                      query.table.branch)
            cache.put_state(state, cache.state())
            out["plan"] = True
        except (OSError, ValueError, KeyError):
            pass          # stale/corrupt plan blob: cold plan instead
    for key, fname in (manifest.get("ssts") or {}).items():
        path = os.path.join(src, fname)
        if not os.path.exists(path):
            continue
        try:
            query.store.adopt(key, path)
            out["ssts"] += 1
        except (OSError, ValueError, RuntimeError):
            continue      # unreadable file: build it cold instead
    return out
