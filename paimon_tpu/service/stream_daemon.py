"""Streaming lakehouse daemon: checkpointed exactly-once CDC ingest,
level-triggered compaction and changelog serving over ONE table, as
three supervised concurrent loops.

This is the long-running form of Paimon's core scenario (PAPER.md:
continuous upserts into per-bucket LSM trees with low-latency streaming
changelog reads), built from the batch pieces the repo already has:

    ingest   cdc/sink.py + parallel/write_pipeline.py (flush budget =
             backpressure), offsets committed ATOMICALLY with each
             snapshot via commit properties
    compact  compact/compact_action.py -> parallel/mesh_engine.py (full
             compactions ride PR 2's retry/fallback ladder)
    serve    table/stream_scan.py follow-up scans, buffered for
             in-process consumers and exposed on the query service
             (`/changelog`)

Robustness model
----------------

**Exactly-once ingest.**  A checkpoint is one snapshot committed with
`commit_identifier = N` and properties::

    stream.source.offset  offset of the last CDC event included
    stream.ingest.ts-ms   wall time the checkpoint's first event was
                          pulled (feeds end-to-end freshness)

Recovery (daemon start OR supervised ingest-loop restart) discards the
writer (uploaded-but-uncommitted files become orphans for maintenance),
reads the newest snapshot of this daemon's commit user that carries an
offset, and re-polls the source after it.  Replay is idempotent twice
over: the source offset only advances inside committed snapshots, and
`CdcSinkWriter.commit` + `filter_committed` drop a checkpoint whose
CAS landed but whose ack was lost (cdc/sink.py).

**Backpressure.**  The ingest loop pulls at most
`stream.ingest.max-batch` events per poll and hands them straight to
the writer, whose `write.flush.max-bytes` budget BLOCKS the loop while
the flush pipeline is saturated — the daemon holds no internal event
queue, so the source pull rate is coupled to sustained flush/upload
throughput.  The changelog buffer is likewise bounded
(`stream.serve.buffer.rows`): a lagging consumer stalls the serving
loop, never memory.

**Supervision.**  Each loop runs under a supervisor that restarts it on
any error with capped decorrelated-jitter backoff (utils/backoff.py,
`stream.restart.*`); a run longer than `stream.restart.healthy-
threshold` resets the schedule.  Loops degrade independently:
compaction pauses while ingest is under pressure
(`stream.compaction.pause-*`), and serving keeps reading committed
snapshots while ingest or compaction are down or crash-looping.

**Drain.**  `stop()` (also wired to SIGTERM/SIGINT via
`install_signal_handlers`) stops pulling, commits one final checkpoint
for everything already ingested, lets the serving loop catch up to the
final snapshot, then joins all loops.  `kill()` is the crash path used
by the fault harness: loops abandon work immediately and nothing past
the last committed checkpoint survives — which is the point.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from paimon_tpu.options import CoreOptions
from paimon_tpu.table.table import FileStoreTable

__all__ = ["StreamDaemon", "recover_checkpoint", "checkpoint_once",
           "PROP_OFFSET", "PROP_INGEST_TS"]

PROP_OFFSET = "stream.source.offset"
PROP_INGEST_TS = "stream.ingest.ts-ms"

DEFAULT_COMMIT_USER = "stream-daemon"


def _now_ms() -> int:
    return int(time.time() * 1000)


def find_checkpoint_snapshot(table: FileStoreTable, commit_user: str):
    """Newest snapshot of `commit_user` carrying an offset property,
    or None."""
    sm = table.snapshot_manager
    latest = sm.latest_snapshot_id()
    earliest = sm.earliest_snapshot_id()
    if latest is None or earliest is None:
        return None
    for sid in range(latest, earliest - 1, -1):
        try:
            snap = sm.snapshot(sid)
        except FileNotFoundError:
            continue              # expired under us
        if snap.commit_user != commit_user:
            continue
        if PROP_OFFSET in (snap.properties or {}):
            return snap
    return None


def recover_checkpoint(table: FileStoreTable,
                       commit_user: str) -> tuple:
    """(last committed source offset, last commit identifier) for this
    daemon user, from the newest snapshot carrying an offset property —
    (-1, 0) when the daemon has never checkpointed.  The offset is read
    from snapshot properties, so it is exactly as durable as the data
    it describes."""
    snap = find_checkpoint_snapshot(table, commit_user)
    if snap is None:
        return -1, 0
    return int(snap.properties[PROP_OFFSET]), snap.commit_identifier


def checkpoint_once(table: FileStoreTable, source, *,
                    commit_user: str = DEFAULT_COMMIT_USER,
                    format: str = "debezium",
                    max_events: Optional[int] = None) -> Optional[int]:
    """One synchronous ingest step: recover the committed offset, pull
    every available event past it (up to `max_events`) and commit ONE
    checkpoint.  This is the daemon's ingest loop unrolled — and the
    crash-sweep surface for the offset-commit path: killing any
    mutating op inside it must leave a table that recovers to exactly
    one copy of every event."""
    from paimon_tpu.cdc.sink import CdcSinkWriter

    offset, last_ckpt = recover_checkpoint(table, commit_user)
    events = source.poll(offset, max_events if max_events is not None
                         else 1 << 30)
    if not events:
        return None
    ingest_ts = _now_ms()
    sink = CdcSinkWriter(table.copy({"write-only": "true"}),
                         format=format, commit_user=commit_user)
    try:
        sink.write_events([e for _, e in events])
        return sink.commit(
            last_ckpt + 1,
            properties={PROP_OFFSET: str(events[-1][0]),
                        PROP_INGEST_TS: str(ingest_ts)})
    finally:
        sink.close()


class _Supervisor:
    """Runs one loop body in a named thread, restarting it on failure
    with capped decorrelated-jitter backoff.  The body is expected to
    loop until the daemon stops and return; any raise is a crash."""

    def __init__(self, daemon: "StreamDaemon", name: str, body):
        self.daemon = daemon
        self.name = name
        self.body = body
        self.restarts = 0
        self.consecutive = 0
        self.last_error: Optional[str] = None
        self.failed = False
        self.thread: Optional[threading.Thread] = None

    def start(self):
        from paimon_tpu.parallel.executors import spawn_thread
        self.thread = spawn_thread(self._run,
                                   name=f"paimon-stream-{self.name}")

    def alive(self) -> bool:
        return self.thread is not None and self.thread.is_alive()

    def join(self, timeout: Optional[float]):
        if self.thread is not None:
            self.thread.join(timeout)

    def _run(self):
        from paimon_tpu.metrics import STREAM_LOOP_RESTARTS
        from paimon_tpu.obs.trace import span
        from paimon_tpu.utils.backoff import Backoff

        d = self.daemon
        backoff: Optional[Backoff] = None
        while not d._stop.is_set():
            t0 = time.monotonic()
            try:
                self.body()
                return                        # clean exit (stop/drain)
            except BaseException as e:        # noqa: BLE001 — supervised
                self.last_error = f"{type(e).__name__}: {e}"
                if d._killed:
                    return                    # crash path: expected
                if d._stop.is_set():
                    # crashed DURING drain (e.g. the final checkpoint
                    # commit failed): no restart is coming, so surface
                    # it — status()/CLI exit code must not report a
                    # clean drain that wasn't
                    self.failed = True
                    return
                self.restarts += 1
                d._metrics.counter(STREAM_LOOP_RESTARTS).inc()
                healthy = (time.monotonic() - t0) * 1000 >= \
                    d._o["healthy_ms"]
                self.consecutive = 0 if healthy else self.consecutive + 1
                if d._o["max_restarts"] is not None and \
                        self.consecutive > d._o["max_restarts"]:
                    self.failed = True
                    return                    # terminal; status carries it
                if healthy or backoff is None:
                    backoff = Backoff(d._o["restart_backoff_ms"],
                                      d._o["restart_cap_ms"])
                wait_ms = backoff.next_ms()
                with span("stream.restart.backoff", cat="stream",
                          loop=self.name, attempt=self.restarts,
                          error=type(e).__name__):
                    d._stop.wait(wait_ms / 1000.0)


class StreamDaemon:
    """Drive ingest + compaction + changelog serving over one table.

    Usage::

        daemon = StreamDaemon(table, source).start()
        ...
        rows = daemon.poll_changelog(max_rows=1000)
        ...
        daemon.stop()          # drain: final checkpoint, serve catches up
    """

    def __init__(self, table: FileStoreTable, source, *,
                 format: str = "debezium",
                 commit_user: str = DEFAULT_COMMIT_USER,
                 compact: bool = True, serve: bool = True,
                 dynamic_options: Optional[Dict[str, str]] = None):
        from paimon_tpu.metrics import global_registry
        from paimon_tpu.obs.trace import sync_from_options

        self._dynamic = dict(dynamic_options or {})
        self.table = table.copy(self._dynamic) if self._dynamic else table
        self.source = source
        self.format = format
        self.commit_user = commit_user
        o = self.table.options
        sync_from_options(o)
        self._o = {
            "ckpt_interval_ms": o.get(
                CoreOptions.STREAM_CHECKPOINT_INTERVAL),
            "max_batch": o.get(CoreOptions.STREAM_INGEST_MAX_BATCH),
            "ingest_poll_ms": o.get(
                CoreOptions.STREAM_INGEST_POLL_INTERVAL),
            "compact_interval_ms": o.get(
                CoreOptions.STREAM_COMPACTION_INTERVAL),
            "compact_full": o.get(CoreOptions.STREAM_COMPACTION_FULL),
            "pause_ratio": o.get(
                CoreOptions.STREAM_COMPACTION_PAUSE_RATIO),
            "pause_backlog": o.get(
                CoreOptions.STREAM_COMPACTION_PAUSE_BACKLOG),
            "serve_poll_ms": o.get(
                CoreOptions.STREAM_SERVE_POLL_INTERVAL),
            "serve_buffer_rows": o.get(
                CoreOptions.STREAM_SERVE_BUFFER_ROWS),
            "restart_backoff_ms": o.get(
                CoreOptions.STREAM_RESTART_BACKOFF),
            "restart_cap_ms": o.get(
                CoreOptions.STREAM_RESTART_BACKOFF_CAP),
            "healthy_ms": o.get(CoreOptions.STREAM_RESTART_HEALTHY_MS),
            "max_restarts": o.get(CoreOptions.STREAM_RESTART_MAX),
            "expire_interval_ms": o.get(
                CoreOptions.STREAM_EXPIRE_INTERVAL),
            "flush_max_bytes": o.get(CoreOptions.WRITE_FLUSH_MAX_BYTES),
        }
        self._metrics = global_registry().stream_metrics()
        self._stop = threading.Event()
        self._draining = False
        self._killed = False
        self._signal = threading.Event()
        self._last_close_error: Optional[str] = None

        # ingest state (owned by the ingest thread; exposed read-only)
        self._sink = None
        self._offset = -1              # last COMMITTED source offset
        self._offset_pending = -1      # last offset written to the sink
        self._next_ckpt = 1
        self._batch_first_pull_ms: Optional[int] = None

        # bounded changelog buffer (serve loop -> consumers)
        self._buf: List[dict] = []
        self._buf_cond = threading.Condition()

        self._loops: List[_Supervisor] = [
            _Supervisor(self, "ingest", self._ingest_body)]
        if compact:
            self._loops.append(
                _Supervisor(self, "compact", self._compact_body))
        if serve:
            self._loops.append(
                _Supervisor(self, "serve", self._serve_body))

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "StreamDaemon":
        for sup in self._loops:
            sup.start()
        return self

    def stop(self, drain: bool = True,
             timeout: float = 30.0) -> Dict:
        """Stop the daemon.  With `drain` (the default) the ingest loop
        commits a final checkpoint for everything already pulled and
        the serving loop catches up to the final snapshot before
        exiting; without it this behaves like `kill()`."""
        if not drain:
            return self.kill()
        self._draining = True
        self._stop.set()
        with self._buf_cond:
            self._buf_cond.notify_all()
        deadline = time.monotonic() + timeout
        # join ingest FIRST (it commits the final checkpoint), serve
        # second (it must still be running to see that final snapshot)
        for name in ("ingest", "compact", "serve"):
            for sup in self._loops:
                if sup.name == name:
                    sup.join(max(0.1, deadline - time.monotonic()))
        if any(sup.alive() for sup in self._loops):
            # a loop is wedged (e.g. a consumer stopped draining the
            # changelog buffer): force the crash path for what remains
            self._killed = True
            with self._buf_cond:
                self._buf_cond.notify_all()
            for sup in self._loops:
                sup.join(5.0)
        self._close_sink()
        from paimon_tpu.obs.trace import maybe_export
        maybe_export()
        return self.status()

    def kill(self) -> Dict:
        """Abrupt termination (the fault-injection/crash path): no
        final checkpoint, no serve catch-up.  Everything since the last
        committed checkpoint is intentionally lost; a new daemon on the
        same table + source replays it exactly once."""
        self._killed = True
        self._stop.set()
        with self._buf_cond:
            self._buf_cond.notify_all()
        for sup in self._loops:
            sup.join(10.0)
        self._close_sink()
        return self.status()

    def install_signal_handlers(self):
        """SIGTERM/SIGINT -> graceful drain (run_forever returns)."""
        import signal

        def handler(signum, frame):
            self._signal.set()

        try:
            signal.signal(signal.SIGTERM, handler)
            signal.signal(signal.SIGINT, handler)
        except ValueError:
            pass     # not the main thread: caller drives stop() itself

    def run_forever(self, duration_s: Optional[float] = None) -> Dict:
        """Block until SIGTERM/SIGINT (or `duration_s`), then drain."""
        self._signal.wait(duration_s)
        return self.stop(drain=True)

    def status(self) -> Dict:
        return {
            "commit_user": self.commit_user,
            "offset_committed": self._offset,
            "offset_pending": self._offset_pending,
            "next_checkpoint": self._next_ckpt,
            "draining": self._draining,
            "killed": self._killed,
            "buffered_rows": len(self._buf),
            "sink_close_error": self._last_close_error,
            "loops": {
                sup.name: {"alive": sup.alive(),
                           "restarts": sup.restarts,
                           "failed": sup.failed,
                           "last_error": sup.last_error}
                for sup in self._loops},
        }

    # -- changelog consumption ----------------------------------------------

    def poll_changelog(self, max_rows: int = 4096,
                       timeout: Optional[float] = None) -> List[dict]:
        """Pop up to `max_rows` buffered changelog rows (each carries
        `_ROW_KIND`); blocks up to `timeout` for the first row."""
        deadline = None if timeout is None \
            else time.monotonic() + timeout
        with self._buf_cond:
            while not self._buf:
                if self._stop.is_set() and not self._serve_alive():
                    return []
                wait = 0.2 if deadline is None \
                    else min(0.2, deadline - time.monotonic())
                if wait <= 0:
                    return []
                self._buf_cond.wait(wait)
            out = self._buf[:max_rows]
            del self._buf[:max_rows]
            self._buf_cond.notify_all()
            return out

    def _serve_alive(self) -> bool:
        return any(sup.name == "serve" and sup.alive()
                   for sup in self._loops)

    def _ingest_alive(self) -> bool:
        return any(sup.name == "ingest" and sup.alive()
                   for sup in self._loops)

    # -- ingest loop ---------------------------------------------------------

    def _ingest_recover(self):
        """(Re)entry of the ingest loop = recovery: drop the writer
        (its uncommitted uploads become orphans), reload the table
        (schema may have evolved), re-read the committed offset."""
        from paimon_tpu.cdc.sink import CdcSinkWriter

        self._close_sink()
        table = FileStoreTable.load(
            self.table.path, file_io=self.table.file_io,
            dynamic_options={**self._dynamic, "write-only": "true"})
        offset, last_ckpt = recover_checkpoint(table, self.commit_user)
        # in-memory floor: on a supervised IN-PROCESS restart, never
        # fall behind what this process already saw committed — if the
        # offset snapshot was expired/lost underneath us, regressing to
        # it (or to -1) would re-ingest committed events and reuse
        # identifiers
        self._offset = max(offset, self._offset)
        self._offset_pending = self._offset
        self._next_ckpt = max(last_ckpt + 1, self._next_ckpt)
        self._batch_first_pull_ms = None
        self._sink = CdcSinkWriter(table, format=self.format,
                                   commit_user=self.commit_user)

    def _close_sink(self):
        if self._sink is None:
            return
        try:
            self._sink.close()
        except Exception as e:                # noqa: BLE001
            # close() joins the flush pool; under injected store faults
            # it can re-raise the latched worker error. The sink is
            # being discarded either way — record, don't mask the
            # recovery that is about to run.
            self._metrics.counter("sink_close_errors").inc()
            self._last_close_error = f"{type(e).__name__}: {e}"
        self._sink = None

    def _ingest_body(self):
        from paimon_tpu.metrics import (
            STREAM_EVENTS_INGESTED, STREAM_SOURCE_BACKLOG,
        )
        from paimon_tpu.obs.trace import span

        self._ingest_recover()
        o = self._o
        last_ckpt_at = time.monotonic()
        while True:
            if self._killed:
                return
            stopping = self._stop.is_set()
            events = [] if stopping else self.source.poll(
                self._offset_pending, o["max_batch"])
            now_mono = time.monotonic()
            if events:
                if self._batch_first_pull_ms is None:
                    self._batch_first_pull_ms = _now_ms()
                with span("stream.ingest.batch", cat="stream",
                          events=len(events),
                          first=events[0][0], last=events[-1][0]):
                    # write_events blocks on write.flush.max-bytes:
                    # THE backpressure coupling — no internal queue
                    self._sink.write_events([e for _, e in events])
                self._offset_pending = events[-1][0]
                self._metrics.counter(STREAM_EVENTS_INGESTED) \
                    .inc(len(events))
            self._metrics.gauge(STREAM_SOURCE_BACKLOG).set(
                self.source.backlog(self._offset_pending))
            pending = self._offset_pending > self._offset
            if pending and (stopping or
                            (now_mono - last_ckpt_at) * 1000
                            >= o["ckpt_interval_ms"]):
                self._checkpoint()
                last_ckpt_at = time.monotonic()
            if stopping:
                return            # drained (final checkpoint above)
            if not events:
                self._stop.wait(o["ingest_poll_ms"] / 1000.0)

    def _checkpoint(self):
        from paimon_tpu.metrics import (
            STREAM_CHECKPOINT_MS, STREAM_CHECKPOINTS,
        )
        from paimon_tpu.obs.trace import span

        ckpt = self._next_ckpt
        props = {PROP_OFFSET: str(self._offset_pending),
                 PROP_INGEST_TS: str(self._batch_first_pull_ms
                                     or _now_ms())}
        with span("stream.checkpoint", cat="stream", group="stream",
                  metric=STREAM_CHECKPOINT_MS, checkpoint=ckpt,
                  offset=self._offset_pending):
            self._sink.commit(ckpt, properties=props)
        # past this line the checkpoint is durable: advance in-memory
        # state (a crash between commit and here replays the
        # checkpoint, which filter_committed + pending-keying dedup)
        self._offset = self._offset_pending
        self._next_ckpt = ckpt + 1
        self._batch_first_pull_ms = None
        self._metrics.counter(STREAM_CHECKPOINTS).inc()
        # sources that cache events may evict everything at/below the
        # now-durable offset (FileCdcSource bounds its memory this way)
        commit_through = getattr(self.source, "commit_through", None)
        if commit_through is not None:
            commit_through(self._offset)

    # -- compaction loop -----------------------------------------------------

    def _ingest_pressure(self) -> bool:
        from paimon_tpu.metrics import (
            STREAM_SOURCE_BACKLOG, WRITE_INFLIGHT_BYTES, global_registry,
        )

        inflight = global_registry().write_metrics() \
            .gauge(WRITE_INFLIGHT_BYTES).value
        budget = self._o["flush_max_bytes"]
        if budget and inflight > self._o["pause_ratio"] * budget:
            return True
        backlog = self._metrics.gauge(STREAM_SOURCE_BACKLOG).value
        return backlog > self._o["pause_backlog"]

    def _needs_compaction(self, table: FileStoreTable) -> bool:
        """Level/size trigger: any bucket at/over the sorted-run
        trigger (pk tables: level-0 files each count as a run, higher
        levels one run each — compact/levels.py semantics) or, for
        append tables, at/over compaction.min.file-num."""
        snapshot = table.latest_snapshot()
        if snapshot is None:
            return False
        scan = table.new_scan()
        per_bucket: Dict[tuple, List] = {}
        for e in scan.read_entries(snapshot):
            if e.bucket == -2:
                continue
            per_bucket.setdefault((e.partition, e.bucket), []) \
                .append(e.file)
        if not table.schema.primary_keys:
            trigger = table.options.get(
                CoreOptions.COMPACTION_MIN_FILE_NUM)
            return any(len(fs) >= trigger for fs in per_bucket.values())
        trigger = table.options.num_sorted_runs_compaction_trigger
        for files in per_bucket.values():
            runs = sum(1 for f in files if f.level == 0) + \
                len({f.level for f in files if f.level > 0})
            if runs >= trigger:
                return True
        return False

    def _compact_body(self):
        from paimon_tpu.metrics import (
            STREAM_COMPACTIONS, STREAM_COMPACTIONS_PAUSED,
        )
        from paimon_tpu.obs.trace import span

        o = self._o
        last_expire_at = time.monotonic()
        while not self._stop.wait(o["compact_interval_ms"] / 1000.0):
            if self._ingest_pressure():
                # graceful degradation: ingest pressure wins; try
                # again next round
                self._metrics.counter(STREAM_COMPACTIONS_PAUSED).inc()
                continue
            table = FileStoreTable.load(
                self.table.path, file_io=self.table.file_io,
                dynamic_options=self._dynamic or None)
            if self._needs_compaction(table):
                with span("stream.compact", cat="stream",
                          full=o["compact_full"]):
                    sid = table.compact(full=o["compact_full"])
                if sid is not None:
                    self._metrics.counter(STREAM_COMPACTIONS).inc()
            if o["expire_interval_ms"] is not None and \
                    (time.monotonic() - last_expire_at) * 1000 \
                    >= o["expire_interval_ms"]:
                # NEVER expire the newest offset-carrying snapshot: it
                # is the recovery point — losing it would restart the
                # source from scratch and reuse commit identifiers.
                # Widening retain_min pins everything back to it (an
                # idle source under active compaction is exactly when
                # newer non-ingest snapshots would otherwise push it
                # out of the retention window).
                retain_min = None
                ckpt_snap = find_checkpoint_snapshot(table,
                                                     self.commit_user)
                latest = table.snapshot_manager.latest_snapshot_id()
                if ckpt_snap is not None and latest is not None:
                    retain_min = latest - ckpt_snap.id + 1
                table.expire_snapshots(
                    retain_min=retain_min,
                    retain_max=None if retain_min is None else max(
                        retain_min, table.options.get(
                            CoreOptions.SNAPSHOT_NUM_RETAINED_MAX)))
                last_expire_at = time.monotonic()

    # -- changelog serving loop ----------------------------------------------

    def _serve_body(self):
        from paimon_tpu.metrics import (
            STREAM_CHANGELOG_ROWS, STREAM_FRESHNESS_MS,
        )
        from paimon_tpu.obs.trace import span

        # persist serving progress as consumer state so a restarted
        # serving loop (or daemon incarnation) RESUMES the stream
        # instead of full-rescanning — resuming replays every delta
        # (including delete tombstones) exactly from where consumers
        # last got rows, and re-served batches are upsert-idempotent
        table = FileStoreTable.load(
            self.table.path, file_io=self.table.file_io,
            dynamic_options={**self._dynamic,
                             "consumer-id": f"{self.commit_user}-serve"})
        rb = table.new_read_builder()
        scan = rb.new_stream_scan()
        while True:
            if self._killed:
                return
            was_first = scan._first
            plan = scan.plan()
            if plan is None:
                if self._stop.is_set() and not self._ingest_alive():
                    # caught up AND the final checkpoint (committed by
                    # the ingest loop before it exited) has been served
                    return
                self._stop.wait(self._o["serve_poll_ms"] / 1000.0)
                continue
            if plan.splits:
                with span("stream.serve.batch", cat="stream",
                          snapshot=plan.snapshot_id) as sp:
                    rows = rb.new_read().to_arrow(plan).to_pylist()
                    # freshness is only meaningful for follow-up
                    # deltas (a startup full scan spans all history)
                    freshness = None if was_first else \
                        self._freshness_ms(table, plan.snapshot_id)
                    if freshness is not None:
                        # event -> visible-in-changelog-scan latency,
                        # from the ingest ts the checkpoint committed
                        self._metrics.histogram(STREAM_FRESHNESS_MS) \
                            .update(freshness)
                        sp.set(freshness_ms=freshness)
                if not self._emit(rows):
                    return          # killed while blocked on the buffer
                self._metrics.counter(STREAM_CHANGELOG_ROWS) \
                    .inc(len(rows))
            # rows are delivered (bounded buffer): record consumer
            # progress so a restart resumes past this snapshot
            scan.notify_checkpoint_complete(scan.checkpoint())

    def _freshness_ms(self, table: FileStoreTable,
                      snapshot_id: Optional[int]) -> Optional[float]:
        if snapshot_id is None:
            return None
        try:
            snap = table.snapshot_manager.snapshot(snapshot_id)
        except (FileNotFoundError, OSError):
            return None
        props = snap.properties or {}
        if PROP_INGEST_TS not in props:
            return None           # not one of our ingest checkpoints
        return max(0.0, _now_ms() - int(props[PROP_INGEST_TS]))

    def _emit(self, rows: List[dict]) -> bool:
        """Bounded blocking enqueue: the serving loop stalls (never
        drops, never grows without bound) while consumers lag.  False
        when killed while waiting — the rows were NOT delivered, so
        the caller must not record progress past them."""
        cap = self._o["serve_buffer_rows"]
        i = 0
        with self._buf_cond:
            while i < len(rows):
                while len(self._buf) >= cap and not self._killed:
                    self._buf_cond.wait(0.2)
                if self._killed:
                    # partially-delivered batch: progress is NOT
                    # recorded, the next incarnation re-serves it
                    # (upsert-idempotent for consumers)
                    return False
                take = max(1, cap - len(self._buf))
                self._buf.extend(rows[i:i + take])
                i += take
                self._buf_cond.notify_all()
        return True
