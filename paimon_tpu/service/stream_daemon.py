"""Streaming lakehouse daemon: checkpointed exactly-once CDC ingest,
level-triggered compaction and changelog serving over ONE table, as
three supervised concurrent loops.

This is the long-running form of Paimon's core scenario (PAPER.md:
continuous upserts into per-bucket LSM trees with low-latency streaming
changelog reads), built from the batch pieces the repo already has:

    ingest   cdc/sink.py + parallel/write_pipeline.py (flush budget =
             backpressure), offsets committed ATOMICALLY with each
             snapshot via commit properties
    compact  compact/compact_action.py -> parallel/mesh_engine.py (full
             compactions ride PR 2's retry/fallback ladder)
    serve    table/stream_scan.py follow-up scans, buffered for
             in-process consumers and exposed on the query service
             (`/changelog`)

Robustness model
----------------

**Exactly-once ingest.**  A checkpoint is one snapshot committed with
`commit_identifier = N` and properties::

    stream.source.offset  offset of the last CDC event included
    stream.ingest.ts-ms   wall time the checkpoint's first event was
                          pulled (feeds end-to-end freshness)

Recovery (daemon start OR supervised ingest-loop restart) discards the
writer (uploaded-but-uncommitted files become orphans for maintenance),
reads the newest snapshot of this daemon's commit user that carries an
offset, and re-polls the source after it.  Replay is idempotent twice
over: the source offset only advances inside committed snapshots, and
`CdcSinkWriter.commit` + `filter_committed` drop a checkpoint whose
CAS landed but whose ack was lost (cdc/sink.py).

**Backpressure.**  The ingest loop pulls at most
`stream.ingest.max-batch` events per poll and hands them straight to
the writer, whose `write.flush.max-bytes` budget BLOCKS the loop while
the flush pipeline is saturated — the daemon holds no internal event
queue, so the source pull rate is coupled to sustained flush/upload
throughput.  The changelog buffer is likewise bounded
(`stream.serve.buffer.rows`): a lagging consumer stalls the serving
loop, never memory.

**Supervision.**  Each loop runs under a supervisor that restarts it on
any error with capped decorrelated-jitter backoff (utils/backoff.py,
`stream.restart.*`); a run longer than `stream.restart.healthy-
threshold` resets the schedule.  Loops degrade independently:
compaction pauses while ingest is under pressure
(`stream.compaction.pause-*`), and serving keeps reading committed
snapshots while ingest or compaction are down or crash-looping.

**Drain.**  `stop()` (also wired to SIGTERM/SIGINT via
`install_signal_handlers`) stops pulling, commits one final checkpoint
for everything already ingested, lets the serving loop catch up to the
final snapshot, then joins all loops.  `kill()` is the crash path used
by the fault harness: loops abandon work immediately and nothing past
the last committed checkpoint survives — which is the point.

Distributed mode (host-death tolerance)
---------------------------------------

Passing `plane=MaintenancePlane(table, base_user=<commit_user>, ...)`
(parallel/maintenance_plane.py) turns one daemon per host into one
LOGICAL daemon over the shared table:

- **Sharded ingest.**  Every host sees the IDENTICAL CDC stream (the
  SPMD shape) but writes only the events whose (partition, bucket)
  it owns; offsets for a host's owned share are committed under its
  OWN commit user (`<base>-p<i>`), atomically with the data and with
  the plane's lease + ownership stamps.
- **Sharded maintenance.**  The compaction loop compacts only owned
  groups (the `group_filter` seam of compact_table / the mesh
  engine); snapshot expiry is ELECTED (lowest-ranked alive process)
  and protects EVERY live host's newest offset-carrying checkpoint;
  idle hosts renew their lease with heartbeat snapshots.
- **Sharded serving.**  Each host's serve loop ships only the
  changelog of owned buckets, under a per-host consumer id.
- **Takeover.**  When a peer's lease expires, the survivor adopts its
  buckets exactly-once: it BACKFILLS the gap between the dead peer's
  committed offset and its own from the replayable source (only the
  adopted groups, only offsets the dead peer had not committed), and
  publishes the backfill, the bumped ownership generation, and an
  offset FLOOR for the dead peer in ONE commit — so a crash
  mid-takeover redoes it from scratch and a crash after it never
  re-delivers.  The floor suppresses forward events the dead peer
  already wrote (its offset may be ahead of the survivor's).  The
  serve loop then catches up the adopted buckets from the dead
  peer's persisted consumer position before folding them into its
  own stream.  Recovery merges chains: a restarted survivor resumes
  its own offsets, re-reads its own stamped dead set and floors, and
  re-runs any takeover it had not durably published.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from paimon_tpu.options import CoreOptions
from paimon_tpu.table.table import FileStoreTable

__all__ = ["StreamDaemon", "recover_checkpoint", "checkpoint_once",
           "recover_max_identifier", "recover_plane_stamps",
           "PROP_OFFSET", "PROP_INGEST_TS", "PROP_FLOOR_PREFIX"]

PROP_OFFSET = "stream.source.offset"
PROP_INGEST_TS = "stream.ingest.ts-ms"
# survivor-stamped offset floor for an adopted dead peer: events at or
# below it in the peer's old buckets are ALREADY in the table (the
# peer committed them before dying) and must never be re-written
PROP_FLOOR_PREFIX = "stream.floor.p"
# THIS daemon's durable adoption ledger (csv of dead pids whose
# backfill it has published).  Deliberately separate from
# multihost.ownership.dead: the global dead set can reach my commit
# user through a heartbeat that merely relays another survivor's
# stamp — it must never convince a restarted ingest loop that MY
# share of a takeover was published when it wasn't
PROP_ADOPTED = "stream.adopted"

DEFAULT_COMMIT_USER = "stream-daemon"


def _now_ms() -> int:
    return int(time.time() * 1000)


def find_checkpoint_snapshot(table: FileStoreTable, commit_user: str):
    """Newest snapshot of `commit_user` carrying an offset property,
    or None."""
    sm = table.snapshot_manager
    latest = sm.latest_snapshot_id()
    earliest = sm.earliest_snapshot_id()
    if latest is None or earliest is None:
        return None
    for sid in range(latest, earliest - 1, -1):
        try:
            snap = sm.snapshot(sid)
        except FileNotFoundError:
            continue              # expired under us
        if snap.commit_user != commit_user:
            continue
        if PROP_OFFSET in (snap.properties or {}):
            return snap
    return None


def recover_checkpoint(table: FileStoreTable,
                       commit_user: str) -> tuple:
    """(last committed source offset, last commit identifier) for this
    daemon user, from the newest snapshot carrying an offset property —
    (-1, 0) when the daemon has never checkpointed.  The offset is read
    from snapshot properties, so it is exactly as durable as the data
    it describes."""
    snap = find_checkpoint_snapshot(table, commit_user)
    if snap is None:
        return -1, 0
    return int(snap.properties[PROP_OFFSET]), snap.commit_identifier


def recover_max_identifier(table: FileStoreTable,
                           commit_user: str) -> int:
    """Largest NON-batch commit identifier this user ever committed.
    Distributed daemons need this beyond `recover_checkpoint`: a
    takeover-backfill commit carries an identifier but deliberately NO
    offset property, so recovering `last_ckpt` from the newest
    offset-carrying snapshot alone could reuse the backfill's
    identifier — and `filter_committed` would then silently drop the
    next real checkpoint as a replay."""
    from paimon_tpu.snapshot.snapshot import BATCH_COMMIT_IDENTIFIER
    best = 0
    for snap in table.snapshot_manager.snapshots():
        if snap.commit_user != commit_user:
            continue
        if snap.commit_identifier == BATCH_COMMIT_IDENTIFIER:
            continue              # heartbeats / batch commits
        best = max(best, snap.commit_identifier)
    return best


def recover_plane_stamps(table: FileStoreTable, commit_user: str):
    """(this daemon's durable adoption ledger, its stamped floors
    {dead_pid: offset}) from the newest snapshot of `commit_user`
    carrying plane stamps.  A dead peer appears in the ledger
    (`stream.adopted`) exactly when THIS daemon's backfill commit for
    it landed — the global ownership dead set is deliberately not
    consulted, see PROP_ADOPTED."""
    from paimon_tpu.parallel.distributed import has_ownership_stamp
    sm = table.snapshot_manager
    latest = sm.latest_snapshot_id()
    earliest = sm.earliest_snapshot_id()
    if latest is None or earliest is None:
        return frozenset(), {}
    for sid in range(latest, earliest - 1, -1):
        try:
            snap = sm.snapshot(sid)
        except FileNotFoundError:
            continue
        if snap.commit_user != commit_user:
            continue
        props = snap.properties or {}
        if not has_ownership_stamp(props):
            continue
        adopted = frozenset(
            int(p) for p in (props.get(PROP_ADOPTED) or "").split(",")
            if p.strip())
        floors = {}
        for k, v in props.items():
            if k.startswith(PROP_FLOOR_PREFIX):
                try:
                    floors[int(k[len(PROP_FLOOR_PREFIX):])] = int(v)
                except ValueError:
                    continue
        return adopted, floors
    return frozenset(), {}


def checkpoint_once(table: FileStoreTable, source, *,
                    commit_user: str = DEFAULT_COMMIT_USER,
                    format: str = "debezium",
                    max_events: Optional[int] = None) -> Optional[int]:
    """One synchronous ingest step: recover the committed offset, pull
    every available event past it (up to `max_events`) and commit ONE
    checkpoint.  This is the daemon's ingest loop unrolled — and the
    crash-sweep surface for the offset-commit path: killing any
    mutating op inside it must leave a table that recovers to exactly
    one copy of every event."""
    from paimon_tpu.cdc.sink import CdcSinkWriter

    offset, last_ckpt = recover_checkpoint(table, commit_user)
    events = source.poll(offset, max_events if max_events is not None
                         else 1 << 30)
    if not events:
        return None
    ingest_ts = _now_ms()
    sink = CdcSinkWriter(table.copy({"write-only": "true"}),
                         format=format, commit_user=commit_user)
    try:
        sink.write_events([e for _, e in events])
        return sink.commit(
            last_ckpt + 1,
            properties={PROP_OFFSET: str(events[-1][0]),
                        PROP_INGEST_TS: str(ingest_ts)})
    finally:
        sink.close()


class _Supervisor:
    """Runs one loop body in a named thread, restarting it on failure
    with capped decorrelated-jitter backoff.  The body is expected to
    loop until the daemon stops and return; any raise is a crash."""

    def __init__(self, daemon: "StreamDaemon", name: str, body):
        self.daemon = daemon
        self.name = name
        self.body = body
        self.restarts = 0
        self.consecutive = 0
        self.last_error: Optional[str] = None
        self.failed = False
        self.thread: Optional[threading.Thread] = None

    def start(self):
        from paimon_tpu.parallel.executors import spawn_thread
        self.thread = spawn_thread(self._run,
                                   name=f"paimon-stream-{self.name}")

    def alive(self) -> bool:
        return self.thread is not None and self.thread.is_alive()

    def join(self, timeout: Optional[float]):
        if self.thread is not None:
            self.thread.join(timeout)

    def _run(self):
        from paimon_tpu.metrics import STREAM_LOOP_RESTARTS
        from paimon_tpu.obs.trace import span
        from paimon_tpu.utils.backoff import Backoff

        d = self.daemon
        backoff: Optional[Backoff] = None
        while not d._stop.is_set():
            t0 = time.monotonic()
            try:
                self.body()
                return                        # clean exit (stop/drain)
            except BaseException as e:        # noqa: BLE001 — supervised
                self.last_error = f"{type(e).__name__}: {e}"
                if d._killed:
                    return                    # crash path: expected
                if d._stop.is_set():
                    # crashed DURING drain (e.g. the final checkpoint
                    # commit failed): no restart is coming, so surface
                    # it — status()/CLI exit code must not report a
                    # clean drain that wasn't
                    self.failed = True
                    self._record_terminal("drain")
                    return
                self.restarts += 1
                d._metrics.counter(STREAM_LOOP_RESTARTS).inc()
                healthy = (time.monotonic() - t0) * 1000 >= \
                    d._o["healthy_ms"]
                self.consecutive = 0 if healthy else self.consecutive + 1
                if d._o["max_restarts"] is not None and \
                        self.consecutive > d._o["max_restarts"]:
                    self.failed = True
                    self._record_terminal("max_restarts")
                    return                    # terminal; status carries it
                if healthy or backoff is None:
                    backoff = Backoff(d._o["restart_backoff_ms"],
                                      d._o["restart_cap_ms"])
                wait_ms = backoff.next_ms()
                with span("stream.restart.backoff", cat="stream",
                          loop=self.name, attempt=self.restarts,
                          error=type(e).__name__):
                    d._stop.wait(wait_ms / 1000.0)

    def _record_terminal(self, why: str):
        """A loop died for good: black-box the crash so a post-mortem
        can see the triggering event plus the preceding ring."""
        from paimon_tpu.obs import flight
        from paimon_tpu.obs.trace import spool_flush
        flight.record(flight.EV_LOOP_CRASH, loop=self.name, why=why,
                      error=self.last_error, restarts=self.restarts)
        flight.dump()
        spool_flush()


class StreamDaemon:
    """Drive ingest + compaction + changelog serving over one table.

    Usage::

        daemon = StreamDaemon(table, source).start()
        ...
        rows = daemon.poll_changelog(max_rows=1000)
        ...
        daemon.stop()          # drain: final checkpoint, serve catches up
    """

    def __init__(self, table: FileStoreTable, source, *,
                 format: str = "debezium",
                 commit_user: str = DEFAULT_COMMIT_USER,
                 compact: bool = True, serve: bool = True,
                 dynamic_options: Optional[Dict[str, str]] = None,
                 plane=None):
        from paimon_tpu.metrics import global_registry
        from paimon_tpu.obs.trace import sync_from_options

        self._dynamic = dict(dynamic_options or {})
        self.table = table.copy(self._dynamic) if self._dynamic else table
        self.source = source
        self.format = format
        # distributed mode: `plane` is this host's MaintenancePlane
        # (parallel/maintenance_plane.py) — the daemon commits under a
        # per-host user, ingests/compacts/serves only owned buckets
        # and adopts a dead peer's share exactly-once
        self.plane = plane
        self._user_base = commit_user
        if plane is not None:
            if plane.base_user != commit_user:
                raise ValueError(
                    f"plane.base_user {plane.base_user!r} != daemon "
                    f"commit_user {commit_user!r}: heartbeats and "
                    f"checkpoints must share one per-host commit user")
            self.commit_user = plane.commit_user
        else:
            self.commit_user = commit_user
        o = self.table.options
        sync_from_options(o)
        from paimon_tpu.obs import flight
        flight.sync_from_options(o)
        self._o = {
            "ckpt_interval_ms": o.get(
                CoreOptions.STREAM_CHECKPOINT_INTERVAL),
            "max_batch": o.get(CoreOptions.STREAM_INGEST_MAX_BATCH),
            "ingest_poll_ms": o.get(
                CoreOptions.STREAM_INGEST_POLL_INTERVAL),
            "compact_interval_ms": o.get(
                CoreOptions.STREAM_COMPACTION_INTERVAL),
            "compact_full": o.get(CoreOptions.STREAM_COMPACTION_FULL),
            "manifest_compact_interval_ms": o.get(
                CoreOptions.STREAM_MANIFEST_COMPACTION_INTERVAL),
            "pause_ratio": o.get(
                CoreOptions.STREAM_COMPACTION_PAUSE_RATIO),
            "pause_backlog": o.get(
                CoreOptions.STREAM_COMPACTION_PAUSE_BACKLOG),
            "serve_poll_ms": o.get(
                CoreOptions.STREAM_SERVE_POLL_INTERVAL),
            "serve_buffer_rows": o.get(
                CoreOptions.STREAM_SERVE_BUFFER_ROWS),
            "restart_backoff_ms": o.get(
                CoreOptions.STREAM_RESTART_BACKOFF),
            "restart_cap_ms": o.get(
                CoreOptions.STREAM_RESTART_BACKOFF_CAP),
            "healthy_ms": o.get(CoreOptions.STREAM_RESTART_HEALTHY_MS),
            "max_restarts": o.get(CoreOptions.STREAM_RESTART_MAX),
            "expire_interval_ms": o.get(
                CoreOptions.STREAM_EXPIRE_INTERVAL),
            "flush_max_bytes": o.get(CoreOptions.WRITE_FLUSH_MAX_BYTES),
        }
        self._metrics = global_registry().stream_metrics()
        self._stop = threading.Event()
        self._draining = False
        self._killed = False
        self._signal = threading.Event()
        self._last_close_error: Optional[str] = None

        # ingest state (owned by the ingest thread; exposed read-only)
        self._sink = None
        self._offset = -1              # last COMMITTED source offset
        self._offset_pending = -1      # last offset written to the sink
        self._next_ckpt = 1
        self._batch_first_pull_ms: Optional[int] = None

        # distributed-mode state
        # commits (checkpoints, heartbeats, takeover backfills) of one
        # daemon serialize on this lock so a heartbeat can never stamp
        # a takeover generation whose backfill has not been published
        self._commit_lock = threading.Lock()
        # dead peers whose buckets MY chain has durably adopted (the
        # forward-ingest filter's dead set — may lag plane.ownership
        # while a backfill is pending, never leads it)
        self._ingest_dead: frozenset = frozenset()
        self._floors: Dict[int, int] = {}          # dead pid -> offset
        self._pending_adoptions: List[int] = []    # detector -> ingest
        self._pending_rejoins: List[int] = []      # grant queue (elected)
        self._pending_rejoin_acks: List[int] = []  # floor-stamp queue
        self._rejoin_replayed = 0                  # rows gap-replayed
        self._serve_adoptions: List[int] = []      # ingest -> serve
        self._serve_dead: frozenset = frozenset()
        if plane is not None:
            self._init_event_router()
            # heartbeats / forced adoption stamps must carry the
            # daemon's FULL property set (floors included): a
            # heartbeat stamping ownership without the active floors
            # would shadow them for recovery
            plane._file_store_commit().properties_provider = \
                self._plane_props

        # bounded changelog buffer (serve loop -> consumers)
        self._buf: List[dict] = []
        self._buf_cond = threading.Condition()

        self._loops: List[_Supervisor] = [
            _Supervisor(self, "ingest", self._ingest_body)]
        if compact:
            self._loops.append(
                _Supervisor(self, "compact", self._compact_body))
        if serve:
            self._loops.append(
                _Supervisor(self, "serve", self._serve_body))

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "StreamDaemon":
        for sup in self._loops:
            sup.start()
        return self

    def stop(self, drain: bool = True,
             timeout: float = 30.0) -> Dict:
        """Stop the daemon.  With `drain` (the default) the ingest loop
        commits a final checkpoint for everything already pulled and
        the serving loop catches up to the final snapshot before
        exiting; without it this behaves like `kill()`."""
        if not drain:
            return self.kill()
        self._draining = True
        self._stop.set()
        with self._buf_cond:
            self._buf_cond.notify_all()
        deadline = time.monotonic() + timeout
        # join ingest FIRST (it commits the final checkpoint), serve
        # second (it must still be running to see that final snapshot)
        for name in ("ingest", "compact", "serve"):
            for sup in self._loops:
                if sup.name == name:
                    sup.join(max(0.1, deadline - time.monotonic()))
        if any(sup.alive() for sup in self._loops):
            # a loop is wedged (e.g. a consumer stopped draining the
            # changelog buffer): force the crash path for what remains
            self._killed = True
            with self._buf_cond:
                self._buf_cond.notify_all()
            for sup in self._loops:
                sup.join(5.0)
        self._close_sink()
        from paimon_tpu.obs.trace import maybe_export
        maybe_export()
        return self.status()

    def kill(self) -> Dict:
        """Abrupt termination (the fault-injection/crash path): no
        final checkpoint, no serve catch-up.  Everything since the last
        committed checkpoint is intentionally lost; a new daemon on the
        same table + source replays it exactly once."""
        self._killed = True
        self._stop.set()
        with self._buf_cond:
            self._buf_cond.notify_all()
        for sup in self._loops:
            sup.join(10.0)
        self._close_sink()
        from paimon_tpu.obs.trace import spool_flush
        spool_flush()
        return self.status()

    def install_signal_handlers(self):
        """SIGTERM/SIGINT -> graceful drain (run_forever returns).

        The handler flushes the trace spool and flight ring *before*
        initiating the drain: if the drain itself wedges and the
        process is then killed hard, the black box still made it to
        disk."""
        import signal

        def handler(signum, frame):
            from paimon_tpu.obs import flight
            from paimon_tpu.obs.trace import spool_flush
            flight.record(flight.EV_SIGTERM, signum=signum)
            flight.dump()
            spool_flush()
            self._signal.set()

        try:
            signal.signal(signal.SIGTERM, handler)
            signal.signal(signal.SIGINT, handler)
        except ValueError:
            pass     # not the main thread: caller drives stop() itself

    def run_forever(self, duration_s: Optional[float] = None) -> Dict:
        """Block until SIGTERM/SIGINT (or `duration_s`), then drain."""
        self._signal.wait(duration_s)
        return self.stop(drain=True)

    def status(self) -> Dict:
        out = {
            "commit_user": self.commit_user,
            "offset_committed": self._offset,
            "offset_pending": self._offset_pending,
            "next_checkpoint": self._next_ckpt,
            "draining": self._draining,
            "killed": self._killed,
            "buffered_rows": len(self._buf),
            "sink_close_error": self._last_close_error,
            "loops": {
                sup.name: {"alive": sup.alive(),
                           "restarts": sup.restarts,
                           "failed": sup.failed,
                           "last_error": sup.last_error}
                for sup in self._loops},
        }
        if self.plane is not None:
            out["distributed"] = {
                "process_index": self.plane.process_index,
                "process_count": self.plane.process_count,
                "ownership_version": self.plane.ownership.version,
                "dead": sorted(self.plane.ownership.dead),
                "adopted": sorted(self._ingest_dead),
                "floors": dict(self._floors),
                "rejoining": self.plane.rejoining,
                "rejoin_replayed": self._rejoin_replayed,
            }
        return out

    # -- changelog consumption ----------------------------------------------

    def poll_changelog(self, max_rows: int = 4096,
                       timeout: Optional[float] = None) -> List[dict]:
        """Pop up to `max_rows` buffered changelog rows (each carries
        `_ROW_KIND`); blocks up to `timeout` for the first row."""
        deadline = None if timeout is None \
            else time.monotonic() + timeout
        with self._buf_cond:
            while not self._buf:
                if self._stop.is_set() and not self._serve_alive():
                    return []
                wait = 0.2 if deadline is None \
                    else min(0.2, deadline - time.monotonic())
                if wait <= 0:
                    return []
                self._buf_cond.wait(wait)
            out = self._buf[:max_rows]
            del self._buf[:max_rows]
            self._buf_cond.notify_all()
            return out

    def _serve_alive(self) -> bool:
        return any(sup.name == "serve" and sup.alive()
                   for sup in self._loops)

    def _ingest_alive(self) -> bool:
        return any(sup.name == "ingest" and sup.alive()
                   for sup in self._loops)

    # -- ingest loop ---------------------------------------------------------

    def _ingest_recover(self):
        """(Re)entry of the ingest loop = recovery: drop the writer
        (its uncommitted uploads become orphans), reload the table
        (schema may have evolved), re-read the committed offset."""
        from paimon_tpu.cdc.sink import CdcSinkWriter

        self._close_sink()
        table = FileStoreTable.load(
            self.table.path, file_io=self.table.file_io,
            dynamic_options={**self._dynamic, "write-only": "true"})
        offset, last_ckpt = recover_checkpoint(table, self.commit_user)
        # in-memory floor: on a supervised IN-PROCESS restart, never
        # fall behind what this process already saw committed — if the
        # offset snapshot was expired/lost underneath us, regressing to
        # it (or to -1) would re-ingest committed events and reuse
        # identifiers
        self._offset = max(offset, self._offset)
        self._offset_pending = self._offset
        self._next_ckpt = max(last_ckpt + 1, self._next_ckpt)
        self._batch_first_pull_ms = None
        if self.plane is not None:
            # identifier floor over my WHOLE chain: backfill commits
            # carry identifiers but no offsets
            self._next_ckpt = max(
                self._next_ckpt,
                recover_max_identifier(table, self.commit_user) + 1)
            # my durable takeover ledger (dead set + floors stamped by
            # MY commits) — the global map on the plane may be ahead
            # (another survivor's stamp) or behind (nobody committed
            # since the takeover): pending adoptions are exactly the
            # globally-declared dead I have not durably adopted
            my_dead, floors = recover_plane_stamps(table,
                                                   self.commit_user)
            self._ingest_dead = frozenset(my_dead)
            merged = dict(floors)
            for j, f in self._floors.items():
                merged[j] = max(f, merged.get(j, f))
            self._floors = merged
            self.plane.refresh_view()
            self.plane.refresh_ownership()
            self._reconcile_adoptions()
            for j in sorted(self._ingest_dead):
                if j not in self._serve_dead and \
                        j not in self._serve_adoptions:
                    self._serve_adoptions.append(j)
        self._sink = CdcSinkWriter(table, format=self.format,
                                   commit_user=self.commit_user)
        if self.plane is not None:
            # plane stamps ride a PROVIDER (re-evaluated per CAS
            # attempt): a checkpoint losing its race to a peer's
            # takeover commit must re-stamp the NEW generation on
            # retry, not republish the stale one at the tip
            self._sink.properties_provider = self._plane_props

    def _close_sink(self):
        if self._sink is None:
            return
        try:
            self._sink.close()
        except Exception as e:                # noqa: BLE001
            # close() joins the flush pool; under injected store faults
            # it can re-raise the latched worker error. The sink is
            # being discarded either way — record, don't mask the
            # recovery that is about to run.
            self._metrics.counter("sink_close_errors").inc()
            self._last_close_error = f"{type(e).__name__}: {e}"
        self._sink = None

    # -- distributed routing + takeover (plane mode) -------------------------

    def _init_event_router(self):
        """Per-event (partition, bucket) routing with the SAME hash
        the write path uses (core/bucket.FixedBucketAssigner), so the
        ingest ownership split can never disagree with where the sink
        would actually put the row."""
        from paimon_tpu.cdc.sink import _PARSERS
        from paimon_tpu.core.bucket import FixedBucketAssigner
        schema = self.table.schema
        bucket_keys = schema.bucket_keys() or \
            schema.trimmed_primary_keys()
        if not bucket_keys:
            raise ValueError(
                "distributed stream daemons need a primary-key table: "
                "ownership shards on the bucket key")
        rt = schema.logical_row_type()
        self._assigner = FixedBucketAssigner(
            bucket_keys, [rt.get_field(k).type for k in bucket_keys],
            self.table.options.bucket)
        self._bucket_key_names = bucket_keys
        self._partition_key_names = schema.partition_keys
        self._key_schema = None
        self._parse_event = _PARSERS[self.format]

    def _event_group(self, event):
        """(partition, bucket) of one CDC event, or None for events
        that parse to no changes.  All changes of one pk event share
        the key, so the first change decides."""
        return self._event_groups([event])[0]

    def _event_groups(self, events) -> list:
        """[(partition, bucket) or None] for a whole poll batch: the
        bucket hash runs ONCE vectorized over the batch's key rows
        (core/bucket KeyHasher numpy path) instead of building a
        one-row table per event — the ROADMAP item 5 residual.  The
        per-row path (_event_group) is the oracle the equivalence test
        compares against."""
        import pyarrow as pa
        rows: list = []
        present: list = []
        for i, event in enumerate(events):
            changes = self._parse_event(event)
            if not changes:
                rows.append(None)
                continue
            rows.append(changes[0][0])
            present.append(i)
        groups: list = [None] * len(events)
        if not present:
            return groups
        if self._key_schema is None:
            arrow = self.table.arrow_schema()
            self._key_schema = pa.schema(
                [arrow.field(k) for k in self._bucket_key_names])
        sub = pa.Table.from_pylist(
            [{k: rows[i].get(k) for k in self._bucket_key_names}
             for i in present],
            schema=self._key_schema)
        buckets = self._assigner.assign(sub)
        for i, bucket in zip(present, buckets):
            part = tuple(rows[i].get(k)
                         for k in self._partition_key_names)
            groups[i] = (part, int(bucket))
        return groups

    def _forward_map(self):
        """The forward-ingest ownership map: the plane's topology with
        MY durably-adopted dead set — a takeover in flight (declared
        but not yet backfilled+published) must not leak adopted groups
        into forward writes, or backfilled rows would land with HIGHER
        sequence numbers than newer forward rows and win the merge."""
        from paimon_tpu.parallel.distributed import OwnershipMap
        m = self.plane.ownership
        return OwnershipMap(m.version, m.num_processes, m.num_buckets,
                            self._ingest_dead)

    def _owns_forward_event(self, offset: int, event,
                            m=None) -> bool:
        return self._owns_forward_group(
            offset, self._event_group(event), m)

    def _owns_forward_group(self, offset: int, g,
                            m=None) -> bool:
        if g is None:
            return False
        part, bucket = g
        if m is None:
            m = self._forward_map()
        if m.owner_of(part, bucket) != self.plane.process_index:
            return False
        for j, floor in self._floors.items():
            if offset <= floor and self._was_owned_by(j, part, bucket):
                return False      # the dead peer committed this one
        return True

    def _was_owned_by(self, j: int, part, bucket) -> bool:
        """Did (part, bucket) belong to dead peer `j` immediately
        before its takeover?  EXACT: evaluated against the newest
        persisted generation in which j was alive
        (`GenerationHistory.map_governing` — the map that actually
        governed j's writes), so chained multi-death floors stay
        correct: with two peers dead, `current dead − {j}` would
        re-shard the OTHER victim's groups differently from any map j
        ever wrote under and mis-scope the floor.  Deterministic from
        persisted properties alone, so floors survive restarts.
        Falls back to the adopted-map-minus-j approximation only when
        the history was truncated past j (64-generation cap) or the
        topology changed since."""
        from paimon_tpu.parallel.distributed import OwnershipMap
        m = self._forward_map()
        governing = self.plane.history.map_governing(j)
        if governing is not None and \
                (governing.num_processes, governing.num_buckets) == \
                (m.num_processes, m.num_buckets):
            return governing.owner_of(part, bucket) == j
        prev = OwnershipMap(m.version, m.num_processes, m.num_buckets,
                            frozenset(m.dead) - {j})
        return prev.owner_of(part, bucket) == j

    def _adopted_from(self, j: int, part, bucket) -> bool:
        """Group moves j -> ME in the takeover (my backfill share)."""
        from paimon_tpu.parallel.distributed import OwnershipMap
        m = self._forward_map()
        if not self._was_owned_by(j, part, bucket):
            return False
        nxt = OwnershipMap(m.version, m.num_processes, m.num_buckets,
                           frozenset(m.dead) | {j})
        return nxt.owner_of(part, bucket) == self.plane.process_index

    def _floor_props(self) -> Dict[str, str]:
        """Active floors ride every checkpoint until the committed
        offset passes them (recovery re-reads them from my newest
        stamped snapshot)."""
        return {f"{PROP_FLOOR_PREFIX}{j}": str(f)
                for j, f in sorted(self._floors.items())
                if f > self._offset}

    def _adopt(self, j: int):
        """Adopt dead peer `j`'s share exactly-once.  Under the commit
        lock: backfill the gap between j's committed offset and MY
        POLL POSITION from the replayable source (adopted groups only,
        offsets j never committed), bump the plane generation, and
        publish backfill + new ownership + floor in ONE commit.  A
        crash before the commit leaves no trace (re-detected and
        redone); a crash after is durable in MY chain
        (`recover_plane_stamps`).

        The backfill upper bound is `_offset_pending`, NOT the
        committed `_offset`: events between the two were already
        polled (and their adopted-group share filtered out while j
        still owned it) — forward ingest resumes PAST them, so
        stopping the backfill at the committed offset would lose them
        forever.  Because the adoption commit then also publishes my
        in-flight forward rows up to `_offset_pending`, it carries the
        offset property whenever the offset actually advances (still
        strictly increasing)."""
        from paimon_tpu.obs.trace import span

        dead_user = f"{self._user_base}-p{j}"
        off_j, _ = recover_checkpoint(self._sink.table, dead_user)
        off_i = self._offset_pending
        with span("stream.takeover", cat="stream", peer=j,
                  peer_offset=off_j, own_offset=off_i):
            with self._commit_lock:
                backfill = []
                cursor = off_j
                while cursor < off_i:
                    # bounded slices: a peer that died far behind must
                    # not buffer its whole gap at once — each slice is
                    # one vectorized bucket-hash (the batched router
                    # the ingest loop uses), and only the adopted
                    # subset is retained
                    polled = self.source.poll(cursor, 1 << 16)
                    if not polled:
                        break
                    window = [ev for off, ev in polled
                              if off <= off_i]
                    for ev, g in zip(window,
                                     self._event_groups(window)):
                        if g is not None and \
                                self._adopted_from(j, *g):
                            backfill.append(ev)
                    cursor = polled[-1][0]
                    if len(window) < len(polled):
                        break              # crossed off_i inside slice
                self._floors[j] = off_j
                self.plane.adopt({j})
                # ledger entry BEFORE the publishing commit so the
                # stamped PROP_ADOPTED includes j; a failed commit
                # crashes the loop and recovery re-reads the ledger
                # from the store
                self._ingest_dead = frozenset(self._ingest_dead) | {j}
                if backfill:
                    self._sink.write_events(backfill)
                # ONE commit publishes backfill + my pending forward
                # rows + bumped ownership + floor + ledger (the plane
                # stamps ride the sink's per-attempt provider;
                # force_create: with nothing buffered the stamps
                # alone must still be durable BEFORE any forward
                # write into the adopted groups)
                props = {}
                advanced = self._offset_pending > self._offset
                if advanced:
                    props[PROP_OFFSET] = str(self._offset_pending)
                    props[PROP_INGEST_TS] = str(
                        self._batch_first_pull_ms or _now_ms())
                ckpt = self._next_ckpt
                self._sink.commit(ckpt, properties=props,
                                  force_create=True)
                self._next_ckpt = ckpt + 1
                if advanced:
                    self._offset = self._offset_pending
                    self._batch_first_pull_ms = None
                self.plane.note_renewal()
        # hand the adopted buckets to the serve loop (it catches up
        # from the dead peer's persisted consumer position first)
        self._serve_adoptions.append(j)

    # -- coordinated rejoin (plane mode) -------------------------------------

    def _queue_rejoin_work(self) -> None:
        """Detector-cadence rejoin bookkeeping (compact loop): queue
        floor-stamp acks for peers some granter readmitted while MY
        ledger still holds them, and — on the elected granter — queue
        readmission grants for dead peers with a fresh rejoin
        request, but only once EVERY alive host's durable ledger
        covers them.  That ledger gate is the global drain of
        in-flight adoptions: readmitting earlier would strand a
        survivor's unpublished share of the victim's groups in a
        generation that no longer re-shards them to it."""
        back = frozenset(self._ingest_dead) - \
            frozenset(self.plane.ownership.dead)
        for j in sorted(back):
            if j not in self._pending_rejoin_acks:
                self._pending_rejoin_acks.append(j)
        if not self.plane.owns_rejoin_grant():
            return
        asking = self.plane.pending_rejoin_requests() - \
            frozenset(self._pending_rejoins)
        if not asking:
            return
        alive = [p for p in range(self.plane.process_count)
                 if p not in self.plane.ownership.dead]
        ledgers = {q: recover_plane_stamps(
            self.table, f"{self._user_base}-p{q}")[0] for q in alive}
        for j in sorted(asking):
            if all(j in ledgers[q] for q in alive):
                self._pending_rejoins.append(j)

    def _release_rejoined(self, returned) -> None:
        """Forget adopted state for peers that are alive again: their
        groups are theirs, my floors for them can only mis-suppress
        (the governing map is their NEW generation), and the serve
        loop must stop shipping their changelog."""
        self._ingest_dead = frozenset(self._ingest_dead) - returned
        for j in returned:
            self._floors.pop(j, None)
        self._serve_dead = frozenset(self._serve_dead) - returned
        self._serve_adoptions[:] = [j for j in self._serve_adoptions
                                    if j not in returned]

    def _ack_rejoins(self) -> None:
        """A granter readmitted peers MY durable ledger still holds:
        stop writing their groups and stamp MY rejoin floor —
        'everything I ever wrote into your groups is committed and
        ends here'.  ONE forced commit carries the floor together
        with my pending forward rows, so the floor is never published
        without the rows it bounds; the rejoiner's gap replay (up to
        the max granted floor) then supersedes my copies in offset
        order."""
        from paimon_tpu.parallel.distributed import rejoin_floor_props
        with self._commit_lock:
            back = frozenset(self._pending_rejoin_acks) - \
                frozenset(self.plane.ownership.dead)
            self._pending_rejoin_acks.clear()
            back = back & frozenset(self._ingest_dead)
            if not back:
                return
            props = {}
            advanced = self._offset_pending > self._offset
            if advanced:
                props[PROP_OFFSET] = str(self._offset_pending)
                props[PROP_INGEST_TS] = str(
                    self._batch_first_pull_ms or _now_ms())
            for j in sorted(back):
                props.update(rejoin_floor_props(
                    self.plane.process_index, j,
                    self.plane.ownership.version,
                    self._offset_pending))
            self._release_rejoined(back)
            ckpt = self._next_ckpt
            self._sink.commit(ckpt, properties=props,
                              force_create=True)
            self._next_ckpt = ckpt + 1
            if advanced:
                self._offset = self._offset_pending
                self._batch_first_pull_ms = None
            self.plane.note_renewal()

    def _grant_rejoins(self) -> None:
        """The elected granter readmits every queued requester in ONE
        generation bump: one forced commit publishes the new map
        (requesters back ALIVE — the salted-crc32 shard hands each
        exactly its old primary groups, warm), the full generation
        history, MY rejoin floor for each, and my pending forward
        rows.  `fleet.rejoins` counts inside `readmit`, on the
        granter — two victims rejoining render rejoins 2."""
        from paimon_tpu.obs.trace import span
        from paimon_tpu.parallel.distributed import rejoin_floor_props
        returning = list(self._pending_rejoins)
        self._pending_rejoins.clear()
        with span("stream.rejoin.grant", cat="stream",
                  peers=returning):
            with self._commit_lock:
                granted = self.plane.readmit(returning)
                if not granted:
                    return
                props = {}
                advanced = self._offset_pending > self._offset
                if advanced:
                    props[PROP_OFFSET] = str(self._offset_pending)
                    props[PROP_INGEST_TS] = str(
                        self._batch_first_pull_ms or _now_ms())
                for j in sorted(granted):
                    props.update(rejoin_floor_props(
                        self.plane.process_index, j,
                        self.plane.ownership.version,
                        self._offset_pending))
                self._release_rejoined(granted)
                ckpt = self._next_ckpt
                self._sink.commit(ckpt, properties=props,
                                  force_create=True)
                self._next_ckpt = ckpt + 1
                if advanced:
                    self._offset = self._offset_pending
                    self._batch_first_pull_ms = None
                self.plane.note_renewal()

    def _rejoin(self) -> bool:
        """Blocking rejoin phase of a resurrected host (the ingest
        loop enters here when the plane constructed in the
        `rejoining` state):

          1. publish/refresh the rejoin request at lease cadence
             until the elected survivor readmits us;
          2. wait for a rejoin floor from every peer that was alive
             in the generation right before readmission — each floor
             bounds that peer's writes into our groups.  Peers
             readmitted WITH us never wrote past the survivors'
             floors (their adopted shares cascaded to the survivors
             when they died), and a peer that dies while we wait is
             dropped from the wait — its committed writes re-ingest
             idempotently past our replay;
          3. replay the offset gap (own committed, max floor] for the
             groups we own under the new map, in offset order, as ONE
             forced commit stamping offset=floor, then resume forward
             ingest past it.

        Returns False when killed/stopped mid-phase.  Crash-safe: a
        restart after readmission but before the replay commit finds
        `rejoining` already False and falls back to plain forward
        ingest from its committed offset, which re-writes the same
        gap rows (upsert-idempotent) under normal checkpoints."""
        from paimon_tpu.obs.trace import span
        from paimon_tpu.parallel.distributed import merge_rejoin_floors

        o = self._o
        plane = self.plane
        published = False
        while plane.rejoining:
            if self._killed or self._stop.is_set():
                return False
            if not published or plane.heartbeat_due():
                with self._commit_lock:
                    plane.request_rejoin()
                published = True
            plane.refresh_view()
            plane.refresh_ownership()  # clears rejoining on readmit
            if plane.rejoining:
                self._stop.wait(o["ingest_poll_ms"] / 1000.0)
        version = plane.ownership.version
        # peers readmitted alongside us (or us alone) were DEAD in the
        # generation the grant superseded; everyone else alive there
        # may have written into our groups and owes us a floor
        prev = plane.history.at(version - 1)
        if prev is not None and \
                prev.num_processes == plane.process_count:
            need = set(prev.alive())
        else:
            need = set(p for p in range(plane.process_count)
                       if p not in plane.ownership.dead) \
                - {plane.process_index}
        # our pre-death adoption ledger may hold peers that were
        # readmitted while we were down — they replayed their own
        # gaps; holding their floors would only mis-suppress
        self._release_rejoined(frozenset(self._ingest_dead) -
                               frozenset(plane.ownership.dead))
        # and adoptions queued during recovery for peers readmitted
        # meanwhile are stale — adopting an alive peer is nonsense
        self._pending_adoptions[:] = [
            j for j in self._pending_adoptions
            if j in plane.ownership.dead]
        table = self._sink.table
        floors: Dict[int, int] = {}
        while True:
            if self._killed or self._stop.is_set():
                return False
            floors.update(merge_rejoin_floors(
                table, plane.process_index, version, max_walk=128))
            plane.refresh_view()
            plane.refresh_ownership()
            # a peer that dies before stamping its floor would block
            # us forever: drop it — its committed writes into our
            # groups re-ingest idempotently past the replay
            need -= set(plane.ownership.dead)
            if need <= set(floors):
                break
            with self._commit_lock:
                plane.maybe_heartbeat()   # stay alive while waiting
            self._stop.wait(o["ingest_poll_ms"] / 1000.0)
        floor = max(floors.values(), default=self._offset)
        replayed = 0
        with span("stream.rejoin.replay", cat="stream",
                  committed=self._offset, floor=floor):
            with self._commit_lock:
                if floor > self._offset:
                    cursor = self._offset
                    while cursor < floor:
                        polled = self.source.poll(cursor, 1 << 16)
                        if not polled:
                            break
                        window = [ev for off, ev in polled
                                  if off <= floor]
                        fm = self._forward_map()
                        batch = []
                        for (off, ev), g in zip(
                                polled[:len(window)],
                                self._event_groups(window)):
                            if g is not None and \
                                    self._owns_forward_group(off, g,
                                                             fm):
                                batch.append(ev)
                        if batch:
                            self._sink.write_events(batch)
                            replayed += len(batch)
                        cursor = polled[-1][0]
                        if len(window) < len(polled):
                            break     # crossed the floor inside slice
                    props = {PROP_OFFSET: str(floor),
                             PROP_INGEST_TS: str(_now_ms())}
                    ckpt = self._next_ckpt
                    self._sink.commit(ckpt, properties=props,
                                      force_create=True)
                    self._next_ckpt = ckpt + 1
                    self._offset = floor
                    self._offset_pending = floor
                    plane.note_renewal()
        self._rejoin_replayed += replayed
        return True

    def _plane_props(self) -> Dict[str, str]:
        """Lease + ownership + floor + adoption-ledger stamps for one
        plane-issued commit (checkpoints, compactions, heartbeats,
        backfills)."""
        props = self.plane.stamp_properties()
        props.update(self._floor_props())
        if self._ingest_dead:
            props[PROP_ADOPTED] = ",".join(
                str(p) for p in sorted(self._ingest_dead))
        return props

    def _reconcile_adoptions(self, newly=()) -> None:
        """Queue every dead peer MY ledger has not durably adopted:
        freshly-declared ones (`newly`, from my own detector) AND
        peers whose takeover another survivor already published into
        the global map — without the latter, a 3-host mesh where a
        faster survivor publishes first would leave this host's
        re-sharded share of the dead peer's buckets unwritten until
        its next restart (its detector suppresses peers already in
        `ownership.dead`).  No-op when
        multihost.maintenance.takeover is off: the detector still
        counts lease_expired, ownership stays frozen."""
        if not self.plane.takeover_enabled:
            return
        behind = frozenset(newly) | \
            (frozenset(self.plane.ownership.dead) - self._ingest_dead)
        # never self: a rejoining host recovering against a map that
        # still records IT dead must not queue its own adoption
        behind -= {self.plane.process_index}
        for j in sorted(behind):
            if j not in self._pending_adoptions and \
                    j not in self._ingest_dead:
                self._pending_adoptions.append(j)

    def _ingest_body(self):
        from paimon_tpu.metrics import (
            STREAM_EVENTS_INGESTED, STREAM_SOURCE_BACKLOG,
        )
        from paimon_tpu.obs.trace import span

        self._ingest_recover()
        if self.plane is not None and self.plane.rejoining:
            # resurrected host: blocking rejoin phase (request ->
            # readmission -> gap replay) before any forward ingest
            if not self._rejoin():
                return
        o = self._o
        last_ckpt_at = time.monotonic()
        while True:
            if self._killed:
                return
            if self.plane is not None and self._pending_adoptions:
                # adoption runs BEFORE any forward write past it: a
                # forward row in an adopted group written before the
                # backfill would end up with a LOWER sequence number
                # than the backfilled (older) row and lose the merge
                self._adopt(self._pending_adoptions.pop(0))
                continue
            if self.plane is not None and self._pending_rejoin_acks:
                self._ack_rejoins()
                continue
            if self.plane is not None and self._pending_rejoins:
                # grants run only with the adoption queue drained:
                # readmission must never race my own pending backfill
                self._grant_rejoins()
                continue
            stopping = self._stop.is_set()
            events = [] if stopping else self.source.poll(
                self._offset_pending, o["max_batch"])
            now_mono = time.monotonic()
            if events:
                if self._batch_first_pull_ms is None:
                    self._batch_first_pull_ms = _now_ms()
                if self.plane is None:
                    mine = [e for _, e in events]
                else:
                    # SPMD split: every host sees the identical
                    # stream; each writes only its owned share (plus
                    # floor suppression for adopted groups).  One
                    # forward map AND one vectorized bucket-hash per
                    # poll batch — the map only changes under the
                    # commit lock, never mid-poll
                    fm = self._forward_map()
                    groups = self._event_groups(
                        [e for _, e in events])
                    mine = [e for (off, e), g in zip(events, groups)
                            if self._owns_forward_group(off, g, fm)]
                with span("stream.ingest.batch", cat="stream",
                          events=len(events), owned=len(mine),
                          first=events[0][0], last=events[-1][0]):
                    # write_events blocks on write.flush.max-bytes:
                    # THE backpressure coupling — no internal queue
                    if mine:
                        self._sink.write_events(mine)
                self._offset_pending = events[-1][0]
                self._metrics.counter(STREAM_EVENTS_INGESTED) \
                    .inc(len(mine))
            self._metrics.gauge(STREAM_SOURCE_BACKLOG).set(
                self.source.backlog(self._offset_pending))
            pending = self._offset_pending > self._offset
            if pending and (stopping or
                            (now_mono - last_ckpt_at) * 1000
                            >= o["ckpt_interval_ms"]):
                self._checkpoint()
                last_ckpt_at = time.monotonic()
            if stopping:
                return            # drained (final checkpoint above)
            if not events:
                self._stop.wait(o["ingest_poll_ms"] / 1000.0)

    def _checkpoint(self):
        from paimon_tpu.metrics import (
            STREAM_CHECKPOINT_MS, STREAM_CHECKPOINTS,
        )
        from paimon_tpu.obs.trace import span

        ckpt = self._next_ckpt
        props = {PROP_OFFSET: str(self._offset_pending),
                 PROP_INGEST_TS: str(self._batch_first_pull_ms
                                     or _now_ms())}
        # (distributed mode: lease/ownership/floor/ledger stamps ride
        # the sink's properties_provider, evaluated per CAS attempt —
        # NOT merged here, where they would be stale on retry)
        with span("stream.checkpoint", cat="stream", group="stream",
                  metric=STREAM_CHECKPOINT_MS, checkpoint=ckpt,
                  offset=self._offset_pending):
            if self.plane is None:
                self._sink.commit(ckpt, properties=props)
            else:
                with self._commit_lock:
                    # force_create: my share of the window may hold no
                    # events, but the offset (and the lease) must
                    # still advance — an offset-only stamped snapshot
                    self._sink.commit(ckpt, properties=props,
                                      force_create=True)
                    self.plane.note_renewal()
        # past this line the checkpoint is durable: advance in-memory
        # state (a crash between commit and here replays the
        # checkpoint, which filter_committed + pending-keying dedup)
        self._offset = self._offset_pending
        self._next_ckpt = ckpt + 1
        self._batch_first_pull_ms = None
        self._metrics.counter(STREAM_CHECKPOINTS).inc()
        # drop floors the committed offset has passed (they can no
        # longer suppress anything and stop being stamped)
        for j in [j for j, f in self._floors.items()
                  if f <= self._offset]:
            del self._floors[j]
        # sources that cache events may evict everything at/below the
        # now-durable offset (FileCdcSource bounds its memory this way)
        # — but NOT in distributed mode: a dead peer's un-adopted
        # offsets may still need events at/below MY offset
        commit_through = getattr(self.source, "commit_through", None)
        if commit_through is not None and self.plane is None:
            commit_through(self._offset)

    # -- compaction loop -----------------------------------------------------

    def _ingest_pressure(self) -> bool:
        from paimon_tpu.metrics import (
            STREAM_SOURCE_BACKLOG, WRITE_INFLIGHT_BYTES, global_registry,
        )

        inflight = global_registry().write_metrics() \
            .gauge(WRITE_INFLIGHT_BYTES).value
        budget = self._o["flush_max_bytes"]
        if budget and inflight > self._o["pause_ratio"] * budget:
            return True
        backlog = self._metrics.gauge(STREAM_SOURCE_BACKLOG).value
        return backlog > self._o["pause_backlog"]

    def _needs_compaction(self, table: FileStoreTable) -> bool:
        """Level/size trigger: any bucket at/over the sorted-run
        trigger (pk tables: level-0 files each count as a run, higher
        levels one run each — compact/levels.py semantics) or, for
        append tables, at/over compaction.min.file-num.  Distributed:
        only OWNED groups trigger — a peer's backlog is the peer's
        job (or the survivor's, after takeover re-owns it)."""
        snapshot = table.latest_snapshot()
        if snapshot is None:
            return False
        scan = table.new_scan()
        per_bucket: Dict[tuple, List] = {}
        for e in scan.read_entries(snapshot):
            if e.bucket == -2:
                continue
            if self.plane is not None and not self.plane.owns(
                    tuple(scan._partition_codec.from_bytes(e.partition)),
                    e.bucket):
                continue
            per_bucket.setdefault((e.partition, e.bucket), []) \
                .append(e.file)
        if not table.schema.primary_keys:
            trigger = table.options.get(
                CoreOptions.COMPACTION_MIN_FILE_NUM)
            return any(len(fs) >= trigger for fs in per_bucket.values())
        trigger = table.options.num_sorted_runs_compaction_trigger
        for files in per_bucket.values():
            runs = sum(1 for f in files if f.level == 0) + \
                len({f.level for f in files if f.level > 0})
            if runs >= trigger:
                return True
        return False

    def _expiry_floor(self, table: FileStoreTable) -> Optional[int]:
        """Lowest snapshot id the elected expiry must keep: every
        peer's newest offset-carrying checkpoint — INCLUDING a dead
        peer's, until EVERY alive process's durable adoption ledger
        covers it.  The global dead set alone is not enough: one
        survivor's published takeover puts the peer in
        `ownership.dead` while another survivor's backfill may still
        be pending, and that backfill reads the dead peer's committed
        offset — expiring it would regress the floor to -1 and
        re-deliver the peer's whole history."""
        alive = [p for p in range(self.plane.process_count)
                 if p not in self.plane.ownership.dead]
        ledgers = {p: recover_plane_stamps(
            table, f"{self._user_base}-p{p}")[0] for p in alive}
        protected = []
        for p in range(self.plane.process_count):
            if p in self.plane.ownership.dead and \
                    all(p in ledgers[q] for q in alive):
                continue          # fully adopted: offsets subsumed
            snap = find_checkpoint_snapshot(
                table, f"{self._user_base}-p{p}")
            if snap is not None:
                protected.append(snap.id)
        return min(protected) if protected else None

    def _compact_body(self):
        from paimon_tpu.metrics import (
            STREAM_COMPACTIONS, STREAM_COMPACTIONS_PAUSED,
        )
        from paimon_tpu.obs.trace import span

        o = self._o
        last_expire_at = time.monotonic()
        last_manifest_probe_at = time.monotonic()
        while not self._stop.wait(o["compact_interval_ms"] / 1000.0):
            if self.plane is not None:
                # failure-detector round: newly-expired peers (and
                # peers other survivors already published as dead)
                # queue for the ingest loop's exactly-once adoption —
                # the backfill must publish atomically with the
                # ownership bump, so the detector never adopts
                # directly here
                self._reconcile_adoptions(self.plane.detect_expired())
                # rejoin bookkeeping rides the same detector cadence:
                # queue grants (elected) and floor-stamp acks for the
                # ingest loop — like adoption, the generation change
                # must publish atomically with the rows it bounds
                self._queue_rejoin_work()
                # idle hosts still renew their lease
                with self._commit_lock:
                    self.plane.maybe_heartbeat()
            if self._ingest_pressure():
                # graceful degradation: ingest pressure wins; try
                # again next round
                self._metrics.counter(STREAM_COMPACTIONS_PAUSED).inc()
                continue
            table = FileStoreTable.load(
                self.table.path, file_io=self.table.file_io,
                dynamic_options=self._dynamic or None)
            if self._needs_compaction(table):
                with span("stream.compact", cat="stream",
                          full=o["compact_full"]):
                    if self.plane is None:
                        sid = table.compact(full=o["compact_full"])
                    else:
                        # owned groups only, committed under the
                        # per-host user with per-attempt lease/
                        # ownership stamps
                        sid = table.compact(
                            full=o["compact_full"],
                            group_filter=self.plane.group_filter(),
                            commit_user=self.commit_user,
                            properties_provider=self._plane_props)
                        if sid is not None:
                            self.plane.note_renewal()
                if sid is not None:
                    self._metrics.counter(STREAM_COMPACTIONS).inc()
            # manifest full-compaction (incremental metadata plane):
            # elected like expiry on the mesh — one host folds the
            # accumulated delta manifests once the count trigger
            # fires; CAS-committed, so a racing peer just retries.
            # Interval-gated like expiry: the trigger probe itself
            # reads the snapshot's manifest lists, so running it on
            # every 2s compact tick is continuous wasted metadata IO
            if o["manifest_compact_interval_ms"] is not None and \
                    (self.plane is None or self.plane.owns_expiry()) \
                    and (time.monotonic() - last_manifest_probe_at) \
                    * 1000 >= o["manifest_compact_interval_ms"]:
                last_manifest_probe_at = time.monotonic()
                with span("stream.compact_manifests", cat="stream"):
                    if self.plane is None:
                        msid = table.compact_manifests(force=False)
                    else:
                        msid = table.compact_manifests(
                            force=False, commit_user=self.commit_user,
                            properties_provider=self._plane_props)
                        if msid is not None:
                            self.plane.note_renewal()
            if o["expire_interval_ms"] is not None and \
                    (self.plane is None or self.plane.owns_expiry()) \
                    and (time.monotonic() - last_expire_at) * 1000 \
                    >= o["expire_interval_ms"]:
                # NEVER expire the newest offset-carrying snapshot: it
                # is the recovery point — losing it would restart the
                # source from scratch and reuse commit identifiers.
                # Widening retain_min pins everything back to it (an
                # idle source under active compaction is exactly when
                # newer non-ingest snapshots would otherwise push it
                # out of the retention window).  Distributed (expiry
                # is ELECTED, lowest-ranked alive host): protect
                # EVERY live peer's recovery point via the absolute
                # floor — a dead-but-unadopted peer's too, since a
                # takeover still needs its committed offset.
                retain_min = None
                floor_id = None
                if self.plane is None:
                    ckpt_snap = find_checkpoint_snapshot(
                        table, self.commit_user)
                    latest = \
                        table.snapshot_manager.latest_snapshot_id()
                    if ckpt_snap is not None and latest is not None:
                        retain_min = latest - ckpt_snap.id + 1
                else:
                    floor_id = self._expiry_floor(table)
                table.expire_snapshots(
                    retain_min=retain_min,
                    retain_max=None if retain_min is None else max(
                        retain_min, table.options.get(
                            CoreOptions.SNAPSHOT_NUM_RETAINED_MAX)),
                    min_retained_snapshot_id=floor_id)
                last_expire_at = time.monotonic()

    # -- changelog serving loop ----------------------------------------------

    def _serve_ownership_splits(self, splits):
        """Distributed serving: ship only the changelog of buckets
        this host owns AS FAR AS THE SERVE LOOP KNOWS (`_serve_dead`
        may lag the ingest ledger until the catch-up for an adopted
        peer has replayed its backlog — serving new deltas of adopted
        buckets before the backlog would reorder the stream)."""
        from paimon_tpu.parallel.distributed import OwnershipMap
        m = self.plane.ownership
        serve_map = OwnershipMap(m.version, m.num_processes,
                                 m.num_buckets, self._serve_dead)
        return [s for s in splits
                if serve_map.owner_of(tuple(s.partition), s.bucket)
                == self.plane.process_index]

    def _serve_catch_up(self, j: int, upto: Optional[int]) -> bool:
        """Replay the changelog of the buckets adopted from dead peer
        `j`, from the peer's persisted consumer position up to (not
        including) snapshot `upto` — where my own serve stream will
        take over.  The peer may have served rows past its recorded
        position (consumer state trails delivery); re-serving that
        suffix is upsert-idempotent for consumers, like every other
        restart in this daemon.  Returns False when killed mid-replay
        (progress is NOT recorded; the next incarnation redoes it)."""
        from dataclasses import replace

        from paimon_tpu.metrics import STREAM_CHANGELOG_ROWS
        from paimon_tpu.obs.trace import span

        table = FileStoreTable.load(
            self.table.path, file_io=self.table.file_io,
            dynamic_options=self._dynamic or None)
        cm = table.consumer_manager
        dead_consumer = f"{self._user_base}-p{j}-serve"
        pj = cm.consumer(dead_consumer)
        rb = table.new_read_builder()
        scan = rb.new_stream_scan()
        scan.restore(pj)          # None -> initial full-state replay
        with span("stream.serve.takeover", cat="stream", peer=j,
                  peer_position=pj, upto=upto):
            while True:
                if self._killed:
                    return False
                was_first = scan._first
                plan = scan.plan()
                if plan is None:
                    break
                if not was_first and upto is not None and \
                        plan.snapshot_id is not None and \
                        plan.snapshot_id >= upto:
                    break         # my own stream serves from here on
                if plan.splits:
                    splits = [s for s in plan.splits
                              if self._adopted_from(
                                  j, tuple(s.partition), s.bucket)]
                    if splits:
                        rows = rb.new_read().to_arrow(
                            replace(plan, splits=splits)).to_pylist()
                        if not self._emit(rows):
                            return False
                        self._metrics.counter(STREAM_CHANGELOG_ROWS) \
                            .inc(len(rows))
        # release the dead consumer's expiry pin: my own consumer
        # carries the adopted buckets from `upto` onward
        if upto is not None:
            cm.record_consumer(dead_consumer, upto)
        return True

    def _serve_body(self):
        from paimon_tpu.metrics import (
            STREAM_CHANGELOG_ROWS, STREAM_FRESHNESS_MS,
        )
        from paimon_tpu.obs.trace import span

        # persist serving progress as consumer state so a restarted
        # serving loop (or daemon incarnation) RESUMES the stream
        # instead of full-rescanning — resuming replays every delta
        # (including delete tombstones) exactly from where consumers
        # last got rows, and re-served batches are upsert-idempotent
        table = FileStoreTable.load(
            self.table.path, file_io=self.table.file_io,
            dynamic_options={**self._dynamic,
                             "consumer-id": f"{self.commit_user}-serve"})
        rb = table.new_read_builder()
        scan = rb.new_stream_scan()
        while True:
            if self._killed:
                return
            if self.plane is not None and self._serve_adoptions:
                # adopted-bucket catch-up runs IN the serve thread so
                # the main stream cannot advance underneath it: replay
                # the dead peer's backlog up to my current position,
                # then fold the adopted buckets into my own filter
                j = self._serve_adoptions[0]
                upto = scan.checkpoint()
                if upto is None:
                    # my own stream has not started: its initial
                    # full-state scan will cover the adopted buckets
                    self._serve_adoptions.pop(0)
                    self._serve_dead = \
                        frozenset(self._serve_dead) | {j}
                    continue
                if not self._serve_catch_up(j, upto):
                    return        # killed mid-replay
                self._serve_adoptions.pop(0)
                self._serve_dead = frozenset(self._serve_dead) | {j}
                continue
            was_first = scan._first
            plan = scan.plan()
            if plan is None:
                if self._stop.is_set() and not self._ingest_alive():
                    # caught up AND the final checkpoint (committed by
                    # the ingest loop before it exited) has been served
                    return
                self._stop.wait(self._o["serve_poll_ms"] / 1000.0)
                continue
            if self.plane is not None:
                from dataclasses import replace
                plan = replace(
                    plan,
                    splits=self._serve_ownership_splits(plan.splits))
            if plan.splits:
                with span("stream.serve.batch", cat="stream",
                          snapshot=plan.snapshot_id) as sp:
                    rows = rb.new_read().to_arrow(plan).to_pylist()
                    # freshness is only meaningful for follow-up
                    # deltas (a startup full scan spans all history)
                    freshness = None if was_first else \
                        self._freshness_ms(table, plan.snapshot_id)
                    if freshness is not None:
                        # event -> visible-in-changelog-scan latency,
                        # from the ingest ts the checkpoint committed
                        self._metrics.histogram(STREAM_FRESHNESS_MS) \
                            .update(freshness)
                        sp.set(freshness_ms=freshness)
                if not self._emit(rows):
                    return          # killed while blocked on the buffer
                self._metrics.counter(STREAM_CHANGELOG_ROWS) \
                    .inc(len(rows))
            # rows are delivered (bounded buffer): record consumer
            # progress so a restart resumes past this snapshot
            scan.notify_checkpoint_complete(scan.checkpoint())

    def _freshness_ms(self, table: FileStoreTable,
                      snapshot_id: Optional[int]) -> Optional[float]:
        if snapshot_id is None:
            return None
        try:
            snap = table.snapshot_manager.snapshot(snapshot_id)
        except (FileNotFoundError, OSError):
            return None
        props = snap.properties or {}
        if PROP_INGEST_TS not in props:
            return None           # not one of our ingest checkpoints
        return max(0.0, _now_ms() - int(props[PROP_INGEST_TS]))

    def _emit(self, rows: List[dict]) -> bool:
        """Bounded blocking enqueue: the serving loop stalls (never
        drops, never grows without bound) while consumers lag.  False
        when killed while waiting — the rows were NOT delivered, so
        the caller must not record progress past them."""
        cap = self._o["serve_buffer_rows"]
        i = 0
        with self._buf_cond:
            while i < len(rows):
                while len(self._buf) >= cap and not self._killed:
                    self._buf_cond.wait(0.2)
                if self._killed:
                    # partially-delivered batch: progress is NOT
                    # recorded, the next incarnation re-serves it
                    # (upsert-idempotent for consumers)
                    return False
                take = max(1, cap - len(self._buf))
                self._buf.extend(rows[i:i + take])
                i += take
                self._buf_cond.notify_all()
        return True
