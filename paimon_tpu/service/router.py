"""Horizontal read replicas: N query servers behind one router.

Mirrors the reference's dedicated query-service topology
(paimon-service/: a fleet of KvQueryServers fronted by address
discovery) scaled onto this repo's serving plane:

* `ReplicaSet` runs N `KvQueryServer` replicas over ONE table in this
  process.  They share everything sharable — the process-wide byte
  cache (`fs/caching.shared_cache_state`), the host-SSD tier, and the
  hot delta tier (`service/delta.py`, shared by table path) — while
  each replica pins its own snapshot plan (`LocalTableQuery`) and owns
  its own admission budget.  Snapshot advance on ANY replica
  invalidates dropped files for EVERY replica through the existing
  `evict_dropped_file()` hook: the byte-cache tier is process-wide, so
  one replica's plan reload evicts the stale blocks everywhere before
  its new plan serves.
* `ReplicaRouter` fronts the replicas with CONSISTENT HASHING of
  tenants (`service.replicas.virtual-nodes` points per replica on a
  sha1 ring): one tenant's requests always land on the same replica —
  its SSTs, pinned blocks and changelog consumer state stay warm there
  — and adding/removing a replica moves only ~1/N of the tenants.
  The router is itself an event-loop server (service/async_server.py);
  it answers:

      POST /lookup /scan /changelog   forwarded to the owning replica
      POST /register                  {"id", "address"}: a replica on
                                      ANOTHER MACHINE joins the ring
      POST /deregister                {"id"}: planned leave
      GET  /topology                  the ring: replica ids+addresses
      GET  /healthz                   per-replica healthz + a rollup
      GET  /metrics                   Prometheus; remote replicas are
                                      re-labeled replica="<id>"

  In-process replicas are dispatched DIRECTLY (function call, no
  second TCP hop — Netty's local channel, in spirit); remote replicas
  (other processes sharing the SSD tier) forward over pooled
  keep-alive connections.  Registered remotes are health-checked every
  `service.replicas.health-interval`: two consecutive failed GET
  /healthz probes suspend a replica OUT of the ring (its tenants
  rehash to survivors), the first success re-admits it — in-process
  replicas are never probed, their liveness is the process's.
* smart clients skip the hop entirely: `KvQueryClient` fetches
  /topology once, builds the SAME ring, and talks to the owning
  replica directly — the router is the dumb-client path and the
  topology authority, not a mandatory proxy.
"""

from __future__ import annotations

import hashlib
import http.client
import json
import re
import threading
from bisect import bisect_right
from typing import Dict, List, Optional

from paimon_tpu.options import CoreOptions
from paimon_tpu.service.async_server import (
    AsyncHttpServer, HttpRequest, HttpResponse,
)

__all__ = ["HashRing", "ReplicaRouter", "ReplicaSet"]


class HashRing:
    """Consistent-hash ring: `vnodes` sha1 points per node; a key maps
    to the first point clockwise.  Client and router build IDENTICAL
    rings from the same (id, address) list, so direct-to-replica
    routing agrees with proxied routing."""

    def __init__(self, nodes: List[dict], vnodes: int = 64):
        self.nodes = list(nodes)
        self.vnodes = max(1, int(vnodes))
        points = []
        for node in self.nodes:
            ident = f"{node['id']}:{node['address']}"
            for v in range(self.vnodes):
                h = int.from_bytes(hashlib.sha1(
                    f"{ident}#{v}".encode()).digest()[:8], "big")
                points.append((h, node))
        points.sort(key=lambda p: p[0])
        self._hashes = [p[0] for p in points]
        self._points = [p[1] for p in points]

    def pick(self, tenant: str) -> dict:
        if not self._points:
            raise RuntimeError("empty hash ring")
        h = int.from_bytes(
            hashlib.sha1(str(tenant).encode()).digest()[:8], "big")
        i = bisect_right(self._hashes, h) % len(self._points)
        return self._points[i]


class _UpstreamPool:
    """Tiny keep-alive connection pool per upstream address (the
    router's forwarding path for REMOTE replicas)."""

    def __init__(self, address: str, timeout: float = 60.0):
        hostport = address.rstrip("/").split("://", 1)[-1]
        host, _, port = hostport.partition(":")
        self.host, self.port = host, int(port) if port else 80
        self.timeout = timeout
        self._idle: List[http.client.HTTPConnection] = []
        self._lock = threading.Lock()

    def request(self, method: str, path: str, body: bytes,
                headers: Dict[str, str]):
        """One proxied round trip; returns (status, body, headers).
        A dead pooled socket retries once on a fresh connection."""
        for attempt in (0, 1):
            with self._lock:
                conn = self._idle.pop() if self._idle else None
            fresh = conn is None
            if fresh:
                conn = http.client.HTTPConnection(
                    self.host, self.port, timeout=self.timeout)
            try:
                conn.request(method, path, body, headers)
                resp = conn.getresponse()
                data = resp.read()
                out_headers = dict(resp.getheaders())
                status = resp.status
            # lint-ok: fault-taxonomy stale keep-alive reconnect,
            # deliberately narrower than the store ladder: one resend
            # on a reused pooled socket, a fresh connection's failure
            # raises immediately
            except (http.client.HTTPException, ConnectionError,
                    OSError):
                conn.close()
                if fresh or attempt:
                    raise
                continue
            with self._lock:
                if len(self._idle) < 32:
                    self._idle.append(conn)
                else:
                    conn.close()
            return status, data, out_headers

    def close(self):
        with self._lock:
            for c in self._idle:
                c.close()
            self._idle.clear()


class ReplicaRouter:
    """Consistent-hash front end over replicas (see module docstring).
    Construct with in-process `servers` (direct dispatch) or remote
    `addresses` (HTTP forwarding) — or a mix, keyed by replica id."""

    def __init__(self, servers: Optional[List] = None,
                 addresses: Optional[Dict[int, str]] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 vnodes: Optional[int] = None,
                 workers: Optional[int] = None, table_name: str = ""):
        self._local: Dict[int, object] = {
            s.replica_id: s for s in (servers or [])}
        self._remote: Dict[int, _UpstreamPool] = {
            int(i): _UpstreamPool(a)
            for i, a in (addresses or {}).items()}
        entries = [{"id": s.replica_id, "address": s.address}
                   for s in (servers or [])]
        entries += [{"id": int(i), "address": a}
                    for i, a in (addresses or {}).items()]
        if not entries:
            raise ValueError("router needs at least one replica")
        entries.sort(key=lambda e: e["id"])
        self.replicas = entries
        if servers and not table_name:
            table_name = servers[0].table.name
        opts_holder = servers[0].options if servers else None
        if vnodes is None:
            vnodes = opts_holder.get(CoreOptions.SERVICE_REPLICA_VNODES) \
                if opts_holder is not None else 64
        if workers is None:
            workers = opts_holder.get(CoreOptions.SERVICE_WORKERS) \
                if opts_holder is not None else 16
        self._vnodes = vnodes
        self._health_interval_ms = opts_holder.get(
            CoreOptions.SERVICE_REPLICA_HEALTH_INTERVAL) \
            if opts_holder is not None else 1_000
        # membership state: `_lock` guards replicas/_remote/_suspended
        # mutation; `self.ring` swaps ATOMICALLY (readers pick off
        # whatever ring reference they loaded — no read-side lock)
        self._membership_lock = threading.Lock()
        self._suspended: set = set()
        self._fail_counts: Dict[int, int] = {}
        self._health_stop = threading.Event()
        self._health_thread: Optional[threading.Thread] = None
        self.ring = HashRing(entries, vnodes)
        from paimon_tpu.metrics import (
            SERVICE_ROUTER_FORWARDED, SERVICE_ROUTER_RING_CHANGES,
            SERVICE_ROUTER_UPSTREAM_ERRORS, global_registry,
        )
        g = global_registry().service_metrics(table_name)
        self._m_forwarded = g.counter(SERVICE_ROUTER_FORWARDED)
        self._m_upstream_errors = g.counter(
            SERVICE_ROUTER_UPSTREAM_ERRORS)
        self._m_ring_changes = g.counter(SERVICE_ROUTER_RING_CHANGES)
        self.server = AsyncHttpServer(
            host, port, self._handle, workers=workers,
            name="paimon-router")
        self.port = self.server.port
        self.address = f"http://{host}:{self.port}"

    def start(self) -> "ReplicaRouter":
        self.server.start()
        from paimon_tpu.parallel.executors import spawn_thread
        self._health_thread = spawn_thread(
            self._health_loop, name="paimon-router-health")
        return self

    def stop(self):
        self._health_stop.set()
        if self._health_thread is not None:
            self._health_thread.join(timeout=5.0)
        self.server.stop()
        for pool in self._remote.values():
            pool.close()

    # -- membership ----------------------------------------------------------

    def _rebuild_ring_locked(self):
        """Swap in a fresh ring over the non-suspended membership.
        Caller holds `_membership_lock`; readers keep using whichever
        ring reference they already loaded."""
        live = [e for e in self.replicas
                if e["id"] not in self._suspended]
        self.ring = HashRing(live, self._vnodes)
        self._m_ring_changes.inc()

    def register_replica(self, rid: int, address: str) -> None:
        """Admit (or re-admit with a new address) a REMOTE replica.
        Registering an id that is currently suspended clears the
        suspension — the replica is announcing it is back."""
        rid = int(rid)
        with self._membership_lock:
            if rid in self._local:
                raise ValueError(
                    f"replica {rid} is in-process; cannot re-register")
            old_pool = self._remote.get(rid)
            self._remote[rid] = _UpstreamPool(address)
            self.replicas = (
                [e for e in self.replicas if e["id"] != rid]
                + [{"id": rid, "address": address}])
            self.replicas.sort(key=lambda e: e["id"])
            self._suspended.discard(rid)
            self._fail_counts.pop(rid, None)
            self._rebuild_ring_locked()
        if old_pool is not None:
            old_pool.close()

    def deregister_replica(self, rid: int) -> bool:
        """Planned leave: drop a remote replica from ring + membership.
        Returns False for unknown or in-process ids."""
        rid = int(rid)
        with self._membership_lock:
            if rid in self._local or rid not in self._remote:
                return False
            pool = self._remote.pop(rid)
            self.replicas = [e for e in self.replicas
                             if e["id"] != rid]
            self._suspended.discard(rid)
            self._fail_counts.pop(rid, None)
            self._rebuild_ring_locked()
        pool.close()
        return True

    def _health_loop(self):
        """Probe REMOTE replicas every `service.replicas.health-
        interval`: 2 consecutive failures suspend one out of the ring,
        the first success re-admits it.  In-process replicas are never
        probed."""
        interval = max(0.05, self._health_interval_ms / 1000.0)
        while not self._health_stop.wait(interval):
            with self._membership_lock:
                targets = list(self._remote.items())
            for rid, pool in targets:
                ok = False
                try:
                    status, _, _ = pool.request("GET", "/healthz",
                                                b"", {})
                    ok = status == 200
                except Exception:      # noqa: BLE001
                    self._m_upstream_errors.inc()
                with self._membership_lock:
                    if rid not in self._remote:
                        continue       # deregistered mid-probe
                    if ok:
                        self._fail_counts.pop(rid, None)
                        if rid in self._suspended:
                            self._suspended.discard(rid)
                            self._rebuild_ring_locked()
                    else:
                        n = self._fail_counts.get(rid, 0) + 1
                        self._fail_counts[rid] = n
                        if n >= 2 and rid not in self._suspended:
                            self._suspended.add(rid)
                            self._rebuild_ring_locked()

    # -- dispatch ------------------------------------------------------------

    def _handle(self, req: HttpRequest) -> HttpResponse:
        if req.method == "GET":
            if req.path == "/topology":
                with self._membership_lock:
                    replicas = list(self.replicas)
                    suspended = sorted(self._suspended)
                return HttpResponse(200, json.dumps(
                    {"replicas": replicas,
                     "suspended": suspended,
                     "virtual_nodes": self.ring.vnodes,
                     "router": True}).encode())
            if req.path == "/healthz":
                return self._healthz()
            if req.path == "/metrics":
                return self._metrics()
            if req.path == "/slo":
                return self._slo()
            return HttpResponse(404, b'{"error": "not found"}')
        if req.method == "POST" and req.path in ("/register",
                                                 "/deregister"):
            return self._handle_membership(req)
        if req.method != "POST" or req.path not in (
                "/lookup", "/scan", "/changelog"):
            return HttpResponse(404, b'{"error": "not found"}')
        try:
            body = json.loads(req.body or b"{}")
            tenant = str(body.get("tenant") or "default")
        except ValueError:
            return HttpResponse(400, b'{"error": "invalid JSON"}')
        node = self.ring.pick(tenant)
        self._m_forwarded.inc()
        return self._forward(node, req)

    def _handle_membership(self, req: HttpRequest) -> HttpResponse:
        try:
            body = json.loads(req.body or b"{}")
            rid = int(body["id"])
        except (ValueError, KeyError, TypeError):
            return HttpResponse(
                400, b'{"error": "expected {\\"id\\": int}"}')
        if req.path == "/register":
            address = str(body.get("address") or "")
            if not address.startswith("http"):
                return HttpResponse(
                    400, b'{"error": "expected an http address"}')
            try:
                self.register_replica(rid, address)
            except ValueError as e:
                return HttpResponse(
                    409, json.dumps({"error": str(e)}).encode())
            return HttpResponse(200, json.dumps(
                {"registered": rid,
                 "replica_count": len(self.replicas)}).encode())
        if not self.deregister_replica(rid):
            return HttpResponse(
                404, json.dumps(
                    {"error": f"unknown remote replica {rid}"}
                ).encode())
        return HttpResponse(200, json.dumps(
            {"deregistered": rid,
             "replica_count": len(self.replicas)}).encode())

    def _forward(self, node: dict, req: HttpRequest) -> HttpResponse:
        rid = node["id"]
        local = self._local.get(rid)
        if local is not None:
            # in-process replica: direct dispatch, no second TCP hop
            return local._handle(req)
        pool = self._remote.get(rid)
        if pool is None:       # deregistered between pick and forward
            self._m_upstream_errors.inc()
            return HttpResponse(
                502, json.dumps({"error": f"replica {rid} left the "
                                          f"ring"}).encode(),
                headers={"X-Replica-Id": str(rid)})
        fwd_headers = {"Content-Type": "application/json"}
        if "x-request-timeout-ms" in req.headers:
            fwd_headers["X-Request-Timeout-Ms"] = \
                req.headers["x-request-timeout-ms"]
        # propagate the trace context: the router's own serve.request
        # span (adopted from the client by the engine) is current on
        # this thread, so the replica links to the router hop and the
        # router hop links to the client — the full chain survives
        # the extra network boundary
        from paimon_tpu.obs.trace import inject_headers
        inject_headers(fwd_headers)
        try:
            status, data, up_headers = pool.request(
                "POST", req.path, req.body, fwd_headers)
        except (http.client.HTTPException, ConnectionError,
                OSError) as e:
            self._m_upstream_errors.inc()
            return HttpResponse(
                502, json.dumps({"error": f"replica {rid} "
                                          f"unreachable: {e}"}).encode(),
                headers={"X-Replica-Id": str(rid)})
        headers = {"X-Replica-Id":
                   up_headers.get("X-Replica-Id", str(rid))}
        return HttpResponse(status, data, headers=headers)

    # -- aggregation ---------------------------------------------------------

    def _replica_get(self, rid: int, path: str):
        """GET `path` from one replica (direct for local, HTTP for
        remote); returns parsed JSON or raw text depending on path."""
        local = self._local.get(rid)
        if local is not None:
            resp = local._handle(HttpRequest("GET", path, {}, b"",
                                             True))
            return resp.status, resp.body
        status, data, _ = self._remote[rid].request(
            "GET", path, b"", {})
        return status, data

    def _healthz(self) -> HttpResponse:
        """Aggregated health: per-replica /healthz plus a rollup —
        the fleet is as degraded as its most degraded replica."""
        per: Dict[str, object] = {}
        worst = 0
        ok = True
        with self._membership_lock:
            replicas = list(self.replicas)
            suspended = set(self._suspended)
        for e in replicas:
            rid = e["id"]
            if rid in suspended:
                per[str(rid)] = {"suspended": True}
                ok = False
                continue
            try:
                status, body = self._replica_get(rid, "/healthz")
                h = json.loads(body)
                if status != 200:
                    ok = False
                worst = max(worst, int(h.get("brownout_level") or 0))
            except Exception as exc:      # noqa: BLE001
                self._m_upstream_errors.inc()
                h = {"error": str(exc)}
                ok = False
            per[str(rid)] = h
        return HttpResponse(200, json.dumps({
            "router": True,
            "status": "ok" if ok and worst == 0 else "degraded",
            "brownout_level_max": worst,
            "replica_count": len(replicas),
            "suspended": sorted(suspended),
            "replicas": per}).encode())

    def _metrics(self) -> HttpResponse:
        """Prometheus across the fleet.  In-process replicas share ONE
        registry — render it once.  Remote replicas' texts are
        federated with a replica="<id>" label injected per series, so
        same-named series never collide."""
        parts: List[str] = []
        if self._local:
            from paimon_tpu.obs.export import render_prometheus
            parts.append(render_prometheus())
        with self._membership_lock:
            remotes = list(self._remote.items())
        for rid, pool in remotes:
            try:
                status, data, _ = pool.request("GET", "/metrics", b"",
                                               {})
                if status == 200:
                    parts.append(_relabel_prometheus(
                        data.decode(), rid))
            except Exception:      # noqa: BLE001
                self._m_upstream_errors.inc()
        return HttpResponse(
            200, "\n".join(parts).encode(),
            content_type="text/plain; version=0.0.4; charset=utf-8")

    def _slo(self) -> HttpResponse:
        """Fleet-wide SLO rollup: per-replica /slo documents folded by
        obs/slo.aggregate_slo — the fleet burns at the WORST replica's
        rate (an SLO is violated wherever any user lands) and alerts
        on the OR.  Unreachable/suspended replicas degrade the answer
        to partial instead of failing it, same contract as /metrics
        federation."""
        from paimon_tpu.obs.slo import aggregate_slo
        per: Dict[str, Dict] = {}
        with self._membership_lock:
            replicas = list(self.replicas)
            suspended = set(self._suspended)
        for e in replicas:
            rid = e["id"]
            if rid in suspended:
                per[str(rid)] = {"suspended": True}
                continue
            try:
                status, body = self._replica_get(rid, "/slo")
                doc = json.loads(body)
                per[str(rid)] = doc if status == 200 else \
                    {"error": doc}
            except Exception as exc:      # noqa: BLE001
                self._m_upstream_errors.inc()
                per[str(rid)] = {"error": str(exc)}
        agg = aggregate_slo(per)
        agg["router"] = True
        agg["suspended"] = sorted(suspended)
        return HttpResponse(200, json.dumps(agg).encode())


_SERIES_RE = re.compile(
    r"^(?P<name>[A-Za-z_:][A-Za-z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?"
    r"(?P<rest>\s.*)$")


def _relabel_prometheus(text: str, replica_id: int) -> str:
    """Inject replica="<id>" into every series line of one replica's
    exposition text (comments/HELP/TYPE pass through)."""
    out = []
    label = f'replica="{replica_id}"'
    for line in text.splitlines():
        if not line or line.startswith("#"):
            out.append(line)
            continue
        m = _SERIES_RE.match(line)
        if m is None:
            out.append(line)
            continue
        labels = m.group("labels")
        merged = f"{label},{labels}" if labels else label
        out.append(f"{m.group('name')}{{{merged}}}{m.group('rest')}")
    return "\n".join(out)


class ReplicaSet:
    """N in-process replicas + the fronting router over one table.

        rs = ReplicaSet(table, replicas=4).start()
        client = KvQueryClient(address=rs.address)   # follows /topology
        ...
        rs.stop()

    The replicas share the process byte-cache/SSD/delta tiers; the
    router's address is what gets registered in the table's service
    directory (clients discover the ROUTER, then the ring)."""

    def __init__(self, table, replicas: Optional[int] = None,
                 host: str = "127.0.0.1"):
        from paimon_tpu.service.query_service import (
            PRIMARY_KEY_LOOKUP, KvQueryServer, ServiceManager,
        )
        n = int(replicas if replicas is not None
                else table.options.get(CoreOptions.SERVICE_REPLICAS))
        if n < 1:
            raise ValueError(f"service.replicas must be >= 1, got {n}")
        self.table = table
        self.servers = [KvQueryServer(table, host=host, replica_id=i)
                        for i in range(n)]
        self.router = ReplicaRouter(servers=self.servers, host=host)
        self.address = self.router.address
        self._services = ServiceManager(table.file_io, table.path)
        self._service_name = PRIMARY_KEY_LOOKUP

    def start(self) -> "ReplicaSet":
        for s in self.servers:
            # replicas serve but do NOT register: the ROUTER is the
            # discoverable address (KvQueryServer.start would register
            # each replica over the previous one)
            s.server.start()
        self.router.start()
        self._services.register(self._service_name, self.address)
        return self

    def stop(self):
        self._services.unregister(self._service_name)
        self.router.stop()
        for s in self.servers:
            s.shutdown()       # replicas never registered themselves

    def new_serving_writer(self, commit_user: Optional[str] = None):
        """The fleet's serving writer: the delta tier is shared, so a
        write is immediately visible on EVERY replica."""
        return self.servers[0].new_serving_writer(commit_user)

    def __enter__(self) -> "ReplicaSet":
        return self

    def __exit__(self, *exc):
        self.stop()
        return False
