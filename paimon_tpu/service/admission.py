"""Admission control for the query-serving plane: per-tenant in-flight
byte budgets with a bounded wait queue.

The serving-side counterpart of the scan pipeline's
`read.prefetch.max-bytes` throttle (parallel/scan_pipeline.py): every
request is charged an ESTIMATED byte cost before any heavy work runs;
requests that would push the process (or their tenant) over budget
queue — bounded, with a timeout that turns into HTTP 429 — instead of
oversubscribing memory.  Capacity drains to waiters LARGEST-FIRST
(the LPT discipline of parallel/packing.py: freeing one big admission
unblocks the most bytes per wakeup), with the scan pipeline's
anti-stall rule — an idle budget always admits one request, so a
single request larger than the whole budget cannot wedge the service.

Observability: queue depth / in-flight bytes gauges, admission-wait
histogram and admitted/rejected counters in the `service` metric
group; per-tenant in-flight bytes render as one gauge per tenant
(group("service", tenant) -> prometheus label table="<tenant>").
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

__all__ = ["AdmissionController", "AdmissionRejected", "AdmissionTicket"]

DEFAULT_TENANT = "default"
DEFAULT_PRIORITY = 100


class AdmissionRejected(RuntimeError):
    """Raised when a request cannot be admitted: the wait queue is
    full, or the byte budget did not free up within the queue timeout.
    The HTTP layer maps this to 429."""

    status = 429


class _Waiter:
    __slots__ = ("bytes", "tenant", "event", "admitted", "enqueued_at")

    def __init__(self, nbytes: int, tenant: str):
        self.bytes = nbytes
        self.tenant = tenant
        self.event = threading.Event()
        self.admitted = False
        self.enqueued_at = time.perf_counter()


class AdmissionTicket:
    """Held while a request runs; releasing returns the bytes to the
    budget and drains the queue.  Context-manager form preferred."""

    def __init__(self, controller: "AdmissionController", nbytes: int,
                 tenant: str):
        self._controller = controller
        self.bytes = nbytes
        self.tenant = tenant
        self._released = False

    def release(self):
        if not self._released:
            self._released = True
            self._controller._release(self)

    def __enter__(self) -> "AdmissionTicket":
        return self

    def __exit__(self, *exc):
        self.release()
        return False


class AdmissionController:
    def __init__(self, max_bytes: int,
                 tenant_max_bytes: Optional[int] = None,
                 queue_depth: int = 256,
                 queue_timeout_ms: int = 10_000,
                 table: str = ""):
        self.max_bytes = max(1, int(max_bytes))
        # `is not None`, not truthiness: an explicit 0 means "throttle
        # every tenant to the one-request anti-starvation minimum",
        # the opposite of the unlimited default
        self.tenant_max_bytes = int(tenant_max_bytes) \
            if tenant_max_bytes is not None else self.max_bytes
        self.queue_depth = max(0, int(queue_depth))
        self.queue_timeout_ms = max(0, int(queue_timeout_ms))
        self._lock = threading.Lock()
        self._inflight = 0
        self._tenant_inflight: Dict[str, int] = {}
        self._waiters: List[_Waiter] = []
        from paimon_tpu.metrics import (
            SERVICE_ADMISSION_WAIT_MS, SERVICE_INFLIGHT_BYTES,
            SERVICE_QUEUE_DEPTH, SERVICE_REJECTED, SERVICE_REQUESTS,
            global_registry,
        )
        self._registry = global_registry()
        g = self._registry.service_metrics(table)
        self._m_requests = g.counter(SERVICE_REQUESTS)
        self._m_rejected = g.counter(SERVICE_REJECTED)
        self._m_wait = g.histogram(SERVICE_ADMISSION_WAIT_MS)
        from paimon_tpu.metrics import RESILIENCE_BROWNOUT_SHEDS
        self._m_sheds = self._registry.resilience_metrics() \
            .counter(RESILIENCE_BROWNOUT_SHEDS)
        # brownout rung 2 (service/brownout.py): requests with
        # priority below this are shed immediately with 429 — the
        # lowest-priority tenants lose service first, the high-
        # priority path keeps its byte budget
        self._shed_below = 0
        # explicitly-set gauges (not fn-backed): a later controller on
        # the same table must take the series over, not leave a stale
        # closure pointing at a dead instance
        self._g_queue = g.gauge(SERVICE_QUEUE_DEPTH)
        self._g_inflight = g.gauge(SERVICE_INFLIGHT_BYTES)
        self._g_queue.set(0)
        self._g_inflight.set(0)
        self._tenant_gauges: Dict[str, object] = {}

    # -- introspection (tests/benchmarks) ------------------------------------

    @property
    def inflight_bytes(self) -> int:
        return self._inflight

    def tenant_inflight(self, tenant: str) -> int:
        return self._tenant_inflight.get(tenant, 0)

    @property
    def queued(self) -> int:
        return len(self._waiters)

    # -- admission -----------------------------------------------------------

    def _fits_locked(self, nbytes: int, tenant: str) -> bool:
        t_in = self._tenant_inflight.get(tenant, 0)
        fits_global = self._inflight + nbytes <= self.max_bytes \
            or self._inflight == 0
        fits_tenant = t_in + nbytes <= self.tenant_max_bytes \
            or t_in == 0
        return fits_global and fits_tenant

    # bound on DISTINCT per-tenant gauge series: tenant ids arrive
    # from untrusted request bodies, and registry gauges are
    # permanent — without a cap a client cycling tenant strings grows
    # server memory and the /metrics output without bound.  Byte
    # accounting (self._tenant_inflight) stays exact per tenant (that
    # dict IS pruned on release); only the observability series fold
    # into "__other__" past the cap.
    MAX_TENANT_GAUGES = 256

    def _tenant_gauge(self, tenant: str):
        g = self._tenant_gauges.get(tenant)
        if g is None:
            if len(self._tenant_gauges) >= self.MAX_TENANT_GAUGES:
                tenant = "__other__"
                g = self._tenant_gauges.get(tenant)
                if g is not None:
                    return g
            from paimon_tpu.metrics import SERVICE_TENANT_BYTES
            g = self._registry.service_metrics(tenant).gauge(
                SERVICE_TENANT_BYTES)
            self._tenant_gauges[tenant] = g
        return g

    def _admit_locked(self, nbytes: int, tenant: str):
        self._inflight += nbytes
        self._tenant_inflight[tenant] = \
            self._tenant_inflight.get(tenant, 0) + nbytes
        self._g_inflight.set(self._inflight)
        self._tenant_gauge(tenant).set(self._tenant_inflight[tenant])
        self._m_requests.inc()

    def _drain_locked(self):
        """Admit every waiter that now fits, LARGEST-FIRST (LPT like
        parallel/packing.py).  Called with the lock held after any
        release; a smaller waiter can slip past a larger one only when
        the larger one genuinely does not fit yet."""
        if not self._waiters:
            return
        for w in sorted(self._waiters,
                        key=lambda w: (-w.bytes, w.enqueued_at)):
            if w.admitted:
                continue
            if self._fits_locked(w.bytes, w.tenant):
                w.admitted = True
                self._admit_locked(w.bytes, w.tenant)
                w.event.set()
        self._waiters = [w for w in self._waiters if not w.admitted]
        self._g_queue.set(len(self._waiters))

    def set_shed_below(self, priority: int):
        """Brownout hook: shed acquires with priority < `priority`
        (0 restores normal admission)."""
        with self._lock:
            self._shed_below = int(priority)

    def acquire(self, tenant: str = DEFAULT_TENANT,
                nbytes: int = 1,
                priority: int = DEFAULT_PRIORITY) -> AdmissionTicket:
        """Block until `nbytes` fits under both the global and the
        tenant budget, then return the ticket.  Raises
        AdmissionRejected immediately when the wait queue is full,
        when brownout is shedding this request's priority class, or
        after service.queue.timeout with no capacity.  A request
        deadline (utils/deadline.py) bounds the queue wait: a spent
        deadline raises DeadlineExceededError (504), never parks the
        caller for the full queue timeout."""
        from paimon_tpu.utils.deadline import current_deadline
        tenant = tenant or DEFAULT_TENANT
        nbytes = max(1, int(nbytes))
        t0 = time.perf_counter()
        dl = current_deadline()
        if dl is not None:
            dl.check("admission")
        with self._lock:
            if priority < self._shed_below:
                self._m_rejected.inc()
                self._m_sheds.inc()
                raise AdmissionRejected(
                    f"brownout: shedding priority<{self._shed_below} "
                    f"requests; retry later")
            # fast path only when nobody is queued: arrivals must not
            # starve the waiters the drain is ordering
            if not self._waiters and self._fits_locked(nbytes, tenant):
                self._admit_locked(nbytes, tenant)
                self._m_wait.update(0.0)
                return AdmissionTicket(self, nbytes, tenant)
            if len(self._waiters) >= self.queue_depth:
                self._m_rejected.inc()
                raise AdmissionRejected(
                    f"admission queue full "
                    f"({self.queue_depth} waiting); retry later")
            w = _Waiter(nbytes, tenant)
            self._waiters.append(w)
            self._g_queue.set(len(self._waiters))
            self._drain_locked()     # we may fit right now
        wait_s = self.queue_timeout_ms / 1000.0
        deadline_bound = dl is not None and \
            dl.remaining_s() < wait_s
        if deadline_bound:
            wait_s = dl.remaining_s()
        if w.event.wait(wait_s):
            self._m_wait.update((time.perf_counter() - t0) * 1000.0)
            return AdmissionTicket(self, nbytes, tenant)
        with self._lock:
            if w.admitted:
                # the drain won the race with the timeout: keep it
                self._m_wait.update((time.perf_counter() - t0) * 1000.0)
                return AdmissionTicket(self, nbytes, tenant)
            self._waiters.remove(w)
            self._g_queue.set(len(self._waiters))
            if not deadline_bound:
                self._m_rejected.inc()
        if deadline_bound:
            # the request's own deadline ran out first: that is a 504
            # (the caller's budget), not a 429 (our capacity)
            dl.check("admission")
        raise AdmissionRejected(
            f"no byte budget within {self.queue_timeout_ms}ms "
            f"({nbytes} bytes requested, {self._inflight} in flight); "
            f"retry later")

    def _release(self, ticket: AdmissionTicket):
        with self._lock:
            self._inflight -= ticket.bytes
            left = self._tenant_inflight.get(ticket.tenant, 0) \
                - ticket.bytes
            if left > 0:
                self._tenant_inflight[ticket.tenant] = left
            else:
                self._tenant_inflight.pop(ticket.tenant, None)
            self._g_inflight.set(self._inflight)
            self._tenant_gauge(ticket.tenant).set(max(0, left))
            self._drain_locked()
