"""SchemaManager: versioned schema files with optimistic-lock commit.

reference: paimon-core/.../schema/SchemaManager.java (1517 lines) --
schemas live at ``<table>/schema/schema-<N>``; DDL writes schema-(N+1) via
atomic CAS; alters validate compatibility (SchemaChange ops).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from paimon_tpu.fs import FileIO
from paimon_tpu.options import CoreOptions
from paimon_tpu.schema.schema import Schema
from paimon_tpu.schema.table_schema import TableSchema
from paimon_tpu.types import DataField, DataType

__all__ = ["SchemaManager", "SchemaChange"]

SCHEMA_PREFIX = "schema-"


class SchemaChange:
    """DDL change ops (reference schema/SchemaChange.java)."""

    def __init__(self, kind: str, **kw):
        self.kind = kind
        self.kw = kw

    @staticmethod
    def set_option(key: str, value: str) -> "SchemaChange":
        return SchemaChange("set-option", key=key, value=str(value))

    @staticmethod
    def remove_option(key: str) -> "SchemaChange":
        return SchemaChange("remove-option", key=key)

    @staticmethod
    def add_column(name: str, typ: DataType,
                   description: Optional[str] = None) -> "SchemaChange":
        return SchemaChange("add-column", name=name, type=typ,
                            description=description)

    @staticmethod
    def drop_column(name: str) -> "SchemaChange":
        return SchemaChange("drop-column", name=name)

    @staticmethod
    def rename_column(name: str, new_name: str) -> "SchemaChange":
        return SchemaChange("rename-column", name=name, new_name=new_name)

    @staticmethod
    def update_column_type(name: str, typ: DataType) -> "SchemaChange":
        return SchemaChange("update-column-type", name=name, type=typ)

    @staticmethod
    def update_column_nullability(name: str, nullable: bool) -> "SchemaChange":
        return SchemaChange("update-column-nullability", name=name,
                            nullable=nullable)

    @staticmethod
    def update_comment(comment: str) -> "SchemaChange":
        return SchemaChange("update-comment", comment=comment)


class SchemaManager:
    def __init__(self, file_io: FileIO, table_path: str, branch: str = "main"):
        self.file_io = file_io
        self.table_path = table_path.rstrip("/")
        self.branch = branch

    def _schema_dir(self) -> str:
        if self.branch and self.branch != "main":
            return f"{self.table_path}/branch/branch-{self.branch}/schema"
        return f"{self.table_path}/schema"

    def schema_path(self, schema_id: int) -> str:
        return f"{self._schema_dir()}/{SCHEMA_PREFIX}{schema_id}"

    # -- reads ---------------------------------------------------------------

    def schema(self, schema_id: int) -> TableSchema:
        return TableSchema.from_json(
            self.file_io.read_utf8(self.schema_path(schema_id)))

    def list_all_ids(self) -> List[int]:
        out = []
        for st in self.file_io.list_status(self._schema_dir()):
            name = st.path.rstrip("/").split("/")[-1]
            if name.startswith(SCHEMA_PREFIX):
                try:
                    out.append(int(name[len(SCHEMA_PREFIX):]))
                except ValueError:
                    pass
        return sorted(out)

    def list_all(self) -> List[TableSchema]:
        return [self.schema(i) for i in self.list_all_ids()]

    def latest(self) -> Optional[TableSchema]:
        ids = self.list_all_ids()
        return self.schema(ids[-1]) if ids else None

    def exists(self) -> bool:
        return bool(self.list_all_ids())

    # -- writes --------------------------------------------------------------

    def create_table(self, schema: Schema,
                     ignore_if_exists: bool = False) -> TableSchema:
        latest = self.latest()
        if latest is not None:
            if ignore_if_exists:
                return latest
            raise RuntimeError(f"Table already exists at {self.table_path}")
        ts = TableSchema.from_schema(0, schema)
        if not self._commit(ts):
            raise RuntimeError("Concurrent table creation detected")
        return ts

    def commit_changes(self, *changes) -> TableSchema:
        """Apply DDL with optimistic retry (reference
        SchemaManager.commitChanges).  Accepts either varargs of
        SchemaChange or a single list/tuple of them."""
        if len(changes) == 1 and isinstance(changes[0], (list, tuple)):
            changes = tuple(changes[0])
        while True:
            latest = self.latest()
            if latest is None:
                raise RuntimeError(f"Table not found: {self.table_path}")
            new_schema = self._apply(latest, list(changes))
            if self._commit(new_schema):
                return new_schema
            # CAS lost: retry against newer schema

    def _commit(self, ts: TableSchema) -> bool:
        return self.file_io.try_to_write_atomic(
            self.schema_path(ts.id), ts.to_json().encode("utf-8"))

    # -- change application --------------------------------------------------

    def _apply(self, base: TableSchema,
               changes: List[SchemaChange]) -> TableSchema:
        fields = list(base.fields)
        options = dict(base.options)
        comment = base.comment
        highest = base.highest_field_id

        def idx_of(name: str) -> int:
            for i, f in enumerate(fields):
                if f.name == name:
                    return i
            raise ValueError(f"Column {name!r} not found")

        for ch in changes:
            k = ch.kw
            if ch.kind == "set-option":
                _validate_option_change(k["key"])
                options[k["key"]] = k["value"]
            elif ch.kind == "remove-option":
                options.pop(k["key"], None)
            elif ch.kind == "add-column":
                if any(f.name == k["name"] for f in fields):
                    raise ValueError(f"Column {k['name']!r} already exists")
                if not k["type"].nullable:
                    raise ValueError(
                        "Cannot add NOT NULL column to existing table")
                highest += 1
                new_field = DataField(highest, k["name"], k["type"],
                                      k.get("description"))
                if _opt(options, CoreOptions.ADD_COLUMN_BEFORE_PARTITION) \
                        and base.partition_keys:
                    pos = min(i for i, f in enumerate(fields)
                              if f.name in base.partition_keys)
                    fields.insert(pos, new_field)
                else:
                    fields.append(new_field)
            elif ch.kind == "drop-column":
                if k["name"] in base.primary_keys:
                    raise ValueError("Cannot drop primary-key column")
                if k["name"] in base.partition_keys:
                    raise ValueError("Cannot drop partition column")
                fields.pop(idx_of(k["name"]))
                if not fields:
                    raise ValueError("Cannot drop all columns")
            elif ch.kind == "rename-column":
                i = idx_of(k["name"])
                if any(f.name == k["new_name"] for f in fields):
                    raise ValueError(
                        f"Column {k['new_name']!r} already exists")
                if k["name"] in base.primary_keys or \
                        k["name"] in base.partition_keys:
                    raise ValueError("Cannot rename key/partition column")
                f = fields[i]
                fields[i] = DataField(f.id, k["new_name"], f.type,
                                      f.description, f.default_value)
            elif ch.kind == "update-column-type":
                i = idx_of(k["name"])
                f = fields[i]
                _check_type_evolution(
                    f.type, k["type"],
                    allow_explicit=not _opt(
                        options, CoreOptions.DISABLE_EXPLICIT_TYPE_CASTING))
                fields[i] = DataField(f.id, f.name, k["type"], f.description,
                                      f.default_value)
            elif ch.kind == "update-column-nullability":
                i = idx_of(k["name"])
                f = fields[i]
                if k["nullable"] and f.name in base.primary_keys:
                    raise ValueError("Primary-key column must be NOT NULL")
                if not k["nullable"] and f.type.nullable and _opt(
                        options, CoreOptions.ALTER_NULL_TO_NOT_NULL_DISABLED):
                    # existing nulls would break readers (reference
                    # alter-column-null-to-not-null.disabled, default on)
                    raise ValueError(
                        "Tightening a nullable column to NOT NULL is "
                        "disabled (alter-column-null-to-not-null."
                        "disabled)")
                fields[i] = DataField(f.id, f.name,
                                      f.type.copy(k["nullable"]),
                                      f.description, f.default_value)
            elif ch.kind == "update-comment":
                comment = k["comment"]
            else:
                raise ValueError(f"Unknown schema change {ch.kind}")

        return TableSchema(base.id + 1, fields, highest, base.partition_keys,
                           base.primary_keys, options, comment)


def _opt(options: dict, option) -> bool:
    """Typed read of a table option from a raw options dict."""
    return option.parse(options.get(option.key))


_IMMUTABLE_OPTIONS = {"bucket-key", "merge-engine", "sequence.field",
                      "primary-key", "partition"}


def _validate_option_change(key: str):
    if key in _IMMUTABLE_OPTIONS:
        raise ValueError(f"Option {key!r} cannot be changed after creation")


# Allowed implicit casts for type evolution
# (reference schema/SchemaEvolutionUtil + casting/CastExecutors).
_NUMERIC_WIDENING = ["TINYINT", "SMALLINT", "INT", "BIGINT", "FLOAT",
                     "DOUBLE"]


def _check_type_evolution(old: DataType, new: DataType,
                          allow_explicit: bool = True):
    if old == new:
        return
    o, n = old.root, new.root
    if o in _NUMERIC_WIDENING and n in _NUMERIC_WIDENING:
        if _NUMERIC_WIDENING.index(n) >= _NUMERIC_WIDENING.index(o):
            return
    if o in ("CHAR", "VARCHAR") and n == "VARCHAR":
        return
    if o in ("BINARY", "VARBINARY") and n == "VARBINARY":
        return
    if o == "DECIMAL" and n == "DECIMAL":
        if new.precision >= old.precision and new.scale == old.scale:
            return
    if o == "TIMESTAMP" and n == "TIMESTAMP":
        return
    # beyond implicit widening: the reference permits any update whose
    # explicit cast rule resolves (SchemaManager.java:525
    # DataTypeCasts.supportsCast(..., allowExplicit) +
    # CastExecutors.resolve != null); our rule matrix is that resolver.
    # disable-explicit-type-casting restricts evolution to the implicit
    # widenings above.
    if allow_explicit:
        from paimon_tpu.data.casting import can_cast
        if can_cast(old, new):
            return
    raise ValueError(f"Unsupported type evolution {old} -> {new}")
