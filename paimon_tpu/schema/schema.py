"""User-facing table schema definition (reference paimon-api/.../schema/Schema.java)."""

from __future__ import annotations

from typing import Dict, List, Optional, Union

import pyarrow as pa

from paimon_tpu.types import (
    DataField, DataType, RowType, arrow_schema_to_row_type,
)

__all__ = ["Schema"]


class Schema:
    """What a user supplies to create a table: fields + partition keys +
    primary keys + options + comment."""

    def __init__(self, fields: Union[RowType, List[DataField], pa.Schema],
                 partition_keys: Optional[List[str]] = None,
                 primary_keys: Optional[List[str]] = None,
                 options: Optional[Dict[str, str]] = None,
                 comment: str = ""):
        if isinstance(fields, pa.Schema):
            fields = arrow_schema_to_row_type(fields).fields
        elif isinstance(fields, RowType):
            fields = fields.fields
        self.fields: List[DataField] = list(fields)
        self.partition_keys = list(partition_keys or [])
        self.primary_keys = list(primary_keys or [])
        self.options = {k: str(v) for k, v in (options or {}).items()}
        self.comment = comment
        self._validate()

    def _validate(self):
        names = [f.name for f in self.fields]
        if len(set(names)) != len(names):
            raise ValueError(f"Duplicate field names: {names}")
        for k in self.partition_keys:
            if k not in names:
                raise ValueError(f"Partition key {k!r} not in fields {names}")
        for k in self.primary_keys:
            if k not in names:
                raise ValueError(f"Primary key {k!r} not in fields {names}")
        # Primary keys must contain all partition keys UNLESS the table
        # runs in cross-partition upsert mode (dynamic bucket, bucket=-1:
        # reference schema/SchemaValidation.java + BucketMode.KEY_DYNAMIC)
        if self.primary_keys:
            missing = [p for p in self.partition_keys
                       if p not in self.primary_keys]
            dynamic_bucket = int(self.options.get("bucket", "-1")) == -1
            if missing and not dynamic_bucket:
                raise ValueError(
                    f"Primary key must include all partition fields, "
                    f"missing {missing} (or use dynamic bucket=-1 for "
                    f"cross-partition upsert)")

    def row_type(self) -> RowType:
        return RowType(self.fields, nullable=False)

    @staticmethod
    def builder() -> "SchemaBuilder":
        return SchemaBuilder()


class SchemaBuilder:
    def __init__(self):
        self._fields: List[DataField] = []
        self._partition_keys: List[str] = []
        self._primary_keys: List[str] = []
        self._options: Dict[str, str] = {}
        self._comment = ""
        self._next_id = 0

    def column(self, name: str, typ: DataType,
               description: Optional[str] = None) -> "SchemaBuilder":
        self._fields.append(DataField(self._next_id, name, typ, description))
        self._next_id += 1
        return self

    def partition_keys(self, *keys: str) -> "SchemaBuilder":
        self._partition_keys = list(keys)
        return self

    def primary_key(self, *keys: str) -> "SchemaBuilder":
        self._primary_keys = list(keys)
        return self

    def option(self, key: str, value: str) -> "SchemaBuilder":
        self._options[key] = str(value)
        return self

    def options(self, opts: Dict[str, str]) -> "SchemaBuilder":
        self._options.update({k: str(v) for k, v in opts.items()})
        return self

    def comment(self, c: str) -> "SchemaBuilder":
        self._comment = c
        return self

    def build(self) -> Schema:
        return Schema(self._fields, self._partition_keys, self._primary_keys,
                      self._options, self._comment)
