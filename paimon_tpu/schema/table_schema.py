"""Versioned table schema persisted as ``schema/schema-N`` JSON.

Wire format per reference docs/docs/concepts/spec/schema.md and
paimon-core/.../schema/TableSchema.java. Current version 3.
"""

from __future__ import annotations

import json
import time as _time
from typing import Any, Dict, List, Optional

from paimon_tpu.schema.schema import Schema
from paimon_tpu.types import (
    DataField, RowType, SpecialFields, row_type_to_arrow_schema,
)

__all__ = ["TableSchema"]

CURRENT_VERSION = 3


class TableSchema:
    def __init__(self, id: int, fields: List[DataField],
                 highest_field_id: int, partition_keys: List[str],
                 primary_keys: List[str], options: Dict[str, str],
                 comment: str = "", time_millis: Optional[int] = None,
                 version: int = CURRENT_VERSION):
        self.version = version
        self.id = id
        self.fields = list(fields)
        self.highest_field_id = highest_field_id
        self.partition_keys = list(partition_keys)
        self.primary_keys = list(primary_keys)
        self.options = dict(options)
        self.comment = comment
        self.time_millis = (int(_time.time() * 1000)
                            if time_millis is None else time_millis)

    # -- derived -------------------------------------------------------------

    @property
    def field_names(self) -> List[str]:
        return [f.name for f in self.fields]

    def logical_row_type(self) -> RowType:
        return RowType(self.fields, nullable=False)

    def logical_partition_type(self) -> RowType:
        rt = self.logical_row_type()
        return rt.project(self.partition_keys)

    def logical_primary_keys_type(self) -> RowType:
        rt = self.logical_row_type()
        return rt.project(self.primary_keys)

    def trimmed_primary_keys(self) -> List[str]:
        """Primary keys minus partition keys — the key columns actually
        stored in data files (reference TableSchema.trimmedPrimaryKeys)."""
        if len(self.primary_keys) > len(self.partition_keys):
            trimmed = [k for k in self.primary_keys
                       if k not in self.partition_keys]
            if trimmed:
                return trimmed
        return list(self.primary_keys)

    def logical_trimmed_primary_keys_type(self) -> RowType:
        return self.logical_row_type().project(self.trimmed_primary_keys())

    def bucket_keys(self) -> List[str]:
        """Effective bucket key: `bucket-key` option, else trimmed pks,
        else empty (reference TableSchema.bucketKeys)."""
        opt = self.options.get("bucket-key")
        if opt:
            return [s.strip() for s in opt.split(",")]
        return self.trimmed_primary_keys()

    def cross_partition_update(self) -> bool:
        """PKs not containing all partition keys => cross-partition upsert
        (reference TableSchema.crossPartitionUpdate)."""
        if not self.primary_keys or not self.partition_keys:
            return False
        return any(p not in self.primary_keys for p in self.partition_keys)

    def to_arrow_schema(self):
        return row_type_to_arrow_schema(self.logical_row_type())

    def key_value_arrow_schema(self):
        """Arrow schema of KV data files: _KEY_* | _SEQUENCE_NUMBER |
        _VALUE_KIND | value fields (reference io/KeyValueDataFileWriter)."""
        kv = self.key_value_row_type()
        return row_type_to_arrow_schema(kv)

    def key_value_row_type(self) -> RowType:
        rt = self.logical_row_type()
        key_fields = [SpecialFields.key_field(rt.get_field(n))
                      for n in self.trimmed_primary_keys()]
        fields = (key_fields
                  + [SpecialFields.SEQUENCE_NUMBER, SpecialFields.VALUE_KIND]
                  + self.fields)
        return RowType(fields, nullable=False)

    # -- serde ---------------------------------------------------------------

    def to_json(self) -> str:
        d: Dict[str, Any] = {
            "version": self.version,
            "id": self.id,
            "fields": [f.to_json() for f in self.fields],
            "highestFieldId": self.highest_field_id,
            "partitionKeys": self.partition_keys,
            "primaryKeys": self.primary_keys,
            "options": self.options,
            "comment": self.comment,
            "timeMillis": self.time_millis,
        }
        return json.dumps(d, indent=2)

    @staticmethod
    def from_json(s: str) -> "TableSchema":
        d = json.loads(s)
        version = d.get("version", 1)
        options = dict(d.get("options", {}))
        # version compat per spec/schema.md
        if version <= 1 and "bucket" not in options:
            options["bucket"] = "1"
        if version <= 2 and "file.format" not in options:
            options["file.format"] = "orc"
        return TableSchema(
            id=d["id"],
            fields=[DataField.from_json(f) for f in d["fields"]],
            highest_field_id=d["highestFieldId"],
            partition_keys=d.get("partitionKeys", []),
            primary_keys=d.get("primaryKeys", []),
            options=options,
            comment=d.get("comment") or "",
            time_millis=d.get("timeMillis"),
            version=version,
        )

    @staticmethod
    def from_schema(schema_id: int, schema: Schema) -> "TableSchema":
        highest = max((f.id for f in schema.fields), default=-1)
        return TableSchema(schema_id, schema.fields, highest,
                           schema.partition_keys, schema.primary_keys,
                           schema.options, schema.comment)

    def copy(self, options: Optional[Dict[str, str]] = None) -> "TableSchema":
        return TableSchema(self.id, self.fields, self.highest_field_id,
                           self.partition_keys, self.primary_keys,
                           options if options is not None else self.options,
                           self.comment, self.time_millis, self.version)

    def __eq__(self, other):
        return (isinstance(other, TableSchema) and self.id == other.id
                and self.fields == other.fields
                and self.partition_keys == other.partition_keys
                and self.primary_keys == other.primary_keys
                and self.options == other.options)

    def __repr__(self):
        return (f"TableSchema(id={self.id}, fields={self.field_names}, "
                f"pk={self.primary_keys}, partition={self.partition_keys})")
