"""Schema subsystem: user-facing Schema, versioned TableSchema files, and
SchemaManager (DDL + schema evolution).

reference: paimon-core/.../schema/ (TableSchema.java, SchemaManager.java,
SchemaChange.java, SchemaEvolutionUtil.java), spec docs/concepts/spec/schema.md.
"""

from paimon_tpu.schema.schema import Schema  # noqa: F401
from paimon_tpu.schema.table_schema import TableSchema  # noqa: F401
from paimon_tpu.schema.schema_manager import SchemaManager, SchemaChange  # noqa: F401
