"""Predicate tree: file pruning on stats + Arrow row filtering.

reference: paimon-common/.../predicate/ (Predicate.java, LeafPredicate,
CompoundPredicate, PredicateBuilder, ~30 LeafFunctions). Each predicate
does double duty: `test_stats` decides whether a file can contain matches
(min/max/null-count pruning) and `to_arrow` emits a pyarrow.compute
expression evaluated vectorized over row batches.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import pyarrow.compute as pc
import pyarrow.dataset as ds

__all__ = ["Predicate", "PredicateBuilder", "equal", "not_equal",
           "greater_than", "greater_or_equal", "less_than", "less_or_equal",
           "is_null", "is_not_null", "in_", "not_in", "between",
           "starts_with", "and_", "or_", "not_"]


class Predicate:
    def test_stats(self, mins: Dict[str, Any], maxs: Dict[str, Any],
                   null_counts: Dict[str, int], row_count: int) -> bool:
        """May the file contain matching rows? Conservative: True unless
        provably empty."""
        raise NotImplementedError

    def test_row(self, row: Dict[str, Any]) -> bool:
        raise NotImplementedError

    def to_arrow(self) -> ds.Expression:
        raise NotImplementedError

    def fields(self) -> List[str]:
        raise NotImplementedError

    def __and__(self, other):
        return and_(self, other)

    def __or__(self, other):
        return or_(self, other)

    def __invert__(self):
        return not_(self)


class Leaf(Predicate):
    def __init__(self, op: str, field: str, literal: Any = None):
        self.op = op
        self.field = field
        self.literal = literal

    def fields(self):
        return [self.field]

    def __repr__(self):
        return f"{self.field} {self.op} {self.literal!r}"

    # -- stats pruning -------------------------------------------------------

    def test_stats(self, mins, maxs, null_counts, row_count):
        mn = mins.get(self.field)
        mx = maxs.get(self.field)
        nc = null_counts.get(self.field)
        op, lit = self.op, self.literal
        if op == "is_null":
            return nc is None or nc > 0
        if op == "is_not_null":
            return nc is None or row_count == 0 or nc < row_count
        if mn is None or mx is None:
            return True  # no stats -> cannot prune
        try:
            if op == "eq":
                return mn <= lit <= mx
            if op == "ne":
                return not (mn == lit == mx)
            if op == "lt":
                return mn < lit
            if op == "le":
                return mn <= lit
            if op == "gt":
                return mx > lit
            if op == "ge":
                return mx >= lit
            if op == "in":
                return any(mn <= v <= mx for v in lit)
            if op == "not_in":
                return not (mn == mx and mn in lit)
            if op == "between":
                lo, hi = lit
                return not (mx < lo or mn > hi)
            if op == "starts_with":
                return (str(mn)[:len(lit)] <= lit <= str(mx)[:len(lit)])
        except TypeError:
            return True
        return True

    # -- row eval ------------------------------------------------------------

    def test_row(self, row):
        v = row.get(self.field)
        op, lit = self.op, self.literal
        if op == "is_null":
            return v is None
        if op == "is_not_null":
            return v is not None
        if v is None:
            return False
        if op == "eq":
            return v == lit
        if op == "ne":
            return v != lit
        if op == "lt":
            return v < lit
        if op == "le":
            return v <= lit
        if op == "gt":
            return v > lit
        if op == "ge":
            return v >= lit
        if op == "in":
            return v in lit
        if op == "not_in":
            return v not in lit
        if op == "between":
            return lit[0] <= v <= lit[1]
        if op == "starts_with":
            return str(v).startswith(lit)
        raise ValueError(f"Unknown op {op}")

    def to_arrow(self):
        f = ds.field(self.field)
        op, lit = self.op, self.literal
        if op == "eq":
            return f == lit
        if op == "ne":
            return f != lit
        if op == "lt":
            return f < lit
        if op == "le":
            return f <= lit
        if op == "gt":
            return f > lit
        if op == "ge":
            return f >= lit
        if op == "is_null":
            return f.is_null()
        if op == "is_not_null":
            return f.is_valid()
        if op == "in":
            return f.isin(list(lit))
        if op == "not_in":
            return ~f.isin(list(lit))
        if op == "between":
            return (f >= lit[0]) & (f <= lit[1])
        if op == "starts_with":
            return pc.starts_with(f, lit)
        raise ValueError(f"Unknown op {op}")


def conjunctive_equalities(pred):
    """[(field, literal)] for every equality that must hold for a row to
    match (eq leaves reachable through AND nodes only) — the conditions a
    per-file bloom filter may safely prune on."""
    out = []
    if isinstance(pred, Leaf):
        if pred.op == "eq":
            out.append((pred.field, pred.literal))
    elif isinstance(pred, Compound) and pred.op == "and":
        for c in pred.children:
            out.extend(conjunctive_equalities(c))
    return out


def conjunctive_bounds(pred, field: str):
    """Inclusive (lo, hi) value bounds that must hold on `field` for a
    row to match, folded from every range/equality leaf reachable
    through AND nodes only; either side may be None (unbounded).
    Returns None when the predicate puts NO usable bound on the field —
    callers must then keep everything.  This is the manifest-level
    vectorized prune's contract: the bounds are necessary conditions,
    so dropping a manifest whose [min,max] misses [lo,hi] can never
    drop a match (OR nodes contribute nothing, conservatively)."""
    lo = hi = None

    def fold(lo, hi, new_lo, new_hi):
        if new_lo is not None and (lo is None or new_lo > lo):
            lo = new_lo
        if new_hi is not None and (hi is None or new_hi < hi):
            hi = new_hi
        return lo, hi

    if isinstance(pred, Leaf):
        v = pred.literal
        if pred.op == "eq" and v is not None:
            lo, hi = fold(lo, hi, v, v)
        elif pred.op in ("gt", "ge") and v is not None:
            lo, hi = fold(lo, hi, v, None)
        elif pred.op in ("lt", "le") and v is not None:
            lo, hi = fold(lo, hi, None, v)
        elif pred.op == "in" and v and all(x is not None for x in v):
            try:
                lo, hi = fold(lo, hi, min(v), max(v))
            except TypeError:
                return None
        else:
            return None
        if pred.field != field:
            return None
        return lo, hi
    if isinstance(pred, Compound) and pred.op == "and":
        found = False
        for c in pred.children:
            b = conjunctive_bounds(c, field)
            if b is not None:
                found = True
                try:
                    lo, hi = fold(lo, hi, b[0], b[1])
                except TypeError:
                    return None
        return (lo, hi) if found else None
    return None


class Compound(Predicate):
    def __init__(self, op: str, children: Sequence[Predicate]):
        assert op in ("and", "or", "not")
        self.op = op
        self.children = list(children)

    def fields(self):
        out = []
        for c in self.children:
            out.extend(c.fields())
        return out

    def __repr__(self):
        if self.op == "not":
            return f"NOT({self.children[0]!r})"
        return ("(" + f" {self.op.upper()} ".join(map(repr, self.children))
                + ")")

    def test_stats(self, mins, maxs, null_counts, row_count):
        if self.op == "and":
            return all(c.test_stats(mins, maxs, null_counts, row_count)
                       for c in self.children)
        if self.op == "or":
            return any(c.test_stats(mins, maxs, null_counts, row_count)
                       for c in self.children)
        return True  # NOT cannot prune safely on min/max

    def test_row(self, row):
        if self.op == "and":
            return all(c.test_row(row) for c in self.children)
        if self.op == "or":
            return any(c.test_row(row) for c in self.children)
        return not self.children[0].test_row(row)

    def to_arrow(self):
        exprs = [c.to_arrow() for c in self.children]
        if self.op == "and":
            out = exprs[0]
            for e in exprs[1:]:
                out = out & e
            return out
        if self.op == "or":
            out = exprs[0]
            for e in exprs[1:]:
                out = out | e
            return out
        return ~exprs[0]


# -- builders ----------------------------------------------------------------

def equal(field: str, v) -> Predicate:
    return Leaf("eq", field, v)


def not_equal(field: str, v) -> Predicate:
    return Leaf("ne", field, v)


def less_than(field: str, v) -> Predicate:
    return Leaf("lt", field, v)


def less_or_equal(field: str, v) -> Predicate:
    return Leaf("le", field, v)


def greater_than(field: str, v) -> Predicate:
    return Leaf("gt", field, v)


def greater_or_equal(field: str, v) -> Predicate:
    return Leaf("ge", field, v)


def is_null(field: str) -> Predicate:
    return Leaf("is_null", field)


def is_not_null(field: str) -> Predicate:
    return Leaf("is_not_null", field)


def in_(field: str, values) -> Predicate:
    return Leaf("in", field, list(values))


def not_in(field: str, values) -> Predicate:
    return Leaf("not_in", field, list(values))


def between(field: str, lo, hi) -> Predicate:
    return Leaf("between", field, (lo, hi))


def starts_with(field: str, prefix: str) -> Predicate:
    return Leaf("starts_with", field, prefix)


def and_(*ps: Predicate) -> Predicate:
    flat = [p for p in ps if p is not None]
    if len(flat) == 1:
        return flat[0]
    return Compound("and", flat)


def or_(*ps: Predicate) -> Predicate:
    flat = [p for p in ps if p is not None]
    if len(flat) == 1:
        return flat[0]
    return Compound("or", flat)


def not_(p: Predicate) -> Predicate:
    return Compound("not", [p])


class PredicateBuilder:
    """Field-index-aware builder mirroring the reference's PredicateBuilder
    API shape (field names here, not indices)."""

    def __init__(self, row_type=None):
        self.row_type = row_type

    equal = staticmethod(equal)
    not_equal = staticmethod(not_equal)
    less_than = staticmethod(less_than)
    less_or_equal = staticmethod(less_or_equal)
    greater_than = staticmethod(greater_than)
    greater_or_equal = staticmethod(greater_or_equal)
    is_null = staticmethod(is_null)
    is_not_null = staticmethod(is_not_null)
    in_ = staticmethod(in_)
    not_in = staticmethod(not_in)
    between = staticmethod(between)
    starts_with = staticmethod(starts_with)
    and_ = staticmethod(and_)
    or_ = staticmethod(or_)
    not_ = staticmethod(not_)
