"""Computed columns for CDC ingestion.

reference: paimon-flink/paimon-flink-cdc/.../action/cdc/Expression.java
— derived columns evaluated per record at ingest time, typically to
synthesize partition values from event fields.  Supported expression
set mirrors the reference: year, month, day, hour, minute, second,
date_format(field, pattern), substring(field, begin[, end]),
truncate(field, width), cast(literal), upper, lower.

Spec strings look like the reference's CLI args:
    "part=date_format(ts, yyyy-MM-dd)"
    "y=year(ts)"  "pfx=substring(name, 0, 3)"  "b=truncate(id, 10)"
"""

from __future__ import annotations

import datetime
import re
from typing import Callable, Dict, List, Tuple

__all__ = ["parse_computed_columns", "apply_computed_columns"]

# Java SimpleDateFormat tokens -> strftime
_DATE_TOKENS = [("yyyy", "%Y"), ("MM", "%m"), ("dd", "%d"),
                ("HH", "%H"), ("mm", "%M"), ("ss", "%S")]


def _to_strftime(pattern: str) -> str:
    out = pattern
    for token, repl in _DATE_TOKENS:
        out = out.replace(token, repl)
    return out


def _as_datetime(v) -> datetime.datetime:
    if isinstance(v, datetime.datetime):
        return v
    if isinstance(v, datetime.date):
        return datetime.datetime(v.year, v.month, v.day)
    if isinstance(v, (int, float)):
        # epoch millis when large, else seconds (reference TypeUtils)
        secs = v / 1000.0 if v > 10_000_000_000 else float(v)
        return datetime.datetime.fromtimestamp(
            secs, tz=datetime.timezone.utc).replace(tzinfo=None)
    return datetime.datetime.fromisoformat(str(v).replace("T", " ")
                                           .replace("Z", ""))


def _temporal(fn: Callable[[datetime.datetime], object]):
    def wrapped(row, field, *args):
        v = row.get(field)
        return None if v is None else fn(_as_datetime(v))
    return wrapped


_FUNCS: Dict[str, Callable] = {
    "year": _temporal(lambda d: d.year),
    "month": _temporal(lambda d: d.month),
    "day": _temporal(lambda d: d.day),
    "hour": _temporal(lambda d: d.hour),
    "minute": _temporal(lambda d: d.minute),
    "second": _temporal(lambda d: d.second),
}


def _date_format(row, field, pattern):
    v = row.get(field)
    if v is None:
        return None
    return _as_datetime(v).strftime(_to_strftime(pattern))


def _substring(row, field, begin, end=None):
    v = row.get(field)
    if v is None:
        return None
    s = str(v)
    b = int(begin)
    return s[b:int(end)] if end is not None else s[b:]


def _truncate(row, field, width):
    v = row.get(field)
    if v is None:
        return None
    w = int(width)
    if isinstance(v, int):
        return v - (v % w)               # reference: numeric bin
    return str(v)[:w]


def _cast(row, literal):
    return literal


def _upper(row, field):
    v = row.get(field)
    return None if v is None else str(v).upper()


def _lower(row, field):
    v = row.get(field)
    return None if v is None else str(v).lower()


_FUNCS.update({"date_format": _date_format, "substring": _substring,
               "truncate": _truncate, "cast": _cast, "upper": _upper,
               "lower": _lower})

_SPEC = re.compile(r"^\s*(\w+)\s*=\s*(\w+)\s*\(([^)]*)\)\s*$")


def parse_computed_columns(specs: List[str]
                           ) -> List[Tuple[str, Callable, List[str]]]:
    """['col=expr(args...)'] -> [(col, fn, args)] (reference
    ComputedColumnUtils.buildComputedColumns)."""
    out = []
    for spec in specs:
        m = _SPEC.match(spec)
        if not m:
            raise ValueError(f"bad computed column spec {spec!r}; "
                             f"expected name=func(args)")
        name, func, raw_args = m.groups()
        if func not in _FUNCS:
            raise ValueError(f"unknown computed-column function "
                             f"{func!r}; available: {sorted(_FUNCS)}")
        args = [a.strip() for a in raw_args.split(",") if a.strip()]
        out.append((name, _FUNCS[func], args))
    return out


def apply_computed_columns(rows: List[dict], computed) -> None:
    """Evaluate in place, row at a time (CDC batches are small; these
    run host-side before the columnar write path)."""
    for row in rows:
        for name, fn, args in computed:
            row[name] = fn(row, *args)
