"""Schema-evolving CDC sink.

reference: paimon-flink-cdc sink/cdc/CdcRecordStoreMultiWriteOperator +
UpdatedDataFieldsProcessFunction: unseen columns trigger ADD COLUMN
through the SchemaManager (optimistic-lock DDL), then the writer reloads
the evolved schema and writes the batch with proper row kinds.
"""

from __future__ import annotations

import datetime
import decimal
from typing import Callable, Dict, List, Optional

import numpy as np
import pyarrow as pa

from paimon_tpu.cdc.formats import (
    parse_aliyun, parse_canal, parse_debezium, parse_dms, parse_maxwell,
    parse_ogg,
)
from paimon_tpu.schema.schema_manager import SchemaChange
from paimon_tpu.table.table import FileStoreTable
from paimon_tpu.types import (
    BigIntType, BooleanType, DataType, DoubleType, TimestampType,
    VarCharType,
)

__all__ = ["CdcSinkWriter"]

_PARSERS: Dict[str, Callable] = {
    "debezium": parse_debezium,
    "canal": parse_canal,
    "maxwell": parse_maxwell,
    "ogg": parse_ogg,
    "dms": parse_dms,
    "aliyun": parse_aliyun,
}


def _infer_type(values: List) -> DataType:
    """Conservative type inference for a new CDC column (reference
    TypeMapping: unknown -> STRING)."""
    non_null = [v for v in values if v is not None]
    if not non_null:
        return VarCharType()
    if all(isinstance(v, bool) for v in non_null):
        return BooleanType()
    if all(isinstance(v, int) and not isinstance(v, bool)
           for v in non_null):
        return BigIntType()
    if all(isinstance(v, (int, float, decimal.Decimal))
           and not isinstance(v, bool) for v in non_null):
        return DoubleType()
    if all(isinstance(v, datetime.datetime) for v in non_null):
        return TimestampType()
    return VarCharType()


_WIDEN_RANK = {"BOOLEAN": 0, "TINYINT": 1, "SMALLINT": 2, "INT": 3,
               "BIGINT": 4, "FLOAT": 5, "DOUBLE": 6, "DECIMAL": 6,
               "TIMESTAMP": 8, "TIMESTAMP_WITH_LOCAL_TIME_ZONE": 8,
               "CHAR": 9, "VARCHAR": 9}
_NUMERIC_RANKS = {0, 1, 2, 3, 4, 5, 6}


def _widen(cur: DataType, want: DataType) -> Optional[DataType]:
    """The type `cur` must become to also hold `want`-shaped values, or
    None when it already can (reference UpdatedDataFieldsProcessFunction
    .canConvert widening lattice).  Numeric widths widen within the
    lattice (INT -> BIGINT -> DOUBLE); any cross-family conflict —
    e.g. numeric meeting TIMESTAMP, whose cast old files cannot
    satisfy — falls back to STRING."""
    a = _WIDEN_RANK.get(cur.root, 9)
    b = _WIDEN_RANK.get(want.root, 9)
    if b <= a:
        return None
    if b == 9:
        return VarCharType()
    if a in _NUMERIC_RANKS and b in _NUMERIC_RANKS:
        return DoubleType() if b >= 5 else want
    # cross-family (numeric vs temporal): only STRING holds both
    return VarCharType()


class CdcSinkWriter:
    """Parses CDC events, evolves the schema for unseen columns and
    writes through the normal table write path."""

    def __init__(self, table: FileStoreTable, format: str = "debezium",
                 commit_user: Optional[str] = None,
                 computed_columns: Optional[List[str]] = None):
        if format not in _PARSERS:
            raise ValueError(f"Unknown CDC format {format!r}; "
                             f"available: {sorted(_PARSERS)}")
        self._parse = _PARSERS[format]
        self.table = table
        self.commit_user = commit_user or "cdc"
        self._writer = None
        self._pending_msgs = []
        # which commit identifier the staged messages were last
        # ATTEMPTED under (None = not yet attempted; they ride the next
        # commit).  Lets a retried/replayed checkpoint detect that the
        # previous attempt actually landed (crash between the snapshot
        # CAS and the ack) and drop the staged messages instead of
        # re-delivering committed rows under a new identifier.
        self._pending_ckpt: Optional[int] = None
        # optional () -> {str: str} forwarded to
        # FileStoreCommit.properties_provider on every commit this
        # sink issues: re-evaluated per CAS attempt, which is how the
        # distributed stream daemon keeps lease/ownership stamps
        # fresh across commit retries (explicit properties win)
        self.properties_provider = None
        self._computed = None
        if computed_columns:
            from paimon_tpu.cdc.computed import parse_computed_columns
            self._computed = parse_computed_columns(computed_columns)

    def _ensure_schema(self, rows: List[Dict]):
        """ADD COLUMN for unseen keys; widen existing columns whose
        incoming values no longer fit (reference
        UpdatedDataFieldsProcessFunction type merging).  Columns seen
        only as null are DEFERRED — creating them as STRING on a
        null-only first batch would lock in the wrong type."""
        by_name = {f.name: f for f in self.table.schema.fields}
        unseen: Dict[str, List] = {}
        seen_vals: Dict[str, List] = {}
        for row in rows:
            for k, v in row.items():
                if k not in by_name:
                    unseen.setdefault(k, []).append(v)
                elif v is not None:
                    seen_vals.setdefault(k, []).append(v)
        changes = [SchemaChange.add_column(name, _infer_type(vals))
                   for name, vals in unseen.items()
                   if any(v is not None for v in vals)]
        for name, vals in seen_vals.items():
            cur = by_name[name].type
            want = _infer_type(vals)
            widened = _widen(cur, want)
            if widened is not None:
                changes.append(
                    SchemaChange.update_column_type(name, widened))
        if not changes:
            return
        if self._writer is not None:
            # the old writer may hold buffered, uncommitted rows: turn
            # them into pending commit messages before discarding it
            self._pending_msgs.extend(self._writer.prepare_commit())
            self._writer.close()
            self._writer = None
        self.table.schema_manager.commit_changes(*changes)
        dynamic = dict(self.table.schema.options)
        if self.table.branch != "main":
            dynamic["branch"] = self.table.branch
        self.table = FileStoreTable.load(
            self.table.path, file_io=self.table.file_io,
            dynamic_options=dynamic)

    def write_events(self, events: List[dict]):
        changes = []
        for event in events:
            changes.extend(self._parse(event))
        if not changes:
            return
        rows = [dict(c[0]) for c in changes]
        kinds = np.array([c[1] for c in changes], dtype=np.int8)
        if self._computed:
            from paimon_tpu.cdc.computed import apply_computed_columns
            apply_computed_columns(rows, self._computed)
        self._ensure_schema(rows)
        if self._writer is None:
            wb = self.table.new_stream_write_builder() \
                .with_commit_user(self.commit_user)
            self._wb = wb
            self._writer = wb.new_write()
        schema = self.table.arrow_schema()

        def coerce(v, f):
            # a column widened to STRING keeps ingesting the source's
            # native values: render them (datetime -> ISO) instead of
            # failing the arrow build
            if v is None or not (pa.types.is_string(f.type)
                                 or pa.types.is_large_string(f.type)):
                return v
            if isinstance(v, str):
                return v
            return v.isoformat(sep=" ") if hasattr(v, "isoformat") \
                else str(v)

        normalized = [{f.name: coerce(row.get(f.name), f)
                       for f in schema} for row in rows]
        batch = pa.Table.from_pylist(normalized, schema=schema)
        self._writer.write_arrow(batch, kinds)

    def commit(self, commit_identifier: int,
               properties: Optional[Dict[str, str]] = None,
               force_create: bool = False) -> Optional[int]:
        """Commit everything staged + buffered under
        `commit_identifier`; `properties` land in the snapshot (the
        stream daemon commits its source offset here, atomically with
        the data).  `force_create` publishes a snapshot even with
        nothing buffered — distributed daemons advance their offset
        (and renew their lease) through checkpoints whose owned share
        of the window was empty.  Exactly-once on every failure shape:

        - replayed identifier (already committed by this user): commit
          nothing, return None;
        - prepare fails: staged messages restored, writer reset —
          retry the SAME identifier;
        - commit raises (which includes "the CAS actually landed but
          the process died before the ack"): messages restored keyed
          by the attempted identifier, so a later commit drops them if
          that identifier turns out to be durable instead of
          re-delivering the rows under a fresh identifier.
        """
        if self._writer is None and not self._pending_msgs and \
                not force_create:
            return None
        if self._writer is None:
            wb = self.table.new_stream_write_builder() \
                .with_commit_user(self.commit_user)
            self._wb = wb
        commit = self._wb.new_commit()
        if self.properties_provider is not None:
            commit._commit.properties_provider = \
                self.properties_provider
        if self._pending_msgs and self._pending_ckpt is not None and \
                self._pending_ckpt != commit_identifier:
            # the staged messages already rode a commit attempt under an
            # OLDER identifier; if that attempt actually landed (crash
            # between CAS and ack), committing them again here would
            # re-deliver rows the table already holds
            if not commit.filter_committed([self._pending_ckpt]):
                self._pending_msgs = []
            self._pending_ckpt = None
        msgs = list(self._pending_msgs)
        self._pending_msgs = []
        self._pending_ckpt = None
        if self._writer is not None:
            try:
                msgs.extend(self._writer.prepare_commit())
            except Exception:
                # the pipelined flush pool latched a worker error: shut
                # the writer down (joining its pool) before re-raising
                # so a retried checkpoint starts from a clean writer —
                # and RESTORE the staged pre-evolution messages, whose
                # files are already uploaded and must not be lost when
                # the retried checkpoint commits
                self._pending_msgs = msgs
                self._writer.close()
                self._writer = None
                raise
        if not commit.filter_committed([commit_identifier]):
            return None          # replayed checkpoint: exactly-once
        try:
            # (TableCommit force-creates empty snapshots for any
            # non-batch identifier, so bypassing the early return
            # above is all `force_create` needs to do here)
            return commit.commit(msgs,
                                 commit_identifier=commit_identifier,
                                 properties=properties)
        except Exception:
            # the snapshot CAS may or may not have landed (e.g. the
            # process is dying mid-checkpoint): keep the messages,
            # KEYED by this identifier, so the retried/replayed
            # checkpoint can resolve which happened via
            # filter_committed instead of guessing
            self._pending_msgs = msgs
            self._pending_ckpt = commit_identifier
            raise

    def close(self):
        if self._writer is not None:
            self._writer.close()
            self._writer = None
