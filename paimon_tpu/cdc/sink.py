"""Schema-evolving CDC sink.

reference: paimon-flink-cdc sink/cdc/CdcRecordStoreMultiWriteOperator +
UpdatedDataFieldsProcessFunction: unseen columns trigger ADD COLUMN
through the SchemaManager (optimistic-lock DDL), then the writer reloads
the evolved schema and writes the batch with proper row kinds.
"""

from __future__ import annotations

import datetime
import decimal
from typing import Callable, Dict, List, Optional

import numpy as np
import pyarrow as pa

from paimon_tpu.cdc.formats import (
    parse_canal, parse_debezium, parse_maxwell,
)
from paimon_tpu.schema.schema_manager import SchemaChange
from paimon_tpu.table.table import FileStoreTable
from paimon_tpu.types import (
    BigIntType, BooleanType, DataType, DoubleType, TimestampType,
    VarCharType,
)

__all__ = ["CdcSinkWriter"]

_PARSERS: Dict[str, Callable] = {
    "debezium": parse_debezium,
    "canal": parse_canal,
    "maxwell": parse_maxwell,
}


def _infer_type(values: List) -> DataType:
    """Conservative type inference for a new CDC column (reference
    TypeMapping: unknown -> STRING)."""
    non_null = [v for v in values if v is not None]
    if not non_null:
        return VarCharType()
    if all(isinstance(v, bool) for v in non_null):
        return BooleanType()
    if all(isinstance(v, int) and not isinstance(v, bool)
           for v in non_null):
        return BigIntType()
    if all(isinstance(v, (int, float, decimal.Decimal))
           and not isinstance(v, bool) for v in non_null):
        return DoubleType()
    if all(isinstance(v, datetime.datetime) for v in non_null):
        return TimestampType()
    return VarCharType()


class CdcSinkWriter:
    """Parses CDC events, evolves the schema for unseen columns and
    writes through the normal table write path."""

    def __init__(self, table: FileStoreTable, format: str = "debezium",
                 commit_user: Optional[str] = None):
        if format not in _PARSERS:
            raise ValueError(f"Unknown CDC format {format!r}; "
                             f"available: {sorted(_PARSERS)}")
        self._parse = _PARSERS[format]
        self.table = table
        self.commit_user = commit_user or "cdc"
        self._writer = None
        self._pending_msgs = []

    def _ensure_schema(self, rows: List[Dict]):
        """ADD COLUMN for keys the table does not know yet."""
        known = {f.name for f in self.table.schema.fields}
        unseen: Dict[str, List] = {}
        for row in rows:
            for k, v in row.items():
                if k not in known:
                    unseen.setdefault(k, []).append(v)
        if not unseen:
            return
        changes = [SchemaChange.add_column(name, _infer_type(vals))
                   for name, vals in unseen.items()]
        if self._writer is not None:
            # the old writer may hold buffered, uncommitted rows: turn
            # them into pending commit messages before discarding it
            self._pending_msgs.extend(self._writer.prepare_commit())
            self._writer.close()
            self._writer = None
        self.table.schema_manager.commit_changes(*changes)
        dynamic = dict(self.table.schema.options)
        if self.table.branch != "main":
            dynamic["branch"] = self.table.branch
        self.table = FileStoreTable.load(
            self.table.path, file_io=self.table.file_io,
            dynamic_options=dynamic)

    def write_events(self, events: List[dict]):
        changes = []
        for event in events:
            changes.extend(self._parse(event))
        if not changes:
            return
        rows = [c[0] for c in changes]
        kinds = np.array([c[1] for c in changes], dtype=np.int8)
        self._ensure_schema(rows)
        if self._writer is None:
            wb = self.table.new_stream_write_builder() \
                .with_commit_user(self.commit_user)
            self._wb = wb
            self._writer = wb.new_write()
        schema = self.table.arrow_schema()
        normalized = [{f.name: row.get(f.name) for f in schema}
                      for row in rows]
        batch = pa.Table.from_pylist(normalized, schema=schema)
        self._writer.write_arrow(batch, kinds)

    def commit(self, commit_identifier: int) -> Optional[int]:
        if self._writer is None and not self._pending_msgs:
            return None
        if self._writer is None:
            wb = self.table.new_stream_write_builder() \
                .with_commit_user(self.commit_user)
            self._wb = wb
        commit = self._wb.new_commit()
        msgs = list(self._pending_msgs)
        self._pending_msgs = []
        if self._writer is not None:
            msgs.extend(self._writer.prepare_commit())
        if not commit.filter_committed([commit_identifier]):
            return None          # replayed checkpoint: exactly-once
        return commit.commit(msgs, commit_identifier=commit_identifier)

    def close(self):
        if self._writer is not None:
            self._writer.close()
            self._writer = None
