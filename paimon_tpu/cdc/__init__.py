"""CDC ingestion: change-event parsing + schema-evolving sink.

reference: paimon-flink-cdc (action/cdc/: mysql/postgres/kafka sync
actions; format/: debezium, canal, maxwell parsers; sink/cdc/:
CdcRecordStoreMultiWriteOperator applying schema changes through
SchemaManager before writing).
"""

from paimon_tpu.cdc.sink import CdcSinkWriter  # noqa: F401
from paimon_tpu.cdc.database_sync import CdcDatabaseSync  # noqa: F401
from paimon_tpu.cdc.formats import (  # noqa: F401
    parse_canal, parse_debezium, parse_maxwell,
)
from paimon_tpu.cdc.source import (  # noqa: F401
    FileCdcSource, MemoryCdcSource,
)
