"""Replayable CDC event sources with dense integer offsets.

The stream daemon (service/stream_daemon.py) checkpoints the offset of
the last event it committed, atomically with the snapshot; recovery
re-polls the source from that offset.  That only works when the source
can replay: `poll(after_offset, max_events)` must return the SAME
events for the same offsets on every call (a Kafka-like contract —
offsets are dense 0-based positions here).

Two implementations:

- `MemoryCdcSource` — an appendable in-memory log (tests, the soak
  harness, embedding);
- `FileCdcSource` — tails a JSONL file of CDC envelopes, offset = line
  number (the CLI `paimon table stream --source events.jsonl`).  The
  file is append-only; new lines become new events on the next poll.
"""

from __future__ import annotations

import json
import threading
from typing import Dict, List, Tuple

__all__ = ["MemoryCdcSource", "FileCdcSource"]

Polled = List[Tuple[int, Dict]]


class MemoryCdcSource:
    """Thread-safe appendable event log; offset = position."""

    def __init__(self, events=None):
        self._events: List[Dict] = list(events or [])
        self._lock = threading.Lock()

    def append(self, *events: Dict) -> int:
        """Append events; returns the offset of the last one."""
        with self._lock:
            self._events.extend(events)
            return len(self._events) - 1

    def poll(self, after_offset: int, max_events: int) -> Polled:
        with self._lock:
            start = after_offset + 1
            chunk = self._events[start:start + max(0, max_events)]
        return [(start + i, e) for i, e in enumerate(chunk)]

    def backlog(self, after_offset: int) -> int:
        with self._lock:
            return max(0, len(self._events) - (after_offset + 1))

    def latest_offset(self) -> int:
        with self._lock:
            return len(self._events) - 1


class FileCdcSource:
    """JSONL file tail: one CDC envelope per line, offset = line index.

    Lines read so far are cached so recovery replays without re-reading
    the whole file; an incomplete trailing line (a writer mid-append)
    is left in the buffer until its newline arrives.

    Memory is bounded for long-running daemons: `commit_through(off)`
    (called by the stream daemon after each checkpoint) evicts cached
    events at/below the durably committed offset — replay only ever
    needs offsets past the last checkpoint, and a NEW process re-reads
    the file from scratch anyway.
    """

    def __init__(self, path: str):
        self.path = path
        self._events: List[Dict] = []
        self._base = 0              # offset of self._events[0]
        self._pos = 0               # byte offset of the next unread line
        self._tail = b""            # incomplete trailing line
        self._lock = threading.Lock()

    def _refill(self):
        try:
            with open(self.path, "rb") as f:
                f.seek(self._pos)
                data = f.read()
        except FileNotFoundError:
            return
        if not data:
            return
        self._pos += len(data)
        buf = self._tail + data
        lines = buf.split(b"\n")
        self._tail = lines.pop()
        for line in lines:
            line = line.strip()
            if line:
                self._events.append(json.loads(line))

    def commit_through(self, offset: int):
        """Evict cached events at/below the durably committed offset."""
        with self._lock:
            drop = min(max(0, offset + 1 - self._base),
                       len(self._events))
            if drop:
                del self._events[:drop]
                self._base += drop

    def poll(self, after_offset: int, max_events: int) -> Polled:
        with self._lock:
            self._refill()
            start = max(after_offset + 1, self._base)
            i0 = start - self._base
            chunk = self._events[i0:i0 + max(0, max_events)]
        return [(start + i, e) for i, e in enumerate(chunk)]

    def backlog(self, after_offset: int) -> int:
        with self._lock:
            self._refill()
            return max(0, self._base + len(self._events)
                       - (after_offset + 1))

    def latest_offset(self) -> int:
        with self._lock:
            self._refill()
            return self._base + len(self._events) - 1
