"""CDC event format parsers -> (row dict, RowKind) changes.

reference: paimon-flink-cdc format/ parsers (DebeziumRecordParser,
CanalRecordParser, MaxwellRecordParser). Each parser yields zero or more
(row, kind) pairs per event; updates expand to -U/+U pairs.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Tuple

from paimon_tpu.types import RowKind

__all__ = ["parse_debezium", "parse_canal", "parse_maxwell"]

Change = Tuple[Dict, int]


def parse_debezium(event: dict) -> List[Change]:
    """Debezium envelope: {op: c|r|u|d, before: {...}, after: {...}}
    (payload unwrapping handled)."""
    payload = event.get("payload", event)
    op = payload.get("op")
    before = payload.get("before")
    after = payload.get("after")
    if op in ("c", "r"):
        return [(after, RowKind.INSERT)] if after else []
    if op == "u":
        out: List[Change] = []
        if before:
            out.append((before, RowKind.UPDATE_BEFORE))
        if after:
            out.append((after, RowKind.UPDATE_AFTER))
        return out
    if op == "d":
        return [(before, RowKind.DELETE)] if before else []
    raise ValueError(f"Unknown debezium op {op!r}")


def parse_canal(event: dict) -> List[Change]:
    """Canal JSON: {type: INSERT|UPDATE|DELETE, data: [...], old: [...]}."""
    etype = (event.get("type") or "").upper()
    data = event.get("data") or []
    old = event.get("old") or []
    out: List[Change] = []
    if etype == "INSERT":
        out.extend((row, RowKind.INSERT) for row in data)
    elif etype == "DELETE":
        out.extend((row, RowKind.DELETE) for row in data)
    elif etype == "UPDATE":
        for i, row in enumerate(data):
            if i < len(old) and old[i]:
                merged = dict(row)
                merged.update(old[i])
                out.append((merged, RowKind.UPDATE_BEFORE))
            out.append((row, RowKind.UPDATE_AFTER))
    else:
        raise ValueError(f"Unknown canal type {etype!r}")
    return out


def parse_maxwell(event: dict) -> List[Change]:
    """Maxwell JSON: {type: insert|update|delete, data: {...},
    old: {...}}."""
    etype = (event.get("type") or "").lower()
    data = event.get("data") or {}
    old = event.get("old") or {}
    if etype == "insert" or etype == "bootstrap-insert":
        return [(data, RowKind.INSERT)]
    if etype == "delete":
        return [(data, RowKind.DELETE)]
    if etype == "update":
        before = dict(data)
        before.update(old)
        return [(before, RowKind.UPDATE_BEFORE),
                (data, RowKind.UPDATE_AFTER)]
    raise ValueError(f"Unknown maxwell type {etype!r}")
