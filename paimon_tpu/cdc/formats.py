"""CDC event format parsers -> (row dict, RowKind) changes.

reference: paimon-flink-cdc format/ parsers (DebeziumRecordParser,
CanalRecordParser, MaxwellRecordParser). Each parser yields zero or more
(row, kind) pairs per event; updates expand to -U/+U pairs.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Tuple

from paimon_tpu.types import RowKind

__all__ = ["parse_debezium", "parse_canal", "parse_maxwell",
           "parse_ogg", "parse_dms", "parse_aliyun"]

Change = Tuple[Dict, int]


def parse_debezium(event: dict) -> List[Change]:
    """Debezium envelope: {op: c|r|u|d, before: {...}, after: {...}}
    (payload unwrapping handled)."""
    payload = event.get("payload", event)
    op = payload.get("op")
    before = payload.get("before")
    after = payload.get("after")
    if op in ("c", "r"):
        return [(after, RowKind.INSERT)] if after else []
    if op == "u":
        out: List[Change] = []
        if before:
            out.append((before, RowKind.UPDATE_BEFORE))
        if after:
            out.append((after, RowKind.UPDATE_AFTER))
        return out
    if op == "d":
        return [(before, RowKind.DELETE)] if before else []
    raise ValueError(f"Unknown debezium op {op!r}")


def parse_canal(event: dict) -> List[Change]:
    """Canal JSON: {type: INSERT|UPDATE|DELETE, data: [...], old: [...]}."""
    etype = (event.get("type") or "").upper()
    data = event.get("data") or []
    old = event.get("old") or []
    out: List[Change] = []
    if etype == "INSERT":
        out.extend((row, RowKind.INSERT) for row in data)
    elif etype == "DELETE":
        out.extend((row, RowKind.DELETE) for row in data)
    elif etype == "UPDATE":
        for i, row in enumerate(data):
            if i < len(old) and old[i]:
                merged = dict(row)
                merged.update(old[i])
                out.append((merged, RowKind.UPDATE_BEFORE))
            out.append((row, RowKind.UPDATE_AFTER))
    else:
        raise ValueError(f"Unknown canal type {etype!r}")
    return out


def parse_ogg(event: dict) -> List[Change]:
    """Oracle GoldenGate JSON: {op_type: I|U|D, before: {...},
    after: {...}} (reference ogg/OggRecordParser.java)."""
    op = (event.get("op_type") or "").upper()
    before = event.get("before")
    after = event.get("after")
    if op == "I":
        return [(after, RowKind.INSERT)] if after else []
    if op == "U":
        out: List[Change] = []
        if before:
            out.append((before, RowKind.UPDATE_BEFORE))
        if after:
            out.append((after, RowKind.UPDATE_AFTER))
        return out
    if op == "D":
        return [(before, RowKind.DELETE)] if before else []
    raise ValueError(f"Unknown ogg op_type {op!r}")


def parse_dms(event: dict) -> List[Change]:
    """AWS DMS JSON: {data: {...}, metadata: {record-type: data,
    operation: load|insert|update|delete}}; an update carries the
    pre-image in BI_-prefixed columns of `data`
    (reference dms/DMSRecordParser.java)."""
    meta = event.get("metadata") or {}
    if (meta.get("record-type") or "") not in ("data", ""):
        return []                      # control/ddl records
    op = (meta.get("operation") or "").lower()
    data = event.get("data") or {}
    current = {k: v for k, v in data.items() if not k.startswith("BI_")}
    if op in ("load", "insert"):
        return [(current, RowKind.INSERT)]
    if op == "delete":
        return [(current, RowKind.DELETE)]
    if op == "update":
        before = dict(current)
        before.update({k[3:]: v for k, v in data.items()
                       if k.startswith("BI_")})
        return [(before, RowKind.UPDATE_BEFORE),
                (current, RowKind.UPDATE_AFTER)]
    raise ValueError(f"Unknown dms operation {op!r}")


def parse_aliyun(event: dict) -> List[Change]:
    """Aliyun DTS JSON: {op: INSERT|UPDATE_BEFORE|UPDATE_AFTER|DELETE,
    payload: {before: {dataColumn: {...}}, after: {dataColumn:
    {...}}}} — updates arrive as SEPARATE -U/+U events
    (reference aliyun/AliyunRecordParser.java)."""
    if event.get("ddl"):
        return []
    op = (event.get("op") or "").upper()
    payload = event.get("payload") or {}

    def cols(section: str) -> Dict:
        # dataColumn is REQUIRED — falling back to the raw section
        # would leak envelope metadata into the row and the
        # schema-evolving sink would ADD COLUMN bogus fields
        return (payload.get(section) or {}).get("dataColumn") or {}

    def one(section: str, kind: int) -> List[Change]:
        row = cols(section)
        return [(row, kind)] if row else []

    if op == "INSERT":
        return one("after", RowKind.INSERT)
    if op == "UPDATE_BEFORE":
        return one("before", RowKind.UPDATE_BEFORE)
    if op == "UPDATE_AFTER":
        return one("after", RowKind.UPDATE_AFTER)
    if op == "DELETE":
        return one("before", RowKind.DELETE)
    raise ValueError(f"Unknown aliyun op {op!r}")


def parse_maxwell(event: dict) -> List[Change]:
    """Maxwell JSON: {type: insert|update|delete, data: {...},
    old: {...}}."""
    etype = (event.get("type") or "").lower()
    data = event.get("data") or {}
    old = event.get("old") or {}
    if etype == "insert" or etype == "bootstrap-insert":
        return [(data, RowKind.INSERT)]
    if etype == "delete":
        return [(data, RowKind.DELETE)]
    if etype == "update":
        before = dict(data)
        before.update(old)
        return [(before, RowKind.UPDATE_BEFORE),
                (data, RowKind.UPDATE_AFTER)]
    raise ValueError(f"Unknown maxwell type {etype!r}")
