"""Whole-database CDC synchronization.

reference: paimon-flink-cdc action/cdc/SyncDatabaseActionBase (+
CdcDynamicTableParsingProcessFunction): one stream of CDC events for
MANY source tables routes to per-table schema-evolving sinks; unseen
tables are auto-created with schema inferred from their first events,
with regex including/excluding filters and shared table options.

Event -> table routing uses the envelopes' own metadata: debezium
`payload.source.{db,table}`, canal/maxwell top-level
`database`/`table`.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple

from paimon_tpu.cdc.sink import CdcSinkWriter, _infer_type
from paimon_tpu.schema import Schema

__all__ = ["CdcDatabaseSync"]


def _event_table_id(event: dict, fmt: str) -> Tuple[str, str]:
    if fmt == "debezium":
        src = event.get("payload", event).get("source", {}) or {}
        return (src.get("db") or src.get("database") or "default",
                src.get("table") or "unknown")
    return (event.get("database") or "default",
            event.get("table") or "unknown")


def _event_primary_keys(event: dict, fmt: str) -> List[str]:
    if fmt == "maxwell":
        return list(event.get("primary_key_columns") or [])
    if fmt == "canal":
        return list(event.get("pkNames") or [])
    # debezium: key schema is usually separate; callers pass
    # primary_keys explicitly when the envelope lacks it
    return []


class CdcDatabaseSync:
    """Route a mixed CDC stream into a catalog database, creating and
    evolving tables as events arrive."""

    def __init__(self, catalog, database: str, format: str = "debezium",
                 source_database: Optional[str] = None,
                 including_tables: Optional[str] = None,
                 excluding_tables: Optional[str] = None,
                 primary_keys: Optional[Dict[str, List[str]]] = None,
                 table_options: Optional[Dict[str, str]] = None,
                 computed_columns: Optional[Dict[str, List[str]]] = None,
                 commit_user: str = "cdc-db-sync"):
        self.catalog = catalog
        self.database = database
        # events from OTHER source databases never merge in (reference
        # SyncDatabaseAction syncs exactly one source database)
        self.source_database = source_database or database
        self.format = format
        self.including = re.compile(including_tables) \
            if including_tables else None
        self.excluding = re.compile(excluding_tables) \
            if excluding_tables else None
        self.primary_keys = primary_keys or {}
        self.table_options = {"bucket": "1", "write-only": "true",
                              **(table_options or {})}
        self.computed_columns = computed_columns or {}
        self.commit_user = commit_user
        self._writers: Dict[str, CdcSinkWriter] = {}
        catalog.create_database(database, ignore_if_exists=True)

    def _accepts(self, name: str) -> bool:
        if self.including is not None and \
                not self.including.fullmatch(name):
            return False
        if self.excluding is not None and \
                self.excluding.fullmatch(name):
            return False
        return True

    def _writer_for(self, name: str,
                    first_events: List[dict]) -> CdcSinkWriter:
        w = self._writers.get(name)
        if w is not None:
            return w
        ident = f"{self.database}.{name}"
        if not self.catalog.table_exists(ident):
            self.catalog.create_table(
                ident, self._infer_schema(name, first_events),
                ignore_if_exists=True)
        table = self.catalog.get_table(ident)
        w = CdcSinkWriter(
            table, format=self.format, commit_user=self.commit_user,
            computed_columns=self.computed_columns.get(name))
        self._writers[name] = w
        return w

    def _infer_schema(self, name: str, events: List[dict]) -> Schema:
        from paimon_tpu.cdc.sink import _PARSERS
        parse = _PARSERS[self.format]
        cols: Dict[str, List] = {}
        pks = list(self.primary_keys.get(name) or [])
        for event in events:
            if not pks:
                pks = _event_primary_keys(event, self.format)
            for row, _kind in parse(event):
                for k, v in row.items():
                    cols.setdefault(k, []).append(v)
        if not pks:
            raise ValueError(
                f"cannot infer primary keys for table {name!r}: pass "
                f"primary_keys={{'{name}': [...]}} (reference "
                f"SyncDatabaseAction --primary-keys)")
        b = Schema.builder()
        for col, vals in cols.items():
            t = _infer_type(vals)
            if col in pks:
                t = t.copy(False)
            b = b.column(col, t)
        return b.primary_key(*pks).options(self.table_options).build()

    def write_events(self, events: List[dict]):
        by_table: Dict[str, List[dict]] = {}
        for event in events:
            db, name = _event_table_id(event, self.format)
            if db != self.source_database:
                continue
            if self._accepts(name):
                by_table.setdefault(name, []).append(event)
        for name, evs in by_table.items():
            self._writer_for(name, evs).write_events(evs)

    def commit(self, commit_identifier: int,
               properties: Optional[Dict[str, str]] = None
               ) -> Dict[str, Optional[int]]:
        return {name: w.commit(commit_identifier, properties=properties)
                for name, w in self._writers.items()}

    def tables(self) -> List[str]:
        return sorted(self._writers)

    def close(self):
        for w in self._writers.values():
            w.close()
        self._writers.clear()
