"""File formats (L2).

Plugin boundary analogous to the reference's ``FileFormat``
(paimon-common/.../format/FileFormat.java:43): ``get_format(identifier)``
returns a reader/writer factory pair operating on Arrow tables.

- parquet / orc: pyarrow (Arrow C++) with stats extraction and predicate
  pushdown -- the decode feeds device-ready columnar buffers.
- avro: own pure-Python codec (paimon_tpu/format/avro.py) because manifests
  are avro object files and must stay wire-compatible.
"""

from paimon_tpu.format.format import (  # noqa: F401
    FileFormatFactory, get_format, FormatReader, FormatWriter,
)
