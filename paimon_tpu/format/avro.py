"""Avro binary codec + object container files, pure Python.

Implemented from the Apache Avro 1.11 specification (binary encoding +
object container files). The reference serializes manifests as avro object
files (docs/docs/concepts/spec/manifest.md:34); this module keeps those
files wire-compatible without a fastavro dependency.

Supported: all primitives, records, arrays, maps, unions, fixed, enums;
logicalType timestamp-millis (int <-> datetime left to callers: values pass
through as ints); codecs null / deflate / zstandard.
"""

from __future__ import annotations

import io
import json
import os
import struct
import zlib
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

try:
    import zstandard as _zstd
except ImportError:  # pragma: no cover
    _zstd = None

__all__ = ["encode_value", "decode_value", "write_container",
           "read_container", "AvroSchemaError"]

MAGIC = b"Obj\x01"


class AvroSchemaError(ValueError):
    pass


# ---------------------------------------------------------------------------
# Binary encoding
# ---------------------------------------------------------------------------

def _write_long(buf: io.BytesIO, n: int):
    # zigzag + varint
    n = (n << 1) ^ (n >> 63)
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            buf.write(bytes([b | 0x80]))
        else:
            buf.write(bytes([b]))
            return


def _read_long(buf: io.BytesIO) -> int:
    shift = 0
    acc = 0
    while True:
        byte = buf.read(1)
        if not byte:
            raise EOFError("unexpected end of avro data")
        b = byte[0]
        acc |= (b & 0x7F) << shift
        if not (b & 0x80):
            break
        shift += 7
    return (acc >> 1) ^ -(acc & 1)


def _schema_type(schema) -> str:
    if isinstance(schema, str):
        return schema
    if isinstance(schema, list):
        return "union"
    return schema["type"]


def encode_value(schema, value, buf: io.BytesIO):
    t = _schema_type(schema)
    if t == "null":
        if value is not None:
            raise AvroSchemaError(f"non-null value {value!r} for null schema")
        return
    if t == "boolean":
        buf.write(b"\x01" if value else b"\x00")
    elif t in ("int", "long"):
        _write_long(buf, int(value))
    elif t == "float":
        buf.write(struct.pack("<f", float(value)))
    elif t == "double":
        buf.write(struct.pack("<d", float(value)))
    elif t == "bytes":
        data = bytes(value)
        _write_long(buf, len(data))
        buf.write(data)
    elif t == "string":
        data = value.encode("utf-8") if isinstance(value, str) else bytes(value)
        _write_long(buf, len(data))
        buf.write(data)
    elif t == "fixed":
        data = bytes(value)
        if len(data) != schema["size"]:
            raise AvroSchemaError("fixed size mismatch")
        buf.write(data)
    elif t == "enum":
        buf.write(b"")
        _write_long(buf, schema["symbols"].index(value))
    elif t == "union":
        idx = _resolve_union(schema, value)
        _write_long(buf, idx)
        encode_value(schema[idx], value, buf)
    elif t == "record":
        for f in schema["fields"]:
            try:
                fv = value.get(f["name"], f.get("default"))
            except AttributeError:
                raise AvroSchemaError(
                    f"record value must be a dict, got {type(value)}")
            encode_value(f["type"], fv, buf)
    elif t == "array":
        items = list(value or [])
        if items:
            _write_long(buf, len(items))
            for item in items:
                encode_value(schema["items"], item, buf)
        _write_long(buf, 0)
    elif t == "map":
        entries = dict(value or {})
        if entries:
            _write_long(buf, len(entries))
            for k, v in entries.items():
                encode_value("string", k, buf)
                encode_value(schema["values"], v, buf)
        _write_long(buf, 0)
    else:
        raise AvroSchemaError(f"Unknown avro type: {t!r}")


def _resolve_union(union: list, value) -> int:
    """Pick the union branch for a Python value."""
    def matches(s, v) -> bool:
        st = _schema_type(s)
        if st == "null":
            return v is None
        if v is None:
            return False
        if st == "boolean":
            return isinstance(v, bool)
        if st in ("int", "long"):
            return isinstance(v, int) and not isinstance(v, bool)
        if st in ("float", "double"):
            return isinstance(v, float)
        if st in ("bytes", "fixed"):
            return isinstance(v, (bytes, bytearray, memoryview))
        if st == "string":
            return isinstance(v, str)
        if st == "array":
            return isinstance(v, (list, tuple))
        if st in ("map", "record"):
            return isinstance(v, dict)
        if st == "enum":
            return isinstance(v, str)
        return False

    for i, s in enumerate(union):
        if matches(s, value):
            return i
    raise AvroSchemaError(f"Value {value!r} matches no branch of {union}")


def decode_value(schema, buf: io.BytesIO):
    t = _schema_type(schema)
    if t == "null":
        return None
    if t == "boolean":
        return buf.read(1) == b"\x01"
    if t in ("int", "long"):
        return _read_long(buf)
    if t == "float":
        return struct.unpack("<f", buf.read(4))[0]
    if t == "double":
        return struct.unpack("<d", buf.read(8))[0]
    if t == "bytes":
        n = _read_long(buf)
        return buf.read(n)
    if t == "string":
        n = _read_long(buf)
        return buf.read(n).decode("utf-8")
    if t == "fixed":
        return buf.read(schema["size"])
    if t == "enum":
        return schema["symbols"][_read_long(buf)]
    if t == "union":
        return decode_value(schema[_read_long(buf)], buf)
    if t == "record":
        return {f["name"]: decode_value(f["type"], buf)
                for f in schema["fields"]}
    if t == "array":
        out = []
        while True:
            n = _read_long(buf)
            if n == 0:
                break
            if n < 0:
                n = -n
                _read_long(buf)  # block size in bytes, unused
            for _ in range(n):
                out.append(decode_value(schema["items"], buf))
        return out
    if t == "map":
        out = {}
        while True:
            n = _read_long(buf)
            if n == 0:
                break
            if n < 0:
                n = -n
                _read_long(buf)
            for _ in range(n):
                k = decode_value("string", buf)
                out[k] = decode_value(schema["values"], buf)
        return out
    raise AvroSchemaError(f"Unknown avro type: {t!r}")


# ---------------------------------------------------------------------------
# Object container files
# ---------------------------------------------------------------------------

def _compress(codec: str, data: bytes) -> bytes:
    if codec == "null":
        return data
    if codec == "deflate":
        c = zlib.compressobj(9, zlib.DEFLATED, -15)
        return c.compress(data) + c.flush()
    if codec == "zstandard":
        if _zstd is not None:
            return _zstd.ZstdCompressor(level=3).compress(data)
        return _pa_zstd_compress(data)
    raise AvroSchemaError(f"Unknown avro codec {codec!r}")


def _decompress(codec: str, data: bytes) -> bytes:
    if codec == "null":
        return data
    if codec == "deflate":
        return zlib.decompress(data, -15)
    if codec == "zstandard":
        if _zstd is not None:
            return _zstd.ZstdDecompressor().decompress(
                data, max_output_size=1 << 31)
        return _pa_zstd_decompress(data)
    raise AvroSchemaError(f"Unknown avro codec {codec!r}")


def _pa_zstd_compress(data: bytes) -> bytes:
    """zstd via pyarrow's bundled codec when the `zstandard` module is
    absent.  The streaming writer emits standard zstd frames (magic
    0x28B52FFD), byte-compatible with what any avro reader expects."""
    import pyarrow as pa
    sink = pa.BufferOutputStream()
    with pa.CompressedOutputStream(sink, "zstd") as s:
        s.write(data)
    return sink.getvalue().to_pybytes()


def _pa_zstd_decompress(data: bytes) -> bytes:
    """Streaming decompress: avro blocks don't record the decompressed
    size, and pyarrow's one-shot pa.decompress demands it — the
    CompressedInputStream path does not."""
    import pyarrow as pa
    with pa.CompressedInputStream(pa.BufferReader(data), "zstd") as s:
        return s.read()


def write_container(schema, records: Iterable[dict],
                    codec: str = "zstandard",
                    sync_marker: Optional[bytes] = None,
                    block_records: int = 4096) -> bytes:
    """Serialize records into an avro object container file (bytes)."""
    sync = sync_marker or os.urandom(16)
    out = io.BytesIO()
    out.write(MAGIC)
    meta = {"avro.schema": json.dumps(schema).encode("utf-8"),
            "avro.codec": codec.encode("utf-8")}
    encode_value({"type": "map", "values": "bytes"}, meta, out)
    out.write(sync)

    block = io.BytesIO()
    count = 0

    def flush():
        nonlocal block, count
        if count == 0:
            return
        data = _compress(codec, block.getvalue())
        _write_long(out, count)
        _write_long(out, len(data))
        out.write(data)
        out.write(sync)
        block = io.BytesIO()
        count = 0

    for rec in records:
        encode_value(schema, rec, block)
        count += 1
        if count >= block_records:
            flush()
    flush()
    return out.getvalue()


def read_container(data: bytes) -> Tuple[dict, List[dict]]:
    """Parse an avro object container file -> (schema, records)."""
    buf = io.BytesIO(data)
    if buf.read(4) != MAGIC:
        raise AvroSchemaError("Not an avro object container file")
    meta = decode_value({"type": "map", "values": "bytes"}, buf)
    schema = json.loads(meta["avro.schema"].decode("utf-8"))
    codec = meta.get("avro.codec", b"null").decode("utf-8")
    sync = buf.read(16)
    records: List[dict] = []
    while True:
        head = buf.read(1)
        if not head:
            break
        buf.seek(-1, io.SEEK_CUR)
        count = _read_long(buf)
        size = _read_long(buf)
        payload = _decompress(codec, buf.read(size))
        if buf.read(16) != sync:
            raise AvroSchemaError("Sync marker mismatch")
        bbuf = io.BytesIO(payload)
        for _ in range(count):
            records.append(decode_value(schema, bbuf))
    return schema, records
