"""Mosaic: bucketed-columnar multimodal file format.

reference: paimon-mosaic/src/main/java/org/apache/paimon/format/mosaic/
MosaicFileFormat.java (surface: Arrow-batch writes, per-column
statistics via `mosaic.stats-columns`, `mosaic.num-buckets` column
buckets for parallel/partial IO, zstd compression, row-group max size,
writer metadata in MosaicWriterMetadata.java).  The reference's actual
byte codec lives in a native library that is not part of the source
tree, so this is a from-scratch encoding with the same capability
surface, built on Arrow IPC (the repo's native columnar plane).

Layout (little-endian):

    "MOS1"
    row group 0, column-bucket 0: Arrow IPC stream (internal zstd)
    row group 0, column-bucket 1: ...
    row group 1, column-bucket 0: ...
    ...
    footer: zstd-compressed JSON (schema, bucket layout, per-row-group
            bucket offsets/sizes + column min/max/null stats, writer
            metadata)
    u32 footer byte length
    "MOS1"

Why bucketed-columnar: multimodal rows mix tiny scalars with megabyte
blobs; by storing each column bucket as an independently fetchable
blob, a projection touches only the buckets it needs (default: one
bucket per column = pure columnar), and buckets of one row group can
be fetched in parallel.  Row-group column stats drive predicate
skipping without touching data bytes.
"""

from __future__ import annotations

import io
import json
import struct
from typing import Any, Dict, List, Optional, Sequence

import pyarrow as pa

from paimon_tpu.format.format import (
    FileFormatFactory, FormatReader, FormatWriter, extract_simple_stats,
)
from paimon_tpu.fs import FileIO

__all__ = ["MosaicWriter", "MosaicReader", "read_footer",
           "MOSAIC_FACTORY"]

_MAGIC = b"MOS1"
_VERSION = 1
DEFAULT_ROW_GROUP_ROWS = 1 << 16


def _json_safe(v: Any):
    import datetime
    if v is None or isinstance(v, (int, float, str, bool)):
        return v
    if isinstance(v, bytes):
        return {"b64": __import__("base64").b64encode(v).decode()}
    if isinstance(v, datetime.datetime):
        return {"iso": v.isoformat(), "k": "dt"}
    if isinstance(v, datetime.date):
        return {"iso": v.isoformat(), "k": "d"}
    if isinstance(v, datetime.time):
        return {"iso": v.isoformat(), "k": "t"}
    return str(v)


class MosaicWriter(FormatWriter):
    def __init__(self, compression: str = "zstd",
                 row_group_rows: int = DEFAULT_ROW_GROUP_ROWS,
                 num_buckets: Optional[int] = None,
                 stats_columns: Optional[Sequence[str]] = None,
                 format_options: Optional[Dict[str, str]] = None):
        from paimon_tpu.format.format import split_compression
        codec, level = split_compression(compression or "none")
        if codec in ("none", None):
            self.compression = None
        elif level is not None:
            try:
                self.compression = pa.Codec(codec,
                                            compression_level=level)
            except (pa.ArrowInvalid, TypeError, ValueError):
                # codec has no level knob: keep the codec, drop the
                # level (same fallback posture as _ipc_bytes)
                self.compression = codec
        else:
            self.compression = codec
        self.row_group_rows = row_group_rows
        self.num_buckets = num_buckets      # None -> one bucket per column
        self.stats_columns = list(stats_columns) if stats_columns \
            else None                       # None -> all stat-able columns

    def _bucketize(self, names: List[str]) -> List[List[str]]:
        if self.num_buckets is None or self.num_buckets >= len(names):
            return [[n] for n in names]
        b = max(1, self.num_buckets)
        return [names[i::b] for i in range(b)]

    def _ipc_bytes(self, table: pa.Table) -> bytes:
        sink = io.BytesIO()
        try:
            opts = pa.ipc.IpcWriteOptions(compression=self.compression)
        except (pa.ArrowInvalid, TypeError):
            opts = pa.ipc.IpcWriteOptions()
        with pa.ipc.new_stream(sink, table.schema, options=opts) as w:
            w.write_table(table)
        return sink.getvalue()

    def write(self, file_io: FileIO, path: str, table: pa.Table) -> int:
        names = table.column_names
        buckets = self._bucketize(names)
        stats_cols = self.stats_columns
        if stats_cols is None:
            stats_cols = [f.name for f in table.schema
                          if not pa.types.is_nested(f.type)]

        out = io.BytesIO()
        out.write(_MAGIC)
        row_groups = []
        n = table.num_rows
        step = max(1, self.row_group_rows)
        for start in range(0, max(n, 1), step):
            chunk = table.slice(start, min(step, n - start)) if n else table
            bucket_meta = []
            for cols in buckets:
                blob = self._ipc_bytes(chunk.select(cols))
                bucket_meta.append({"offset": out.tell(),
                                    "size": len(blob)})
                out.write(blob)
            mins, maxs, nulls = extract_simple_stats(chunk, stats_cols)
            stats = {c: {"min": _json_safe(mn), "max": _json_safe(mx),
                         "nulls": nc}
                     for c, mn, mx, nc in zip(stats_cols, mins, maxs,
                                              nulls)}
            row_groups.append({"num_rows": chunk.num_rows,
                               "buckets": bucket_meta, "stats": stats})
            if n == 0:
                break

        import base64
        footer = {
            "version": _VERSION,
            "schema": base64.b64encode(
                table.schema.serialize().to_pybytes()).decode(),
            "num_rows": n,
            "column_buckets": buckets,
            "stats_columns": stats_cols,
            "row_groups": row_groups,
            "writer": {"created_by": "paimon-tpu-mosaic",
                       "format_version": _VERSION},
        }
        fbytes = json.dumps(footer).encode("utf-8")
        raw_len = len(fbytes)
        try:
            comp = pa.Codec("zstd").compress(fbytes)
            comp = comp.to_pybytes() if isinstance(comp, pa.Buffer) \
                else bytes(comp)
            tail = b"Z" + struct.pack("<I", raw_len) + comp
        except (pa.ArrowInvalid, OSError):
            tail = b"R" + fbytes
        out.write(tail)
        out.write(struct.pack("<I", len(tail)))
        out.write(_MAGIC)
        data = out.getvalue()
        file_io.write_bytes(path, data, overwrite=False)
        return len(data)


def _parse_footer_tail(raw: bytes) -> Dict:
    if raw[:1] == b"Z":
        (raw_len,) = struct.unpack_from("<I", raw, 1)
        body = pa.Codec("zstd").decompress(raw[5:],
                                           decompressed_size=raw_len)
        if isinstance(body, pa.Buffer):
            body = body.to_pybytes()
    else:
        body = raw[1:]
    return json.loads(body)


def read_footer(data: bytes) -> Dict:
    if data[:4] != _MAGIC or data[-4:] != _MAGIC:
        raise ValueError("not a mosaic file (bad magic)")
    (flen,) = struct.unpack_from("<I", data, len(data) - 8)
    return _parse_footer_tail(data[len(data) - 8 - flen:len(data) - 8])


def _decode_stat(v):
    if isinstance(v, dict):
        if "b64" in v:
            import base64
            return base64.b64decode(v["b64"])
        if "iso" in v:
            import datetime
            parser = {"dt": datetime.datetime, "d": datetime.date,
                      "t": datetime.time}.get(v.get("k"),
                                              datetime.datetime)
            try:
                return parser.fromisoformat(v["iso"])
            except ValueError:
                return v["iso"]
    return v


class MosaicReader(FormatReader):
    def read(self, file_io: FileIO, path: str,
             projection: Optional[List[str]] = None,
             batch_size: int = 1 << 20,
             predicate=None) -> pa.Table:
        tables = list(self.read_batches(file_io, path, projection,
                                        batch_size, predicate))
        if not tables:
            import base64
            footer = read_footer(file_io.read_bytes(path))
            schema = pa.ipc.read_schema(pa.BufferReader(
                base64.b64decode(footer["schema"])))
            if projection:
                schema = pa.schema([schema.field(c) for c in projection])
            return schema.empty_table()
        return pa.concat_tables(tables, promote_options="none")

    def read_batches(self, file_io: FileIO, path: str,
                     projection: Optional[List[str]] = None,
                     batch_size: int = 1 << 20, predicate=None):
        # footer first (two small tail reads), then ONE vectored read
        # of exactly the surviving row groups' needed bucket ranges —
        # a projection never pays for unprojected columns' bytes
        # (reference fs/VectoredReadable + mosaic partial IO)
        size = file_io.get_file_size(path)
        if size < 12:
            raise ValueError(f"not a mosaic file (too small): {path}")
        (tail,) = file_io.read_ranges(path, [(size - 8, 8)])
        (flen,) = struct.unpack_from("<I", tail, 0)
        if tail[4:] != _MAGIC or flen > size - 12:
            raise ValueError(f"not a mosaic file (bad magic): {path}")
        (raw,) = file_io.read_ranges(path,
                                     [(size - 8 - flen, flen + 8)])
        footer = _parse_footer_tail(raw[:flen])
        buckets: List[List[str]] = footer["column_buckets"]
        wanted = list(projection) if projection else \
            [c for b in buckets for c in b]
        need = [i for i, cols in enumerate(buckets)
                if any(c in wanted for c in cols)]
        groups = [rg for rg in footer["row_groups"]
                  if predicate is None or self._rg_matches(rg,
                                                           predicate)]
        ranges = [(rg["buckets"][i]["offset"], rg["buckets"][i]["size"])
                  for rg in groups for i in need]
        blobs = file_io.read_ranges(path, ranges) if ranges else []
        pos = 0
        for rg in groups:
            parts = []
            for _ in need:
                blob = blobs[pos]
                pos += 1
                with pa.ipc.open_stream(pa.BufferReader(blob)) as r:
                    parts.append(r.read_all())
            if not parts:
                continue
            t = parts[0]
            for p in parts[1:]:
                for col_i, f in enumerate(p.schema):
                    t = t.append_column(f, p.column(col_i))
            yield t.select([c for c in wanted if c in t.column_names])

    @staticmethod
    def _rg_matches(rg: Dict, predicate) -> bool:
        """Row-group skip on footer stats (role of the reference's
        native row-group statistics pruning)."""
        stats = rg.get("stats", {})
        mins = {c: _decode_stat(s.get("min")) for c, s in stats.items()}
        maxs = {c: _decode_stat(s.get("max")) for c, s in stats.items()}
        nulls = {c: s.get("nulls") for c, s in stats.items()}
        try:
            return predicate.test_stats(mins, maxs, nulls,
                                        rg.get("num_rows", 0))
        except Exception:
            return True


def extract_footer_stats(file_io: FileIO, path: str):
    """Whole-file (min, max, null_count) per stats column from the
    footer alone — the MosaicSimpleStatsExtractor analog: stats without
    scanning data bytes."""
    footer = read_footer(file_io.read_bytes(path))
    cols = footer.get("stats_columns", [])
    mins: Dict[str, Any] = {}
    maxs: Dict[str, Any] = {}
    nulls: Dict[str, int] = {c: 0 for c in cols}
    for rg in footer["row_groups"]:
        for c, s in rg.get("stats", {}).items():
            mn, mx = _decode_stat(s.get("min")), _decode_stat(s.get("max"))
            if mn is not None and (c not in mins or mn < mins[c]):
                mins[c] = mn
            if mx is not None and (c not in maxs or mx > maxs[c]):
                maxs[c] = mx
            nulls[c] = nulls.get(c, 0) + (s.get("nulls") or 0)
    return ([mins.get(c) for c in cols], [maxs.get(c) for c in cols],
            [nulls.get(c, 0) for c in cols], cols)


MOSAIC_FACTORY = FileFormatFactory("mosaic", MosaicReader(), MosaicWriter)
