"""FileFormat SPI: reader/writer factories over Arrow tables.

reference boundary: paimon-common/.../format/FileFormat.java:43
(createReaderFactory:62, createWriterFactory:66) + SimpleStatsExtractor.
Parquet/ORC are delegated to Arrow C++ (multithreaded decode straight into
columnar buffers that upload to HBM zero-copy via dlpack); avro rows go
through the pure-Python codec.
"""

from __future__ import annotations

import io
from contextlib import contextmanager
from typing import Any, Dict, List, Optional, Sequence, Tuple

import pyarrow as pa
import pyarrow.parquet as pq

try:
    from pyarrow import orc as pa_orc
except ImportError:  # pragma: no cover
    pa_orc = None

from paimon_tpu.fs import FileIO
from paimon_tpu.types import RowType, row_type_to_arrow_schema

__all__ = ["FileFormatFactory", "get_format", "FormatReader",
           "FormatWriter", "extract_simple_stats", "CorruptDataError"]


class CorruptDataError(OSError):
    """Decode-time corruption: the bytes were already fetched, so the
    failure is deterministic — NOT a transient store fault, never worth
    retrying (parallel/fault.py), but eligible for the
    scan.ignore-corrupt-files skip.  Subclasses OSError because modern
    pyarrow surfaces decode corruption (torn footers, corrupt
    compressed pages) as plain OSError and existing handlers expect
    that; the distinct type is what lets the fault taxonomy separate
    'bad bytes' from 'bad store'."""


@contextmanager
def _decode_errors(path: str):
    """Re-raise decode-phase OSErrors as CorruptDataError (fetch-phase
    store faults never pass through here)."""
    try:
        yield
    except CorruptDataError:
        raise
    except OSError as e:
        raise CorruptDataError(f"corrupt data in {path}: {e}") from e


class FormatReader:
    """Reads a file into an Arrow table, with projection + row-group
    filtering."""

    def read(self, file_io: FileIO, path: str,
             projection: Optional[List[str]] = None,
             batch_size: int = 1 << 20) -> pa.Table:
        raise NotImplementedError

    def read_batches(self, file_io: FileIO, path: str,
                     projection: Optional[List[str]] = None,
                     batch_rows: int = 1 << 20):
        """Yield the file as bounded-size Arrow tables (streamed decode
        where the format supports it; whole-file fallback otherwise)."""
        yield self.read(file_io, path, projection)


class FormatWriter:
    """Writer contract: constructors take (compression, format_options)
    — format_options is the raw option map (e.g. parquet.*) and writers
    ignore keys that aren't theirs."""

    def write(self, file_io: FileIO, path: str, table: pa.Table) -> int:
        """Write table, return file size in bytes."""
        raise NotImplementedError


class _ParquetReader(FormatReader):
    @staticmethod
    def _open(file_io, path) -> "pq.ParquetFile":
        """ParquetFile over the (possibly byte-cached) file, reusing a
        previously parsed footer from the process footer cache
        (fs/caching.py) — repeated scans skip the thrift metadata
        decode entirely."""
        from paimon_tpu.fs.caching import global_footer_cache
        from paimon_tpu.metrics import IO_READ_MS
        from paimon_tpu.obs.trace import span
        with span("io.read", cat="io", group="io", metric=IO_READ_MS,
                  path=path) as sp:
            data = file_io.read_bytes(path)  # store faults propagate
            sp.set(bytes=len(data))
        cache = global_footer_cache()
        md = cache.get(path)
        with _decode_errors(path):
            pf = pq.ParquetFile(io.BytesIO(data), metadata=md)
        if md is None:
            cache.put(path, pf.metadata)
        return pf

    def read(self, file_io, path, projection=None, batch_size=1 << 20):
        from paimon_tpu.metrics import IO_DECODE_MS
        from paimon_tpu.obs.trace import span
        pf = self._open(file_io, path)
        with _decode_errors(path), \
                span("decode", cat="io", group="io",
                     metric=IO_DECODE_MS, path=path):
            return pf.read(columns=projection)

    def read_batches(self, file_io, path, projection=None,
                     batch_rows: int = 1 << 20):
        # compressed bytes stay resident; decode is incremental per batch
        pf = self._open(file_io, path)
        with _decode_errors(path):
            for rb in pf.iter_batches(batch_size=batch_rows,
                                      columns=projection):
                yield pa.Table.from_batches([rb])


def split_compression(spec: str):
    """'zstd' or 'zstd:7' -> (codec, level or None)
    (file.compression.zstd-level wiring)."""
    if spec and ":" in spec:
        codec, _, lvl = spec.partition(":")
        try:
            return codec, int(lvl)
        except ValueError:
            return codec, None
    return spec, None


class _ParquetWriter(FormatWriter):
    def __init__(self, compression: str = "zstd",
                 row_group_rows: int = 1 << 20,
                 format_options: Optional[Dict[str, str]] = None):
        self.compression, self.level = split_compression(compression)
        fo = format_options or {}
        self.row_group_rows = int(fo.get("parquet.row-group.rows",
                                         row_group_rows))
        # file.block-size (reference CoreOptions FILE_BLOCK_SIZE):
        # parquet row-group granularity in BYTES; converted to rows per
        # table at write time
        self.block_bytes = int(fo["file.block-size"]) \
            if "file.block-size" in fo else None
        # parquet.enable.dictionary (reference parquet writer option):
        # dictionary encoding is pure overhead on high-cardinality data
        self.use_dictionary = fo.get(
            "parquet.enable.dictionary", "true").lower() != "false"

    def write(self, file_io, path, table):
        from paimon_tpu.metrics import IO_ENCODE_MS, IO_UPLOAD_MS
        from paimon_tpu.obs.trace import span
        buf = io.BytesIO()
        rg = self.row_group_rows
        if self.block_bytes and table.num_rows:
            per_row = max(1, table.nbytes // table.num_rows)
            rg = max(1024, self.block_bytes // per_row)
        with span("encode", cat="io", group="io", metric=IO_ENCODE_MS,
                  path=path, rows=table.num_rows):
            pq.write_table(table, buf, compression=self.compression,
                           compression_level=self.level,
                           row_group_size=rg,
                           use_dictionary=self.use_dictionary,
                           write_statistics=True)
        data = buf.getvalue()
        with span("io.upload", cat="io", group="io",
                  metric=IO_UPLOAD_MS, path=path, bytes=len(data)):
            file_io.write_bytes(path, data, overwrite=False)
        return len(data)


class _OrcReader(FormatReader):
    def read(self, file_io, path, projection=None, batch_size=1 << 20):
        if pa_orc is None:
            raise RuntimeError("pyarrow.orc unavailable")
        data = file_io.read_bytes(path)      # store faults propagate
        with _decode_errors(path):
            f = pa_orc.ORCFile(io.BytesIO(data))
            return f.read(columns=projection)


class _OrcWriter(FormatWriter):
    def __init__(self, compression: str = "zstd",
                 format_options: Optional[Dict[str, str]] = None):
        self.compression, _ = split_compression(compression)
        fo = format_options or {}
        # file.block-size -> orc stripe bytes
        self.stripe_bytes = int(fo["file.block-size"]) \
            if "file.block-size" in fo else None

    def write(self, file_io, path, table):
        if pa_orc is None:
            raise RuntimeError("pyarrow.orc unavailable")
        buf = io.BytesIO()
        kw = {"stripe_size": self.stripe_bytes} if self.stripe_bytes \
            else {}
        pa_orc.write_table(table, buf,
                           compression=self.compression.upper(), **kw)
        data = buf.getvalue()
        file_io.write_bytes(path, data, overwrite=False)
        return len(data)


class _AvroRowReader(FormatReader):
    def read(self, file_io, path, projection=None, batch_size=1 << 20):
        from paimon_tpu.format import avro as avro_fmt
        _, records = avro_fmt.read_container(file_io.read_bytes(path))
        table = pa.Table.from_pylist(records)
        if projection:
            table = table.select(projection)
        return table


class _AvroRowWriter(FormatWriter):
    def __init__(self, compression: str = "zstd",
                 format_options: Optional[Dict[str, str]] = None):
        compression, _ = split_compression(compression)
        self.codec = {"zstd": "zstandard", "none": "null",
                      "gzip": "deflate"}.get(compression, compression)

    def write(self, file_io, path, table):
        from paimon_tpu.format import avro as avro_fmt
        schema = _arrow_to_avro_schema(table.schema)
        data = avro_fmt.write_container(schema, table.to_pylist(),
                                        codec=self.codec)
        file_io.write_bytes(path, data, overwrite=False)
        return len(data)


def _arrow_to_avro_schema(schema: pa.Schema) -> dict:
    def conv(t: pa.DataType):
        if pa.types.is_boolean(t):
            return "boolean"
        if pa.types.is_integer(t):
            return "long" if t.bit_width > 32 else "int"
        if pa.types.is_float32(t):
            return "float"
        if pa.types.is_floating(t):
            return "double"
        if pa.types.is_string(t) or pa.types.is_large_string(t):
            return "string"
        if pa.types.is_binary(t) or pa.types.is_large_binary(t):
            return "bytes"
        if pa.types.is_timestamp(t):
            return {"type": "long", "logicalType": "timestamp-millis"}
        if pa.types.is_date(t):
            return {"type": "int", "logicalType": "date"}
        if pa.types.is_list(t):
            return {"type": "array", "items": conv(t.value_type)}
        raise ValueError(f"No avro mapping for {t}")

    return {"type": "record", "name": "Row", "fields": [
        {"name": f.name,
         "type": ["null", conv(f.type)] if f.nullable else conv(f.type),
         **({"default": None} if f.nullable else {})}
        for f in schema]}


class FileFormatFactory:
    def __init__(self, identifier: str, reader: FormatReader,
                 writer_cls, extension: Optional[str] = None):
        self.identifier = identifier
        self.reader = reader
        self._writer_cls = writer_cls
        self.extension = extension or identifier

    def create_reader(self) -> FormatReader:
        return self.reader

    def create_writer(self, compression: str = "zstd",
                      format_options: Optional[Dict[str, str]] = None
                      ) -> FormatWriter:
        return self._writer_cls(compression,
                                 format_options=format_options)


class _CsvReader(FormatReader):
    def read(self, file_io, path, projection=None, batch_size=1 << 20):
        from pyarrow import csv as pa_csv
        data = file_io.read_bytes(path)
        table = pa_csv.read_csv(io.BytesIO(data))
        if projection:
            table = table.select(projection)
        return table


class _CsvWriter(FormatWriter):
    def __init__(self, compression: str = "none",
                 format_options: Optional[Dict[str, str]] = None):
        pass

    def write(self, file_io, path, table):
        from pyarrow import csv as pa_csv
        buf = io.BytesIO()
        pa_csv.write_csv(table, buf)
        data = buf.getvalue()
        file_io.write_bytes(path, data, overwrite=False)
        return len(data)


class _JsonReader(FormatReader):
    def read(self, file_io, path, projection=None, batch_size=1 << 20):
        from pyarrow import json as pa_json
        data = file_io.read_bytes(path)
        table = pa_json.read_json(io.BytesIO(data))
        if projection:
            table = table.select(projection)
        return table


class _JsonWriter(FormatWriter):
    def __init__(self, compression: str = "none",
                 format_options: Optional[Dict[str, str]] = None):
        pass

    def write(self, file_io, path, table):
        import json as _json
        for f in table.schema:
            if pa.types.is_binary(f.type) or pa.types.is_large_binary(
                    f.type):
                raise ValueError(
                    f"json format cannot round-trip binary column "
                    f"{f.name!r}; use parquet/orc/avro")

        def default(v):
            # temporals serialize as ISO strings; arrow casts them back
            # on read via the schema-aware evolve path
            return v.isoformat() if hasattr(v, "isoformat") else str(v)

        lines = [_json.dumps(r, default=default)
                 for r in table.to_pylist()]
        data = ("\n".join(lines) + "\n").encode("utf-8")
        file_io.write_bytes(path, data, overwrite=False)
        return len(data)


_FORMATS: Dict[str, FileFormatFactory] = {
    "parquet": FileFormatFactory("parquet", _ParquetReader(),
                                 _ParquetWriter),
    "orc": FileFormatFactory("orc", _OrcReader(), _OrcWriter),
    "avro": FileFormatFactory("avro", _AvroRowReader(), _AvroRowWriter),
    "csv": FileFormatFactory("csv", _CsvReader(), _CsvWriter),
    "json": FileFormatFactory("json", _JsonReader(), _JsonWriter),
}


def get_format(identifier: str) -> FileFormatFactory:
    """reference FileFormat.fromIdentifier (FileFormat.java:76)."""
    ident = identifier.lower()
    if ident == "mosaic" and ident not in _FORMATS:
        # registered lazily to keep module import order simple
        from paimon_tpu.format.mosaic import MOSAIC_FACTORY
        _FORMATS["mosaic"] = MOSAIC_FACTORY
    if ident not in _FORMATS:
        raise ValueError(f"Unknown file format {identifier!r}; "
                         f"available: {sorted(_FORMATS)}")
    return _FORMATS[ident]


def extract_simple_stats(table: pa.Table,
                         columns: Optional[Sequence[str]] = None
                         ) -> Tuple[List[Any], List[Any], List[int]]:
    """Column (min, max, null_count) triples from an Arrow table.

    Role of reference SimpleStatsExtractor/SimpleStatsCollector: stats
    computed at write time and stored in manifests for pruning.
    """
    import pyarrow.compute as pc
    names = list(columns) if columns else table.column_names
    mins, maxs, nulls = [], [], []
    for name in names:
        col = table.column(name)
        nulls.append(col.null_count)
        if col.null_count == len(col) or len(col) == 0:
            mins.append(None)
            maxs.append(None)
            continue
        try:
            mm = pc.min_max(col)
            mins.append(mm["min"].as_py())
            maxs.append(mm["max"].as_py())
        except pa.ArrowNotImplementedError:
            mins.append(None)
            maxs.append(None)
    return mins, maxs, nulls
