"""Blob storage: large binary columns externalized to .blob sidecars.

reference: paimon-format/.../blob/BlobFileFormat.java (length-prefixed
binary elements), data/BlobDescriptor.java (pointer stored in the data
file), blob/ externalization in paimon-core.

Wire shape: the data file stores a struct<offset: int64, length: int64>
per row (null = null blob) pointing into `<data-file>.blob`, which holds
the concatenated raw values. The sidecar rides extra_files so expiry /
orphan cleanup track it with the data file.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np
import pyarrow as pa

from paimon_tpu.types import BlobType

__all__ = ["DESCRIPTOR_TYPE", "externalize_blobs", "resolve_blobs",
           "maybe_resolve_blobs", "blob_column_names",
           "blob_sidecar_name"]


def blob_column_names(schema) -> List[str]:
    """Blob-typed field names of a TableSchema (single source of truth
    for blob detection)."""
    return [f.name for f in schema.fields if isinstance(f.type, BlobType)]

DESCRIPTOR_TYPE = pa.struct([pa.field("offset", pa.int64()),
                             pa.field("length", pa.int64())])


def blob_sidecar_name(data_file_name: str) -> str:
    return data_file_name + ".blob"


def externalize_blobs(file_io, path_factory, partition, bucket,
                      data_file_name: str, chunk: pa.Table,
                      blob_columns: List[str]
                      ) -> Tuple[pa.Table, List[str]]:
    """Replace blob columns with descriptor structs; write one sidecar
    holding all the chunk's blob bytes. -> (chunk', extra_files)."""
    cols = [c for c in blob_columns if c in chunk.column_names]
    if not cols:
        return chunk, []
    payload_parts: List[bytes] = []
    payload_len = 0
    out = chunk
    for name in cols:
        arr = out.column(name).combine_chunks().cast(pa.large_binary())
        # zero-copy: arrow binary arrays already hold a contiguous value
        # buffer + offsets; slice buffers instead of per-row pylists
        buf_offsets = np.frombuffer(arr.buffers()[1], dtype=np.int64,
                                    count=len(arr) + 1, offset=0)
        data_buf = arr.buffers()[2]
        raw = bytes(data_buf) if data_buf is not None else b""
        null_mask = np.asarray(arr.is_null())
        lengths = (buf_offsets[1:] - buf_offsets[:-1]).astype(np.int64)
        starts = buf_offsets[:-1] + 0
        offsets_out = starts + payload_len
        payload_parts.append(raw)
        payload_len += len(raw)
        desc = pa.StructArray.from_arrays(
            [pa.array(offsets_out, pa.int64()),
             pa.array(lengths, pa.int64())],
            fields=list(DESCRIPTOR_TYPE),
            mask=pa.array(null_mask))
        out = out.set_column(out.column_names.index(name), name, desc)
    payload = b"".join(payload_parts)
    if not payload:
        return out, []
    sidecar = blob_sidecar_name(data_file_name)
    file_io.write_bytes(
        path_factory.data_file_path(partition, bucket, sidecar),
        payload, overwrite=False)
    return out, [sidecar]


def resolve_blobs(file_io, path_factory, partition, bucket,
                  meta, table: pa.Table,
                  blob_columns: List[str]) -> pa.Table:
    """Inverse of externalize_blobs: descriptor structs -> binary."""
    cols = [c for c in blob_columns
            if c in table.column_names
            and pa.types.is_struct(table.column(c).type)]
    if not cols:
        return table
    sidecar = next((x for x in meta.extra_files if x.endswith(".blob")),
                   None)
    data = b""
    if sidecar is not None:
        data = file_io.read_bytes(
            path_factory.data_file_path(partition, bucket, sidecar))
    for name in cols:
        arr = table.column(name).combine_chunks()
        offsets = arr.field("offset").to_pylist()
        lengths = arr.field("length").to_pylist()
        values = [None if o is None else data[o:o + ln]
                  for o, ln in zip(offsets, lengths)]
        table = table.set_column(table.column_names.index(name), name,
                                 pa.array(values, pa.binary()))
    return table


def maybe_resolve_blobs(file_io, path_factory, partition, bucket, meta,
                        table: pa.Table, schema, schema_manager=None,
                        wanted=None) -> pa.Table:
    """Schema-aware resolve. Blob columns come from the FILE's schema
    (meta.schema_id) so renames never orphan descriptors; columns outside
    `wanted` (a projection) are dropped instead of resolved — no sidecar
    read when the projection excludes every blob column."""
    file_schema = schema
    if meta.schema_id != schema.id and schema_manager is not None:
        try:
            file_schema = schema_manager.schema(meta.schema_id)
        except Exception:
            file_schema = schema
    blob_cols = [c for c in blob_column_names(file_schema)
                 if c in table.column_names]
    if not blob_cols:
        return table
    if wanted is not None:
        # the projection names columns in the CURRENT schema; map the
        # file's blob columns forward by field id before filtering
        file_id = {f.name: f.id for f in file_schema.fields}
        cur_name = {f.id: f.name for f in schema.fields}

        def current_name(c):
            return cur_name.get(file_id.get(c), c)

        skip = [c for c in blob_cols if current_name(c) not in wanted]
        if skip:
            table = table.drop_columns(skip)
            blob_cols = [c for c in blob_cols if c not in skip]
        if not blob_cols:
            return table
    return resolve_blobs(file_io, path_factory, partition, bucket, meta,
                         table, blob_cols)
