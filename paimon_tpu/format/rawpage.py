"""Raw Parquet page reader: undecoded column chunks -> device decode.

The pyarrow read path decodes pages on the host and hands Arrow arrays
to the merge plane, which re-encodes keys into normalized lanes before
any kernel runs.  This reader moves the per-value work onto the device
(ops/decode.py): the parquet FOOTER (already cached process-wide by
read.cache.footer) locates each column chunk, the chunk's raw bytes
are sliced through ``FileIO.read_ranges`` — riding the block-range
cache, SSD tier, hedging and retry ladders for free — and the only
host work left is page-header/run-header parsing (a few dozen thrift
varints per page) and codec decompression.  Every per-value transform
(RLE/bit-packed level expansion, dictionary index gather, PLAIN
fixed-width reinterpret, null scatter) is a traced JAX op.

Coverage is deliberately the hot-path subset: flat columns
(max_repetition_level == 0), physical INT32/INT64/FLOAT/DOUBLE, v1
data pages, PLAIN and RLE/PLAIN-dictionary value encodings, RLE
definition levels, UNCOMPRESSED/SNAPPY/GZIP/ZSTD codecs.  Anything
else raises ``DeviceDecodeUnsupported`` and the caller falls back to
the pyarrow path (core/read.py gates on ``read.device-decode``);
results are byte-identical to pyarrow by the oracle test suite.
"""

from __future__ import annotations

import io
import struct
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from paimon_tpu.fs import FileIO

__all__ = ["DeviceDecodeUnsupported", "read_parquet_device",
           "device_decode_supported", "parse_page_header",
           "parse_rle_runs"]

# parquet-format enums (format/src/main/thrift/parquet.thrift)
_ENC_PLAIN = 0
_ENC_PLAIN_DICT = 2
_ENC_RLE = 3
_ENC_RLE_DICT = 8
_PAGE_DATA = 0
_PAGE_DICT = 2
_PAGE_DATA_V2 = 3

_PHYS_WIDTH = {"INT32": 4, "INT64": 8, "FLOAT": 4, "DOUBLE": 8}
_CODECS = {"UNCOMPRESSED", "SNAPPY", "GZIP", "ZSTD"}
# footer-declared chunk encodings inside coverage; anything else
# (DELTA_*, BYTE_STREAM_SPLIT, legacy BIT_PACKED levels) pre-falls-back
# from the footer alone, before any data byte is fetched
_ENCODINGS = {"PLAIN", "RLE", "PLAIN_DICTIONARY", "RLE_DICTIONARY"}


class DeviceDecodeUnsupported(Exception):
    """This file/column needs an encoding, codec or shape outside the
    device decode plane's coverage; the caller takes the pyarrow host
    path (never an error surfaced to users)."""


# ---------------------------------------------------------------------------
# thrift compact protocol (page headers only — footers come from the
# cached pyarrow FileMetaData)
# ---------------------------------------------------------------------------


def _varint(buf: bytes, pos: int) -> Tuple[int, int]:
    out = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, pos
        shift += 7


def _zigzag(buf: bytes, pos: int) -> Tuple[int, int]:
    v, pos = _varint(buf, pos)
    return (v >> 1) ^ -(v & 1), pos


def _skip(buf: bytes, pos: int, ftype: int) -> int:
    if ftype in (1, 2):                       # bool encoded in header
        return pos
    if ftype == 3:                            # i8
        return pos + 1
    if ftype in (4, 5, 6):                    # i16/i32/i64 zigzag
        return _zigzag(buf, pos)[1]
    if ftype == 7:                            # double
        return pos + 8
    if ftype == 8:                            # binary
        ln, pos = _varint(buf, pos)
        return pos + ln
    if ftype in (9, 10):                      # list/set
        head = buf[pos]
        pos += 1
        size, etype = head >> 4, head & 0x0F
        if size == 0x0F:
            size, pos = _varint(buf, pos)
        for _ in range(size):
            pos = _skip(buf, pos, etype)
        return pos
    if ftype == 11:                           # map
        size, pos = _varint(buf, pos)
        if size == 0:
            return pos
        kv = buf[pos]
        pos += 1
        for _ in range(size):
            pos = _skip(buf, pos, kv >> 4)
            pos = _skip(buf, pos, kv & 0x0F)
        return pos
    if ftype == 12:                           # struct
        _, pos = _compact_struct(buf, pos, keep=())
        return pos
    raise DeviceDecodeUnsupported(f"thrift compact type {ftype}")


def _compact_struct(buf: bytes, pos: int,
                    keep: Sequence[int],
                    structs: Dict[int, Sequence[int]] = {},
                    ) -> Tuple[Dict[int, object], int]:
    """Walk one compact-protocol struct, returning {field id: value}
    for scalar fields in `keep` and nested structs in `structs`
    (mapping field id -> that struct's keep list); everything else is
    skipped."""
    out: Dict[int, object] = {}
    fid = 0
    while True:
        head = buf[pos]
        pos += 1
        if head == 0:
            return out, pos
        delta = head >> 4
        ftype = head & 0x0F
        if delta:
            fid += delta
        else:
            fid, pos = _zigzag(buf, pos)
        if ftype in (1, 2):
            if fid in keep:
                out[fid] = ftype == 1
            continue
        if fid in structs and ftype == 12:
            out[fid], pos = _compact_struct(buf, pos,
                                            keep=structs[fid])
            continue
        if fid in keep and ftype in (4, 5, 6):
            v, pos = _zigzag(buf, pos)
            out[fid] = v
            continue
        pos = _skip(buf, pos, ftype)


def parse_page_header(buf: bytes, pos: int) -> Tuple[Dict, int]:
    """Parse one thrift-compact PageHeader at `pos`; returns (header
    dict, payload start).  Keys: type, uncompressed/compressed sizes,
    plus the nested data/dictionary page headers that matter here."""
    fields, pos = _compact_struct(
        buf, pos, keep=(1, 2, 3),
        structs={5: (1, 2, 3, 4),       # DataPageHeader
                 7: (1, 2, 3),          # DictionaryPageHeader
                 8: (1, 2, 3, 4, 5, 6, 7)})   # DataPageHeaderV2
    hdr = {
        "type": fields.get(1),
        "uncompressed_size": fields.get(2),
        "compressed_size": fields.get(3),
        "data": fields.get(5),
        "dict": fields.get(7),
        "data_v2": fields.get(8),
    }
    return hdr, pos


# ---------------------------------------------------------------------------
# RLE/bit-packed hybrid run headers (host side: a handful of varints)
# ---------------------------------------------------------------------------


def parse_rle_runs(buf: bytes, bit_width: int, count: int,
                   ) -> Tuple[np.ndarray, np.ndarray, np.ndarray,
                              np.ndarray]:
    """Parse the run HEADERS of an RLE/bit-packed hybrid stream over
    `buf` (values start at offset 0) into per-run descriptor arrays for
    ops/decode.expand_rle_hybrid: (is_packed u32[R], value u32[R],
    cum-counts i32[R] inclusive, bit-start i32[R])."""
    is_packed: List[int] = []
    value: List[int] = []
    cum: List[int] = []
    bit_start: List[int] = []
    pos = 0
    total = 0
    vbytes = (bit_width + 7) // 8
    while total < count:
        if pos >= len(buf):
            raise DeviceDecodeUnsupported("truncated RLE stream")
        header, pos = _varint(buf, pos)
        if header & 1:
            groups = header >> 1
            n = groups * 8
            is_packed.append(1)
            value.append(0)
            bit_start.append(pos * 8)
            pos += groups * bit_width
        else:
            n = header >> 1
            v = int.from_bytes(buf[pos:pos + vbytes], "little") \
                if vbytes else 0
            pos += vbytes
            is_packed.append(0)
            value.append(v)
            bit_start.append(0)
        total += n
        cum.append(min(total, count))
    if not cum:
        raise DeviceDecodeUnsupported("empty RLE stream")
    return (np.asarray(is_packed, np.uint32),
            np.asarray(value, np.uint32),
            np.asarray(cum, np.int32),
            np.asarray(bit_start, np.int32))


# ---------------------------------------------------------------------------
# jitted per-page decode entries (padded shapes -> stable compile cache)
# ---------------------------------------------------------------------------


def _pad_bytes_u32(data: bytes) -> np.ndarray:
    """Page bytes -> little-endian u32 word array with one word of
    slack (unpack_bits reads a two-word window) padded to a pow2."""
    from paimon_tpu.ops.decode import pad_pow2
    n_words = len(data) // 4 + 2
    padded = pad_pow2(n_words, floor=256)
    buf = np.zeros(padded * 4, dtype=np.uint8)
    buf[:len(data)] = np.frombuffer(data, np.uint8)
    return buf.view(np.uint32)


def _pad_u8(data: bytes, floor: int = 1024) -> np.ndarray:
    from paimon_tpu.ops.decode import pad_pow2
    buf = np.zeros(pad_pow2(len(data), floor=floor), dtype=np.uint8)
    buf[:len(data)] = np.frombuffer(data, np.uint8)
    return buf


def _pad_runs(runs: Tuple[np.ndarray, ...]) -> Tuple[np.ndarray, ...]:
    """Pad run-descriptor arrays to a pow2 length; padding runs repeat
    the last cumulative count, so searchsorted never selects them."""
    from paimon_tpu.ops.decode import pad_pow2
    is_packed, value, cum, bit_start = runs
    r = len(cum)
    rp = pad_pow2(r, floor=8)
    pad = rp - r

    def ext(a, fill):
        return np.concatenate([a, np.full(pad, fill, a.dtype)]) \
            if pad else a
    return (ext(is_packed, 0), ext(value, 0), ext(cum, cum[-1]),
            ext(bit_start, 0))


def _decode_rle_values(buf: bytes, bit_width: int,
                       count: int) -> np.ndarray:
    """Full RLE/bit-packed hybrid decode: host run headers + device
    expansion.  Returns uint32[count]."""
    import jax.numpy as jnp

    from paimon_tpu.ops.decode import expand_rle_hybrid, pad_pow2
    runs = _pad_runs(parse_rle_runs(buf, bit_width, count))
    words = _pad_bytes_u32(buf)
    padded_count = pad_pow2(count)
    out = expand_rle_hybrid(jnp.asarray(words),
                            jnp.asarray(runs[0]), jnp.asarray(runs[1]),
                            jnp.asarray(runs[2]), jnp.asarray(runs[3]),
                            bit_width, padded_count)
    return np.asarray(out)[:count]


def _decode_plain_values(data: bytes, phys: str,
                         count: int) -> np.ndarray:
    """PLAIN fixed-width page payload -> device reinterpret ->
    numpy raw-bits array (u32 or u64)."""
    import jax.numpy as jnp

    from paimon_tpu.ops.decode import (pad_pow2, plain_to_u32,
                                       plain_to_u64)
    width = _PHYS_WIDTH[phys]
    if len(data) < width * count:
        raise DeviceDecodeUnsupported("PLAIN page shorter than values")
    padded_count = pad_pow2(count)
    buf = _pad_u8(data, floor=padded_count * width)
    if len(buf) < padded_count * width:
        buf = np.concatenate(
            [buf, np.zeros(padded_count * width - len(buf), np.uint8)])
    fn = plain_to_u64 if width == 8 else plain_to_u32
    out = fn(jnp.asarray(buf), padded_count)
    return np.asarray(out)[:count]


# ---------------------------------------------------------------------------
# footer access (rides the process footer cache)
# ---------------------------------------------------------------------------


class _TailFile(io.RawIOBase):
    """Seekable file view for pq.read_metadata backed by the already-
    fetched tail bytes, falling back to ranged reads for anything
    outside the tail (wide schemas whose footer exceeds the probe)."""

    def __init__(self, file_io: FileIO, path: str, size: int,
                 tail: bytes):
        self._io = file_io
        self._path = path
        self._size = size
        self._tail = tail
        self._pos = 0

    def seekable(self) -> bool:
        return True

    def readable(self) -> bool:
        return True

    def seek(self, offset: int, whence: int = 0) -> int:
        if whence == 0:
            self._pos = offset
        elif whence == 1:
            self._pos += offset
        else:
            self._pos = self._size + offset
        return self._pos

    def tell(self) -> int:
        return self._pos

    def read(self, n: int = -1) -> bytes:
        if n < 0:
            n = self._size - self._pos
        start = self._pos
        tail_start = self._size - len(self._tail)
        if start >= tail_start:
            off = start - tail_start
            out = self._tail[off:off + n]
        else:
            out = self._io.read_range(self._path, start, n)
        self._pos = start + len(out)
        return out


def _footer_metadata(file_io: FileIO, path: str, options=None):
    """Parsed parquet FileMetaData for `path`, via the process footer
    cache (fs/caching.py) when the table allows it; a miss reads only
    the footer bytes through ranged reads, never the whole file."""
    from paimon_tpu.fs.caching import footer_cache_scope, \
        global_footer_cache
    with footer_cache_scope(options):
        cache = global_footer_cache()
        md = cache.get(path)
        if md is not None:
            return md
        size = file_io.get_file_size(path)
        probe = min(size, 1 << 16)
        tail = file_io.read_range(path, size - probe, probe)
        if len(tail) < 8 or tail[-4:] != b"PAR1":
            raise DeviceDecodeUnsupported(f"not a parquet file: {path}")
        footer_len = struct.unpack("<I", tail[-8:-4])[0]
        if footer_len + 8 > probe:
            tail = file_io.read_range(path, size - footer_len - 8,
                                      footer_len + 8)
        md = pq.read_metadata(_TailFile(file_io, path, size, tail))
        cache.put(path, md)
        return md


# ---------------------------------------------------------------------------
# column-chunk decode
# ---------------------------------------------------------------------------


def _decompress(data: bytes, codec: str, uncompressed: int) -> bytes:
    if codec == "UNCOMPRESSED":
        return data
    return pa.Codec(codec.lower()).decompress(
        data, decompressed_size=uncompressed).to_pybytes()


def _decode_chunk(data: bytes, col_meta, max_def: int,
                  ) -> Tuple[np.ndarray, Optional[np.ndarray]]:
    """One column chunk's pages -> (raw-bits values with zeros at null
    slots, present mask or None).  Dict pages decode PLAIN on device;
    data pages expand levels + indices on device."""
    import jax.numpy as jnp

    from paimon_tpu.ops.decode import dict_gather, expand_nulls, \
        pad_pow2
    phys = col_meta.physical_type
    codec = col_meta.compression
    total = col_meta.num_values
    pos = 0
    dict_vals = None
    out_parts: List[np.ndarray] = []
    mask_parts: List[np.ndarray] = []
    seen = 0
    while seen < total:
        if pos >= len(data):
            raise DeviceDecodeUnsupported("column chunk truncated")
        hdr, body = parse_page_header(data, pos)
        comp = hdr["compressed_size"]
        payload = data[body:body + comp]
        pos = body + comp
        ptype = hdr["type"]
        if ptype == _PAGE_DICT:
            page = _decompress(payload, codec,
                               hdr["uncompressed_size"])
            dhdr = hdr["dict"] or {}
            if dhdr.get(2, _ENC_PLAIN) not in (_ENC_PLAIN,
                                               _ENC_PLAIN_DICT):
                raise DeviceDecodeUnsupported("non-PLAIN dictionary")
            dict_vals = _decode_plain_values(page, phys, dhdr.get(1, 0))
            continue
        if ptype == _PAGE_DATA_V2:
            raise DeviceDecodeUnsupported("v2 data page")
        if ptype != _PAGE_DATA:
            continue                          # index pages etc.
        dh = hdr["data"]
        if dh is None:
            raise DeviceDecodeUnsupported("data page without header")
        nvals = dh.get(1, 0)
        enc = dh.get(2, _ENC_PLAIN)
        page = _decompress(payload, codec, hdr["uncompressed_size"])
        off = 0
        present = None
        n_present = nvals
        if max_def > 0:
            if dh.get(3, _ENC_RLE) != _ENC_RLE:
                raise DeviceDecodeUnsupported("non-RLE def levels")
            dlen = struct.unpack("<I", page[off:off + 4])[0]
            off += 4
            bw = max_def.bit_length()
            levels = _decode_rle_values(page[off:off + dlen], bw,
                                        nvals)
            off += dlen
            present = levels == max_def
            n_present = int(present.sum())
        if enc == _ENC_PLAIN:
            vals = _decode_plain_values(page[off:], phys, n_present)
        elif enc in (_ENC_PLAIN_DICT, _ENC_RLE_DICT):
            if dict_vals is None:
                raise DeviceDecodeUnsupported("dict page missing")
            if n_present:
                bw = page[off]
                idx = _decode_rle_values(page[off + 1:], bw, n_present)
            else:
                idx = np.zeros(0, np.uint32)
            vals = np.asarray(dict_gather(
                jnp.asarray(dict_vals), jnp.asarray(idx))) \
                if n_present else dict_vals[:0]
        else:
            raise DeviceDecodeUnsupported(f"value encoding {enc}")
        if present is not None and n_present != nvals:
            padded = pad_pow2(nvals)
            vp = np.zeros(padded, vals.dtype)
            vp[:n_present] = vals
            pp = np.zeros(padded, bool)
            pp[:nvals] = present
            full, _ = expand_nulls(jnp.asarray(vp), jnp.asarray(pp))
            vals = np.asarray(full)[:nvals]
        out_parts.append(vals)
        mask_parts.append(present if present is not None
                          else np.ones(nvals, bool))
        seen += nvals
    if not out_parts:
        width = _PHYS_WIDTH[phys]
        empty = np.zeros(0, np.uint64 if width == 8 else np.uint32)
        return empty, np.zeros(0, bool)
    values = np.concatenate(out_parts) if len(out_parts) > 1 \
        else out_parts[0]
    mask = np.concatenate(mask_parts) if len(mask_parts) > 1 \
        else mask_parts[0]
    return values, (None if mask.all() else mask)


def _arrow_array(values: np.ndarray, mask: Optional[np.ndarray],
                 field_type: pa.DataType) -> pa.Array:
    """Raw-bits values + presence mask -> Arrow array of the footer
    schema's type, zero-copy via from_buffers."""
    n = len(values)
    phys_bits = values.dtype.itemsize * 8
    if field_type.bit_width != phys_bits:
        if pa.types.is_integer(field_type) \
                and field_type.bit_width < phys_bits:
            # INT(8/16) logical types store sign-extended in INT32:
            # truncating cast recovers the narrow value exactly
            signed = values.view(np.int32 if phys_bits == 32
                                 else np.int64)
            values = signed.astype(field_type.to_pandas_dtype())
        else:
            raise DeviceDecodeUnsupported(
                f"arrow {field_type} vs physical width {phys_bits}")
    validity = None
    null_count = 0
    if mask is not None:
        null_count = int(n - mask.sum())
        validity = pa.py_buffer(
            np.packbits(mask, bitorder="little").tobytes())
    return pa.Array.from_buffers(
        field_type, n,
        [validity, pa.py_buffer(np.ascontiguousarray(values))],
        null_count=null_count)


def device_decode_supported(md, columns: Sequence[str]) -> bool:
    """Cheap pre-check (footer only) that every requested column is
    inside the decode plane's coverage."""
    try:
        _check_supported(md, columns)
        return True
    except DeviceDecodeUnsupported:
        return False


def _check_supported(md, columns: Sequence[str]) -> Dict[str, int]:
    schema = md.schema
    by_name = {schema.column(i).name: i
               for i in range(len(schema.names))}
    out = {}
    for name in columns:
        ci = by_name.get(name)
        if ci is None:
            raise DeviceDecodeUnsupported(f"no flat column {name!r}")
        col_schema = schema.column(ci)
        if col_schema.max_repetition_level != 0:
            raise DeviceDecodeUnsupported(f"nested column {name!r}")
        if col_schema.physical_type not in _PHYS_WIDTH:
            raise DeviceDecodeUnsupported(
                f"physical type {col_schema.physical_type}")
        for rg in range(md.num_row_groups):
            cm = md.row_group(rg).column(ci)
            if cm.compression not in _CODECS:
                raise DeviceDecodeUnsupported(
                    f"codec {cm.compression}")
            unknown = set(cm.encodings) - _ENCODINGS
            if unknown:
                raise DeviceDecodeUnsupported(
                    f"encodings {sorted(unknown)} in {name!r}")
        out[name] = ci
    return out


# errors that route a file back to the pyarrow host path: the typed
# coverage signal, plus anything the hand-rolled thrift/page parsers
# raise on byte shapes they never anticipated (truncated varints,
# absent header fields) — the host reader is the arbiter of whether
# such a file is readable or genuinely corrupt
_FALLBACK_ERRORS = (DeviceDecodeUnsupported, IndexError, KeyError,
                    TypeError, ValueError, struct.error)


def maybe_read_device(file_io: FileIO, path: str,
                      projection: Optional[List[str]] = None,
                      options=None) -> Optional[pa.Table]:
    """read_parquet_device, or None when the file needs the pyarrow
    host path (fallback counted in the scan metric group)."""
    try:
        return read_parquet_device(file_io, path, projection, options)
    except _FALLBACK_ERRORS:
        from paimon_tpu.metrics import SCAN_DEVICE_DECODE_FALLBACKS, \
            global_registry
        global_registry().group("scan").counter(
            SCAN_DEVICE_DECODE_FALLBACKS).inc()
        return None


def read_parquet_device(file_io: FileIO, path: str,
                        projection: Optional[List[str]] = None,
                        options=None,
                        row_groups: Optional[Sequence[int]] = None
                        ) -> pa.Table:
    """Read a parquet file through the device decode plane; byte-
    identical to the pyarrow reader for covered files, raises
    DeviceDecodeUnsupported otherwise (caller falls back).
    `row_groups` restricts the read (the streamed-compaction batch
    iterator reads one group at a time to keep its memory bound)."""
    md = _footer_metadata(file_io, path, options)
    arrow_schema = md.schema.to_arrow_schema()
    names = list(projection) if projection else list(arrow_schema.names)
    col_idx = _check_supported(md, names)
    groups = list(row_groups) if row_groups is not None \
        else list(range(md.num_row_groups))

    # one ranged read per (row group, column) chunk, all batched into a
    # single read_ranges call (block-range cache / SSD tier / hedging)
    ranges: List[Tuple[int, int]] = []
    keys: List[Tuple[int, str]] = []
    for rg in groups:
        for name in names:
            cm = md.row_group(rg).column(col_idx[name])
            start = cm.data_page_offset
            if cm.dictionary_page_offset is not None:
                start = min(start, cm.dictionary_page_offset)
            ranges.append((start, cm.total_compressed_size))
            keys.append((rg, name))
    blobs = file_io.read_ranges(path, ranges) if ranges else []
    chunks = dict(zip(keys, blobs))

    from paimon_tpu.metrics import SCAN_DEVICE_DECODE_FILES, \
        global_registry
    arrays: Dict[str, List[pa.Array]] = {n: [] for n in names}
    for rg in groups:
        for name in names:
            cm = md.row_group(rg).column(col_idx[name])
            schema_col = md.schema.column(col_idx[name])
            values, mask = _decode_chunk(
                chunks[(rg, name)], cm,
                schema_col.max_definition_level)
            field_type = arrow_schema.field(name).type
            arrays[name].append(_arrow_array(values, mask, field_type))
    cols = {n: pa.chunked_array(arrays[n],
                                type=arrow_schema.field(n).type)
            for n in names}
    out = pa.table(
        [cols[n] for n in names],
        schema=pa.schema([arrow_schema.field(n) for n in names]))
    if row_groups is None:                  # partial reads count once,
        global_registry().group("scan").counter(   # in the iterator
            SCAN_DEVICE_DECODE_FILES).inc()
    return out


def iter_batches_device(file_io: FileIO, path: str,
                        batch_rows: int,
                        options=None):
    """Streamed device-decode: yields the file as bounded Arrow tables,
    decoding and FETCHING one row group at a time — the streamed
    compaction rewriters' memory bound (~runs x chunk rows) holds with
    device decode exactly as it does on the pyarrow iter_batches path.
    Raises DeviceDecodeUnsupported before yielding anything when the
    file is outside coverage (checked from the footer alone)."""
    md = _footer_metadata(file_io, path, options)
    names = list(md.schema.to_arrow_schema().names)
    _check_supported(md, names)            # EAGER: before any yield
    return _iter_batches_device(file_io, path, batch_rows, options, md)


def _iter_batches_device(file_io, path, batch_rows, options, md):
    from paimon_tpu.metrics import SCAN_DEVICE_DECODE_FALLBACKS, \
        SCAN_DEVICE_DECODE_FILES, global_registry
    global_registry().group("scan").counter(
        SCAN_DEVICE_DECODE_FILES).inc()
    for rg in range(md.num_row_groups):
        try:
            t = read_parquet_device(file_io, path, options=options,
                                    row_groups=[rg])
        except _FALLBACK_ERRORS:
            # a page shape the footer cannot reveal (v2 data pages,
            # odd in-page encodings): the REMAINING row groups decode
            # through pyarrow — earlier groups already yielded the
            # identical rows, so the stream stays seamless
            global_registry().group("scan").counter(
                SCAN_DEVICE_DECODE_FALLBACKS).inc()
            data = file_io.read_bytes(path)
            pf = pq.ParquetFile(io.BytesIO(data), metadata=md)
            for rb in pf.iter_batches(
                    batch_size=batch_rows,
                    row_groups=list(range(rg, md.num_row_groups))):
                yield pa.Table.from_batches([rb])
            return
        for start in range(0, t.num_rows, batch_rows):
            yield t.slice(start, batch_rows)
