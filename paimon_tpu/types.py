"""Data type system.

Mirrors the reference's ``DataType`` hierarchy
(paimon-common/.../types/DataType.java and paimon-api/.../types, 35 files)
with the same JSON serialization used in ``schema/schema-N`` files: atomic
types serialize to SQL-ish strings (``"INT NOT NULL"``, ``"VARCHAR(10)"``),
complex types to JSON objects (``{"type": "ARRAY", "element": ...}``).

Also owns the Arrow <-> paimon type mapping, which the reference keeps in
paimon-arrow (ArrowUtils); here Arrow is the native in-memory format so the
mapping lives with the types.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional

import pyarrow as pa

__all__ = [
    "DataType", "DataField", "RowType", "DataTypeRoot",
    "TinyIntType", "SmallIntType", "IntType", "BigIntType",
    "FloatType", "DoubleType", "BooleanType", "CharType", "VarCharType",
    "BinaryType", "VarBinaryType", "DecimalType", "DateType", "TimeType",
    "TimestampType", "LocalZonedTimestampType", "ArrayType", "MapType",
    "MultisetType", "RowKind", "BlobType", "VariantType", "VectorType",
    "parse_data_type", "data_type_from_arrow", "data_type_to_arrow",
    "SpecialFields",
]

# Field ids >= this are reserved for system fields
# (reference paimon-api/.../table/SpecialFields.java:76-93).
SYSTEM_FIELD_ID_START = 2147483647 // 2


class RowKind:
    """Row change kind (+I/-U/+U/-D), reference types/RowKind.java."""

    INSERT = 0          # +I
    UPDATE_BEFORE = 1   # -U
    UPDATE_AFTER = 2    # +U
    DELETE = 3          # -D

    _SHORT = {0: "+I", 1: "-U", 2: "+U", 3: "-D"}
    _FROM_SHORT = {v: k for k, v in _SHORT.items()}

    @staticmethod
    def short_string(kind: int) -> str:
        return RowKind._SHORT[kind]

    @staticmethod
    def from_short_string(s: str) -> int:
        return RowKind._FROM_SHORT[s]

    @staticmethod
    def is_add(kind: int) -> bool:
        return kind in (RowKind.INSERT, RowKind.UPDATE_AFTER)

    @staticmethod
    def is_retract(kind: int) -> bool:
        return kind in (RowKind.UPDATE_BEFORE, RowKind.DELETE)


class DataTypeRoot:
    BOOLEAN = "BOOLEAN"
    TINYINT = "TINYINT"
    SMALLINT = "SMALLINT"
    INTEGER = "INT"
    BIGINT = "BIGINT"
    FLOAT = "FLOAT"
    DOUBLE = "DOUBLE"
    CHAR = "CHAR"
    VARCHAR = "VARCHAR"
    BINARY = "BINARY"
    VARBINARY = "VARBINARY"
    DECIMAL = "DECIMAL"
    DATE = "DATE"
    TIME = "TIME"
    TIMESTAMP = "TIMESTAMP"
    TIMESTAMP_LTZ = "TIMESTAMP WITH LOCAL TIME ZONE"
    ARRAY = "ARRAY"
    MAP = "MAP"
    MULTISET = "MULTISET"
    ROW = "ROW"
    BLOB = "BLOB"
    VARIANT = "VARIANT"
    VECTOR = "VECTOR"


class DataType:
    """Base of all data types. Immutable."""

    root: str = ""

    def __init__(self, nullable: bool = True):
        self.nullable = nullable

    # -- serde ---------------------------------------------------------------

    def _name(self) -> str:
        raise NotImplementedError

    def __str__(self) -> str:
        return self._name() + ("" if self.nullable else " NOT NULL")

    def __repr__(self) -> str:
        return str(self)

    def to_json(self):
        """Atomic types serialize to strings; complex override to dicts."""
        return str(self)

    def copy(self, nullable: bool) -> "DataType":
        import copy as _copy
        c = _copy.copy(self)
        c.nullable = nullable
        return c

    def as_nullable(self) -> "DataType":
        return self if self.nullable else self.copy(True)

    def __eq__(self, other):
        return (type(self) is type(other)
                and self.__dict__ == other.__dict__)

    def __hash__(self):
        return hash((type(self).__name__, str(self)))

    # -- properties ----------------------------------------------------------

    def is_numeric(self) -> bool:
        return self.root in (
            DataTypeRoot.TINYINT, DataTypeRoot.SMALLINT, DataTypeRoot.INTEGER,
            DataTypeRoot.BIGINT, DataTypeRoot.FLOAT, DataTypeRoot.DOUBLE,
            DataTypeRoot.DECIMAL)


class _AtomicType(DataType):
    def _name(self) -> str:
        return self.root


class BooleanType(_AtomicType):
    root = DataTypeRoot.BOOLEAN


class TinyIntType(_AtomicType):
    root = DataTypeRoot.TINYINT


class SmallIntType(_AtomicType):
    root = DataTypeRoot.SMALLINT


class IntType(_AtomicType):
    root = DataTypeRoot.INTEGER


class BigIntType(_AtomicType):
    root = DataTypeRoot.BIGINT


class FloatType(_AtomicType):
    root = DataTypeRoot.FLOAT


class DoubleType(_AtomicType):
    root = DataTypeRoot.DOUBLE


class DateType(_AtomicType):
    root = DataTypeRoot.DATE


class VariantType(_AtomicType):
    root = DataTypeRoot.VARIANT


class CharType(DataType):
    root = DataTypeRoot.CHAR

    def __init__(self, length: int = 1, nullable: bool = True):
        super().__init__(nullable)
        self.length = length

    def _name(self):
        return f"CHAR({self.length})"


class VarCharType(DataType):
    root = DataTypeRoot.VARCHAR
    MAX_LENGTH = 2147483647

    def __init__(self, length: int = MAX_LENGTH, nullable: bool = True):
        super().__init__(nullable)
        self.length = length

    def _name(self):
        return f"VARCHAR({self.length})"

    @staticmethod
    def string_type(nullable: bool = True) -> "VarCharType":
        return VarCharType(VarCharType.MAX_LENGTH, nullable)


class BinaryType(DataType):
    root = DataTypeRoot.BINARY

    def __init__(self, length: int = 1, nullable: bool = True):
        super().__init__(nullable)
        self.length = length

    def _name(self):
        return f"BINARY({self.length})"


class VarBinaryType(DataType):
    root = DataTypeRoot.VARBINARY
    MAX_LENGTH = 2147483647

    def __init__(self, length: int = MAX_LENGTH, nullable: bool = True):
        super().__init__(nullable)
        self.length = length

    def _name(self):
        return f"VARBINARY({self.length})"

    @staticmethod
    def bytes_type(nullable: bool = True) -> "VarBinaryType":
        return VarBinaryType(VarBinaryType.MAX_LENGTH, nullable)


class BlobType(DataType):
    """Large binary externalized to .blob files (reference BlobType)."""
    root = DataTypeRoot.BLOB

    def _name(self):
        return "BLOB"


class DecimalType(DataType):
    root = DataTypeRoot.DECIMAL

    def __init__(self, precision: int = 10, scale: int = 0,
                 nullable: bool = True):
        super().__init__(nullable)
        self.precision = precision
        self.scale = scale

    def _name(self):
        return f"DECIMAL({self.precision}, {self.scale})"


class TimeType(DataType):
    root = DataTypeRoot.TIME

    def __init__(self, precision: int = 0, nullable: bool = True):
        super().__init__(nullable)
        self.precision = precision

    def _name(self):
        return f"TIME({self.precision})"


class TimestampType(DataType):
    root = DataTypeRoot.TIMESTAMP

    def __init__(self, precision: int = 6, nullable: bool = True):
        super().__init__(nullable)
        self.precision = precision

    def _name(self):
        return f"TIMESTAMP({self.precision})"


class LocalZonedTimestampType(DataType):
    root = DataTypeRoot.TIMESTAMP_LTZ

    def __init__(self, precision: int = 6, nullable: bool = True):
        super().__init__(nullable)
        self.precision = precision

    def _name(self):
        return f"TIMESTAMP({self.precision}) WITH LOCAL TIME ZONE"


class ArrayType(DataType):
    root = DataTypeRoot.ARRAY

    def __init__(self, element: DataType, nullable: bool = True):
        super().__init__(nullable)
        self.element = element

    def _name(self):
        return f"ARRAY<{self.element}>"

    def to_json(self):
        d = {"type": "ARRAY" + ("" if self.nullable else " NOT NULL"),
             "element": self.element.to_json()}
        return d


class VectorType(DataType):
    """Fixed-length numeric vector (reference VectorType, for ANN search)."""
    root = DataTypeRoot.VECTOR

    def __init__(self, element: DataType, length: int, nullable: bool = True):
        super().__init__(nullable)
        self.element = element
        self.length = length

    def _name(self):
        return f"VECTOR<{self.element}, {self.length}>"

    def to_json(self):
        return {"type": "VECTOR" + ("" if self.nullable else " NOT NULL"),
                "element": self.element.to_json(), "length": self.length}


class MultisetType(DataType):
    root = DataTypeRoot.MULTISET

    def __init__(self, element: DataType, nullable: bool = True):
        super().__init__(nullable)
        self.element = element

    def _name(self):
        return f"MULTISET<{self.element}>"

    def to_json(self):
        return {"type": "MULTISET" + ("" if self.nullable else " NOT NULL"),
                "element": self.element.to_json()}


class MapType(DataType):
    root = DataTypeRoot.MAP

    def __init__(self, key: DataType, value: DataType, nullable: bool = True):
        super().__init__(nullable)
        self.key = key
        self.value = value

    def _name(self):
        return f"MAP<{self.key}, {self.value}>"

    def to_json(self):
        return {"type": "MAP" + ("" if self.nullable else " NOT NULL"),
                "key": self.key.to_json(), "value": self.value.to_json()}


class DataField:
    """A named, id'd field of a RowType (reference types/DataField.java)."""

    def __init__(self, id: int, name: str, type: DataType,
                 description: Optional[str] = None,
                 default_value: Optional[str] = None):
        self.id = id
        self.name = name
        self.type = type
        self.description = description
        self.default_value = default_value

    def to_json(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"id": self.id, "name": self.name,
                             "type": self.type.to_json()}
        if self.description is not None:
            d["description"] = self.description
        if self.default_value is not None:
            d["defaultValue"] = self.default_value
        return d

    @staticmethod
    def from_json(d: Dict[str, Any]) -> "DataField":
        return DataField(d["id"], d["name"], parse_data_type(d["type"]),
                         d.get("description"), d.get("defaultValue"))

    def __eq__(self, other):
        return (isinstance(other, DataField) and self.id == other.id
                and self.name == other.name and self.type == other.type
                and self.description == other.description
                and self.default_value == other.default_value)

    def __hash__(self):
        return hash((self.id, self.name, str(self.type)))

    def __repr__(self):
        return f"DataField({self.id}, {self.name!r}, {self.type})"


class RowType(DataType):
    root = DataTypeRoot.ROW

    def __init__(self, fields: List[DataField], nullable: bool = True):
        super().__init__(nullable)
        self.fields = list(fields)

    # -- construction helpers ------------------------------------------------

    @staticmethod
    def of(*args, nullable: bool = True) -> "RowType":
        """RowType.of(name, type, name, type, ...) or RowType.of(fields)."""
        if len(args) == 1 and isinstance(args[0], (list, tuple)):
            return RowType(list(args[0]), nullable)
        fields = []
        for i in range(0, len(args), 2):
            fields.append(DataField(i // 2, args[i], args[i + 1]))
        return RowType(fields, nullable)

    @staticmethod
    def builder() -> "RowTypeBuilder":
        return RowTypeBuilder()

    # -- access --------------------------------------------------------------

    @property
    def field_names(self) -> List[str]:
        return [f.name for f in self.fields]

    @property
    def field_types(self) -> List[DataType]:
        return [f.type for f in self.fields]

    def field_count(self) -> int:
        return len(self.fields)

    def get_field(self, name: str) -> DataField:
        for f in self.fields:
            if f.name == name:
                return f
        raise KeyError(name)

    def get_field_by_id(self, fid: int) -> DataField:
        for f in self.fields:
            if f.id == fid:
                return f
        raise KeyError(fid)

    def get_field_index(self, name: str) -> int:
        for i, f in enumerate(self.fields):
            if f.name == name:
                return i
        return -1

    def project(self, names: List[str]) -> "RowType":
        return RowType([self.get_field(n) for n in names], self.nullable)

    def highest_field_id(self) -> int:
        return _highest_field_id(self)

    # -- serde ---------------------------------------------------------------

    def _name(self):
        inner = ", ".join(f"`{f.name}` {f.type}" for f in self.fields)
        return f"ROW<{inner}>"

    def to_json(self):
        return {"type": "ROW" + ("" if self.nullable else " NOT NULL"),
                "fields": [f.to_json() for f in self.fields]}

    def __eq__(self, other):
        return (isinstance(other, RowType) and self.nullable == other.nullable
                and self.fields == other.fields)

    def __hash__(self):
        return hash(tuple(self.fields))


class RowTypeBuilder:
    def __init__(self):
        self._fields: List[DataField] = []
        self._next_id = 0

    def field(self, name: str, type: DataType,
              description: Optional[str] = None) -> "RowTypeBuilder":
        self._fields.append(DataField(self._next_id, name, type, description))
        self._next_id += 1
        return self

    def build(self) -> RowType:
        return RowType(self._fields)


def _highest_field_id(row: RowType) -> int:
    highest = -1

    def visit(t: DataType):
        nonlocal highest
        if isinstance(t, RowType):
            for f in t.fields:
                if f.id < SYSTEM_FIELD_ID_START:
                    highest = max(highest, f.id)
                visit(f.type)
        elif isinstance(t, (ArrayType, MultisetType, VectorType)):
            visit(t.element)
        elif isinstance(t, MapType):
            visit(t.key)
            visit(t.value)

    visit(row)
    return highest


# ---------------------------------------------------------------------------
# Parsing (reference types/DataTypeJsonParser.java)
# ---------------------------------------------------------------------------

_ATOMIC_RE = re.compile(
    r"^\s*([A-Z ]+?)\s*(?:\(\s*(\d+)\s*(?:,\s*(\d+)\s*)?\))?"
    r"(\s+WITH LOCAL TIME ZONE)?(\s+NOT NULL)?\s*$")

_SIMPLE_TYPES = {
    "BOOLEAN": BooleanType, "TINYINT": TinyIntType, "SMALLINT": SmallIntType,
    "INT": IntType, "INTEGER": IntType, "BIGINT": BigIntType,
    "FLOAT": FloatType, "DOUBLE": DoubleType, "DATE": DateType,
    "BLOB": BlobType, "VARIANT": VariantType,
    "STRING": lambda nullable=True: VarCharType(VarCharType.MAX_LENGTH,
                                                nullable),
    "BYTES": lambda nullable=True: VarBinaryType(VarBinaryType.MAX_LENGTH,
                                                 nullable),
}


def parse_data_type(j) -> DataType:
    """Parse JSON (string or dict) into a DataType."""
    if isinstance(j, str) and ("<" in j or j.lstrip().upper().startswith("ROW(")):
        return parse_type_string(j)
    if isinstance(j, dict):
        type_str = j["type"]
        nullable = not type_str.endswith(" NOT NULL")
        root = type_str[:-len(" NOT NULL")] if not nullable else type_str
        root = root.strip()
        if root == "ARRAY":
            return ArrayType(parse_data_type(j["element"]), nullable)
        if root == "MULTISET":
            return MultisetType(parse_data_type(j["element"]), nullable)
        if root == "MAP":
            return MapType(parse_data_type(j["key"]),
                           parse_data_type(j["value"]), nullable)
        if root == "ROW":
            return RowType([DataField.from_json(f) for f in j["fields"]],
                           nullable)
        if root == "VECTOR":
            return VectorType(parse_data_type(j["element"]), j["length"],
                              nullable)
        raise ValueError(f"Unknown complex type: {type_str}")
    return _parse_atomic(j)


def parse_type_string(s: str) -> DataType:
    """Parse the SQL string form of a (possibly nested) data type.

    Accepts `ARRAY<T>`, `MULTISET<T>`, `MAP<K, V>`, `ROW<name T, ...>`
    (also `ROW(name T, ...)`), `VECTOR<T, n>`, and every atomic form
    `_parse_atomic` accepts, with `NOT NULL` at any nesting level.
    Mirrors reference types/DataTypeJsonParser.java's string grammar.
    """
    t, pos = _parse_type_str(s, 0)
    if s[pos:].strip():
        raise ValueError(f"Trailing input in data type: {s!r}")
    return t


def _skip_ws(s: str, i: int) -> int:
    while i < len(s) and s[i].isspace():
        i += 1
    return i


_TYPE_WORD_RE = re.compile(r"[A-Za-z_][A-Za-z0-9_ ]*")


def _parse_not_null(s: str, i: int):
    j = _skip_ws(s, i)
    if s[j:j + 8].upper() == "NOT NULL":
        return False, j + 8
    return True, i


def _parse_type_str(s: str, i: int):
    i = _skip_ws(s, i)
    m = _TYPE_WORD_RE.match(s, i)
    if not m:
        raise ValueError(f"Cannot parse data type: {s!r} at {i}")
    # the word regex is greedy over spaces (multi-word atomics like
    # "DOUBLE PRECISION"); trim trailing keywords that belong to the parent
    word = m.group(0)
    head = word.split()[0].upper()
    if head in ("ARRAY", "MULTISET", "MAP", "ROW", "VECTOR"):
        i += len(head)
        i = _skip_ws(s, i)
        if head == "ROW" and i < len(s) and s[i] in "(<":
            close = ")" if s[i] == "(" else ">"
            i += 1
            fields = []
            while True:
                i = _skip_ws(s, i)
                fm = re.match(r"[A-Za-z_][A-Za-z0-9_]*|`[^`]+`", s[i:])
                if not fm:
                    raise ValueError(f"Expected field name at {i} in {s!r}")
                fname = fm.group(0).strip("`")
                i += fm.end()
                ftype, i = _parse_type_str(s, i)
                fields.append(DataField(len(fields), fname, ftype))
                i = _skip_ws(s, i)
                if i < len(s) and s[i] == ",":
                    i += 1
                    continue
                break
            if i >= len(s) or s[i] != close:
                raise ValueError(f"Expected {close!r} at {i} in {s!r}")
            i += 1
            nullable, i = _parse_not_null(s, i)
            return RowType(fields, nullable), i
        if i >= len(s) or s[i] != "<":
            raise ValueError(f"Expected '<' after {head} in {s!r}")
        i += 1
        if head == "MAP":
            k, i = _parse_type_str(s, i)
            i = _skip_ws(s, i)
            if i >= len(s) or s[i] != ",":
                raise ValueError(f"Expected ',' in MAP type: {s!r}")
            v, i = _parse_type_str(s, i + 1)
            out_cls = lambda nullable: MapType(k, v, nullable)  # noqa: E731
        elif head == "VECTOR":
            el, i = _parse_type_str(s, i)
            i = _skip_ws(s, i)
            if i >= len(s) or s[i] != ",":
                raise ValueError(f"Expected ',' in VECTOR type: {s!r}")
            i = _skip_ws(s, i + 1)
            nm = re.match(r"\d+", s[i:])
            if not nm:
                raise ValueError(f"Expected length in VECTOR type: {s!r}")
            length = int(nm.group(0))
            i += nm.end()
            out_cls = lambda nullable: VectorType(el, length, nullable)  # noqa: E731,E501
        else:
            el, i = _parse_type_str(s, i)
            cls = ArrayType if head == "ARRAY" else MultisetType
            out_cls = lambda nullable: cls(el, nullable)  # noqa: E731
        i = _skip_ws(s, i)
        if i >= len(s) or s[i] != ">":
            raise ValueError(f"Expected '>' at {i} in {s!r}")
        i += 1
        nullable, i = _parse_not_null(s, i)
        return out_cls(nullable), i
    # atomic: consume word + optional (p[,s]) + optional WITH LOCAL TIME
    # ZONE + optional NOT NULL, then delegate to the atomic matcher
    j = i + len(word)
    if j < len(s) and s[j] == "(":
        k = s.find(")", j)
        if k < 0:
            raise ValueError(f"Unterminated '(' in data type: {s!r}")
        j = k + 1
        k = _skip_ws(s, j)
        if s[k:k + 20].upper() == "WITH LOCAL TIME ZONE":
            j = k + 20
    atom = s[i:j]
    # word regex may have greedily eaten into ", name TYPE" of a parent ROW
    # — it can't, since ROW fields are split on ','. But it CAN eat a
    # trailing "NOT NULL" or "WITH LOCAL TIME ZONE"; _ATOMIC_RE handles
    # both, so pass them through.
    nullable = True
    rest = _skip_ws(s, j)
    if s[rest:rest + 8].upper() == "NOT NULL":
        atom = atom.rstrip() + " NOT NULL"
        j = rest + 8
    return _parse_atomic(atom.strip()), j


def _parse_atomic(s: str) -> DataType:
    m = _ATOMIC_RE.match(s)
    if not m:
        raise ValueError(f"Cannot parse data type: {s!r}")
    name, p1, p2, ltz, notnull = m.groups()
    name = name.strip()
    nullable = notnull is None
    if name == "TIMESTAMP" and ltz:
        return LocalZonedTimestampType(int(p1) if p1 else 6, nullable)
    if name in _SIMPLE_TYPES:
        return _SIMPLE_TYPES[name](nullable=nullable)
    if name == "CHAR":
        return CharType(int(p1) if p1 else 1, nullable)
    if name == "VARCHAR":
        return VarCharType(int(p1) if p1 else VarCharType.MAX_LENGTH, nullable)
    if name == "BINARY":
        return BinaryType(int(p1) if p1 else 1, nullable)
    if name == "VARBINARY":
        return VarBinaryType(int(p1) if p1 else VarBinaryType.MAX_LENGTH,
                             nullable)
    if name == "DECIMAL" or name == "NUMERIC":
        return DecimalType(int(p1) if p1 else 10, int(p2) if p2 else 0,
                           nullable)
    if name == "TIME":
        return TimeType(int(p1) if p1 else 0, nullable)
    if name == "TIMESTAMP":
        return TimestampType(int(p1) if p1 else 6, nullable)
    raise ValueError(f"Unknown atomic type: {s!r}")


# ---------------------------------------------------------------------------
# Arrow mapping (role of reference paimon-arrow ArrowUtils)
# ---------------------------------------------------------------------------

def data_type_to_arrow(t: DataType) -> pa.DataType:
    if isinstance(t, BooleanType):
        return pa.bool_()
    if isinstance(t, TinyIntType):
        return pa.int8()
    if isinstance(t, SmallIntType):
        return pa.int16()
    if isinstance(t, IntType):
        return pa.int32()
    if isinstance(t, BigIntType):
        return pa.int64()
    if isinstance(t, FloatType):
        return pa.float32()
    if isinstance(t, DoubleType):
        return pa.float64()
    if isinstance(t, (CharType, VarCharType)):
        return pa.string()
    if isinstance(t, VariantType):
        # single source of truth for the on-disk variant shape
        from paimon_tpu.data.variant import variant_arrow_type
        return variant_arrow_type()
    if isinstance(t, (BinaryType, VarBinaryType, BlobType)):
        return pa.binary()
    if isinstance(t, DecimalType):
        return pa.decimal128(t.precision, t.scale)
    if isinstance(t, DateType):
        return pa.date32()
    if isinstance(t, TimeType):
        return pa.time32("ms") if t.precision <= 3 else pa.time64("us")
    if isinstance(t, TimestampType):
        return pa.timestamp(_ts_unit(t.precision))
    if isinstance(t, LocalZonedTimestampType):
        return pa.timestamp(_ts_unit(t.precision), tz="UTC")
    if isinstance(t, ArrayType):
        return pa.list_(data_type_to_arrow(t.element))
    if isinstance(t, VectorType):
        return pa.list_(data_type_to_arrow(t.element), t.length)
    if isinstance(t, MultisetType):
        return pa.map_(data_type_to_arrow(t.element), pa.int32())
    if isinstance(t, MapType):
        return pa.map_(data_type_to_arrow(t.key), data_type_to_arrow(t.value))
    if isinstance(t, RowType):
        return pa.struct([pa.field(f.name, data_type_to_arrow(f.type),
                                   f.type.nullable) for f in t.fields])
    raise ValueError(f"No arrow mapping for {t}")


def _ts_unit(precision: int) -> str:
    if precision <= 3:
        return "ms"
    if precision <= 6:
        return "us"
    return "ns"


def row_type_to_arrow_schema(row: RowType) -> pa.Schema:
    return pa.schema([pa.field(f.name, data_type_to_arrow(f.type),
                               f.type.nullable) for f in row.fields])


def data_type_from_arrow(t: pa.DataType, nullable: bool = True) -> DataType:
    if pa.types.is_boolean(t):
        return BooleanType(nullable)
    if pa.types.is_int8(t):
        return TinyIntType(nullable)
    if pa.types.is_int16(t):
        return SmallIntType(nullable)
    if pa.types.is_int32(t):
        return IntType(nullable)
    if pa.types.is_int64(t):
        return BigIntType(nullable)
    if pa.types.is_float32(t):
        return FloatType(nullable)
    if pa.types.is_float64(t):
        return DoubleType(nullable)
    if pa.types.is_string(t) or pa.types.is_large_string(t):
        return VarCharType(VarCharType.MAX_LENGTH, nullable)
    if pa.types.is_binary(t) or pa.types.is_large_binary(t):
        return VarBinaryType(VarBinaryType.MAX_LENGTH, nullable)
    if pa.types.is_decimal(t):
        return DecimalType(t.precision, t.scale, nullable)
    if pa.types.is_date(t):
        return DateType(nullable)
    if pa.types.is_time(t):
        return TimeType(3, nullable)
    if pa.types.is_timestamp(t):
        prec = {"s": 0, "ms": 3, "us": 6, "ns": 9}[t.unit]
        if t.tz:
            return LocalZonedTimestampType(prec, nullable)
        return TimestampType(prec, nullable)
    if isinstance(t, pa.FixedSizeListType):
        return VectorType(data_type_from_arrow(t.value_type), t.list_size,
                          nullable)
    if pa.types.is_list(t) or pa.types.is_large_list(t):
        return ArrayType(data_type_from_arrow(t.value_type), nullable)
    if pa.types.is_map(t):
        return MapType(data_type_from_arrow(t.key_type),
                       data_type_from_arrow(t.item_type), nullable)
    if pa.types.is_struct(t):
        fields = [DataField(i, f.name,
                            data_type_from_arrow(f.type, f.nullable))
                  for i, f in enumerate(t)]
        return RowType(fields, nullable)
    raise ValueError(f"No paimon mapping for arrow type {t}")


def arrow_schema_to_row_type(schema: pa.Schema) -> RowType:
    fields = [DataField(i, f.name, data_type_from_arrow(f.type, f.nullable))
              for i, f in enumerate(schema)]
    return RowType(fields)


class SpecialFields:
    """System fields in KV data files
    (reference paimon-api/.../table/SpecialFields.java:76-93)."""

    KEY_FIELD_PREFIX = "_KEY_"
    KEY_FIELD_ID_START = SYSTEM_FIELD_ID_START

    SEQUENCE_NUMBER = DataField(2147483646, "_SEQUENCE_NUMBER",
                                BigIntType(False))
    VALUE_KIND = DataField(2147483645, "_VALUE_KIND", TinyIntType(False))
    LEVEL = DataField(2147483644, "_LEVEL", IntType(False))
    ROW_ID = DataField(2147483643, "_ROW_ID", BigIntType())

    @staticmethod
    def key_field(f: DataField) -> DataField:
        return DataField(f.id + SpecialFields.KEY_FIELD_ID_START,
                         SpecialFields.KEY_FIELD_PREFIX + f.name,
                         f.type.copy(False) if isinstance(f.type, DataType)
                         else f.type)
