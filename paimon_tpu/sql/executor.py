"""SQL execution over Arrow compute with predicate pushdown into scans.

`SQLContext` is the analog of the reference's DataFusion-backed
SQLContext (pypaimon/sql/__init__.py) and of the statement surface the
JVM engines expose.  Queries compile to pyarrow.compute kernels; WHERE
conjuncts that mention a single base-table column with literals are
converted to paimon predicates and pushed into the scan (manifest/stats/
index pruning), with the full WHERE re-applied on the decoded batch so
pushdown is purely an optimization.
"""

import re
from typing import Any, Dict, List, Optional, Tuple

import pyarrow as pa
import pyarrow.compute as pc

from paimon_tpu import predicate as P
from paimon_tpu.catalog.catalog import Catalog, Identifier
from paimon_tpu.schema import Schema
from paimon_tpu.schema.schema_manager import SchemaChange
from paimon_tpu.sql import parser as ast
from paimon_tpu.sql.parser import SQLError, parse
from paimon_tpu.types import RowKind, parse_data_type

_AGG_FUNCS = {"count", "sum", "min", "max", "avg"}

# scalar builtins (Compiler._func) + window names: catalog UDFs never
# shadow these
_BUILTIN_FUNCS = _AGG_FUNCS | {
    "abs", "upper", "lower", "length", "char_length", "trim", "concat",
    "coalesce", "nullif", "round", "floor", "ceil", "sqrt", "power",
    "substr", "substring", "replace", "year", "month", "day", "hour",
    "minute", "second", "if", "variant_get", "row_number", "rank",
    "dense_rank", "lag", "lead", "first_value", "last_value",
    "array", "map",
}


def _result(rows: List[str], name: str = "result") -> pa.Table:
    return pa.table({name: pa.array(rows, pa.string())})


def _sort_indices(tbl: pa.Table, keys) -> pa.Array:
    """`pc.sort_indices` over `keys` = [(name, direction, placement)].

    Modern pyarrow (>= 16) accepts only (name, direction) 2-tuples with
    ONE table-wide `null_placement`; SQL ORDER BY carries per-key NULLS
    FIRST/LAST.  Uniform placements pass straight through; mixed
    placements sort by a prepended is-null indicator per key whose
    placement disagrees with the majority (True first = NULLS FIRST),
    which pyarrow cannot express natively.
    """
    placements = {pl for _, _, pl in keys}
    if len(placements) <= 1:
        return pc.sort_indices(
            tbl, sort_keys=[(n, d) for n, d, _ in keys],
            null_placement=placements.pop() if placements else "at_end")
    sort_keys, extra = [], {}
    for i, (name, direction, placement) in enumerate(keys):
        ind = f"__nulls{i}"
        extra[ind] = pc.is_null(tbl.column(name))
        # nulls-first == indicator True first == descending indicator
        sort_keys.append(
            (ind, "descending" if placement == "at_start"
             else "ascending"))
        sort_keys.append((name, direction))
    aug = tbl
    for cn, arr in extra.items():
        aug = aug.append_column(cn, arr)
    return pc.sort_indices(aug, sort_keys=sort_keys)


class Scope:
    """A resolved relation: an Arrow table whose columns are internally
    qualified ("alias.col"), plus the bare-name resolution map."""

    def __init__(self, table: pa.Table, order: List[str]):
        self.table = table
        self.order = order                      # qualified names, in order
        self.bare: Dict[str, List[str]] = {}
        for q in order:
            bare = q.split(".", 1)[1] if "." in q else q
            self.bare.setdefault(bare, []).append(q)

    def resolve(self, col: ast.Column) -> str:
        if col.qualifier:
            q = f"{col.qualifier}.{col.name}"
            if q in self.table.column_names:
                return q
            raise SQLError(f"unknown column {q}")
        cands = self.bare.get(col.name, [])
        if len(cands) == 1:
            return cands[0]
        if not cands:
            raise SQLError(f"unknown column {col.name!r}")
        raise SQLError(f"ambiguous column {col.name!r}: {cands}")


class Compiler:
    """Compile AST expressions to Arrow arrays against a Scope.  When
    `subst` is set (post-aggregation), any sub-expression whose repr is a
    key in it resolves to that column instead of being re-evaluated."""

    def __init__(self, scope: Scope, subst: Optional[Dict[str, str]] = None):
        self.scope = scope
        self.subst = subst or {}

    def _rows(self) -> int:
        return self.scope.table.num_rows

    def compile(self, e) -> Any:
        if self.subst:
            key = repr(e)
            if key in self.subst:
                return self.scope.table.column(self.subst[key])
        return self._compile(e)

    def as_array(self, e) -> pa.ChunkedArray:
        return self.broadcast(self.compile(e))

    def broadcast(self, v) -> pa.ChunkedArray:
        """Expand an already-compiled scalar across the relation."""
        if isinstance(v, (pa.ChunkedArray, pa.Array)):
            return v
        if not isinstance(v, pa.Scalar):
            v = pa.scalar(v)
        if v.type == pa.null():
            return pa.nulls(self._rows())
        return pa.chunked_array([pa.repeat(v, self._rows())])

    def _compile(self, e) -> Any:
        if isinstance(e, ast.Literal):
            return pa.scalar(e.value)
        if isinstance(e, ast.Column):
            return self.scope.table.column(self.scope.resolve(e))
        if isinstance(e, ast.Unary):
            v = self.compile(e.operand)
            return pc.invert(v) if e.op == "NOT" else pc.negate(v)
        if isinstance(e, ast.Binary):
            return self._binary(e)
        if isinstance(e, ast.IsNull):
            v = self.as_array(e.expr)
            return pc.is_valid(v) if e.negated else pc.is_null(v)
        if isinstance(e, ast.InList):
            v = self.as_array(e.expr)
            vals = [self._literal(x) for x in e.values]
            res = pc.is_in(v, value_set=pa.array(vals))
            return pc.invert(res) if e.negated else res
        if isinstance(e, ast.BetweenExpr):
            v = self.compile(e.expr)
            res = pc.and_kleene(
                pc.greater_equal(v, self.compile(e.lo)),
                pc.less_equal(v, self.compile(e.hi)))
            return pc.invert(res) if e.negated else res
        if isinstance(e, ast.LikeExpr):
            res = pc.match_like(self.as_array(e.expr), e.pattern)
            return pc.invert(res) if e.negated else res
        if isinstance(e, ast.Case):
            return self._case(e)
        if isinstance(e, ast.Cast):
            from paimon_tpu.data.casting import cast_array
            from paimon_tpu.types import data_type_from_arrow
            arr = self.as_array(e.expr)
            if isinstance(arr, pa.ChunkedArray):
                arr = arr.combine_chunks()
            src = data_type_from_arrow(arr.type)
            return cast_array(arr, src, parse_data_type(e.type_str))
        if isinstance(e, ast.Func):
            return self._func(e)
        if isinstance(e, ast.Star):
            raise SQLError("* is only valid in SELECT items and COUNT(*)")
        raise SQLError(f"cannot evaluate expression: {e!r}")

    def _literal(self, e) -> Any:
        if isinstance(e, ast.Literal):
            return e.value
        if isinstance(e, ast.Unary) and e.op == "NEG" and \
                isinstance(e.operand, ast.Literal):
            return -e.operand.value
        raise SQLError(f"expected a literal, got {e!r}")

    def _binary(self, e: ast.Binary):
        op = e.op
        if op in ("AND", "OR"):
            l_, r_ = self.compile(e.left), self.compile(e.right)
            return (pc.and_kleene if op == "AND" else pc.or_kleene)(l_, r_)
        if op == "||":
            l_, r_ = self.as_array(e.left), self.as_array(e.right)
            return pc.binary_join_element_wise(
                pc.cast(l_, pa.string()), pc.cast(r_, pa.string()), "")
        l_, r_ = self.compile(e.left), self.compile(e.right)
        fn = {"+": pc.add, "-": pc.subtract, "*": pc.multiply,
              "/": pc.divide, "%": lambda a, b: pc.subtract(
                  a, pc.multiply(pc.cast(pc.divide(a, b), pa.int64()), b)),
              "=": pc.equal, "<>": pc.not_equal, "<": pc.less,
              "<=": pc.less_equal, ">": pc.greater,
              ">=": pc.greater_equal}.get(op)
        if fn is None:
            raise SQLError(f"unsupported operator {op}")
        return fn(l_, r_)

    def _case(self, e: ast.Case):
        result = self.as_array(e.default) if e.default is not None \
            else pa.nulls(self._rows())
        for cond, val in reversed(e.whens):
            c = self.as_array(cond)
            result = pc.if_else(pc.fill_null(c, False),
                                self.as_array(val), result)
        return result

    def _func(self, e: ast.Func):
        name, args = e.name, e.args
        if e.over is not None:
            raise SQLError(f"window function {name}() OVER is only "
                           f"allowed in SELECT items / ORDER BY")
        if name in _AGG_FUNCS:
            raise SQLError(f"aggregate {name}() not allowed here")
        a = [self.compile(x) for x in args]
        if name == "abs":
            return pc.abs(a[0])
        if name == "upper":
            return pc.utf8_upper(a[0])
        if name == "lower":
            return pc.utf8_lower(a[0])
        if name in ("length", "char_length"):
            return pc.utf8_length(a[0])
        if name == "trim":
            return pc.utf8_trim_whitespace(a[0])
        if name == "concat":
            arrs = [pc.cast(self.broadcast(v), pa.string()) for v in a]
            return pc.binary_join_element_wise(*arrs, "")
        if name == "coalesce":
            # NULL literals (type null) never contribute a value
            live = [x for x in a if x.type != pa.null()]
            if not live:
                return pa.nulls(self._rows())
            return live[0] if len(live) == 1 else pc.coalesce(*live)
        if name == "nullif":
            return pc.if_else(pc.fill_null(pc.equal(a[0], a[1]), False),
                              pa.nulls(self._rows()), self.broadcast(a[0]))
        if name == "round":
            nd = self._literal(args[1]) if len(args) > 1 else 0
            return pc.round(a[0], ndigits=nd)
        if name == "floor":
            return pc.floor(a[0])
        if name == "ceil":
            return pc.ceil(a[0])
        if name == "sqrt":
            return pc.sqrt(a[0])
        if name == "power":
            return pc.power(a[0], a[1])
        if name in ("substr", "substring"):
            start = self._literal(args[1]) - 1       # SQL is 1-based
            stop = start + self._literal(args[2]) if len(args) > 2 else None
            return pc.utf8_slice_codeunits(a[0], start, stop)
        if name == "replace":
            return pc.replace_substring(a[0],
                                        pattern=self._literal(args[1]),
                                        replacement=self._literal(args[2]))
        if name in ("year", "month", "day", "hour", "minute", "second"):
            return getattr(pc, name)(a[0])
        if name == "if":
            return pc.if_else(pc.fill_null(self.broadcast(a[0]), False),
                              self.broadcast(a[1]), self.broadcast(a[2]))
        if name == "variant_get":
            # variant_get(col, '$.path'): decode + path walk per row
            # (typed shredded columns are the fast path; this is the
            # general one — reference GenericVariantUtil.variantGet)
            from paimon_tpu.data.variant import (_parse_path, _walk,
                                                 column_to_variants)
            path = self._literal(args[1])
            segs = _parse_path(path)
            col = self.broadcast(a[0])
            vs = column_to_variants(col)
            vals = [None if v is None else _walk(v.to_object(), segs)
                    for v in vs]
            # mixed types fall back to JSON strings
            try:
                return pa.array(vals)
            except (pa.ArrowInvalid, pa.ArrowTypeError):
                import json as _json
                from paimon_tpu.data.variant import _json_default
                return pa.array([
                    None if x is None else
                    (x if isinstance(x, str)
                     else _json.dumps(x, default=_json_default))
                    for x in vals])
        if name == "array":
            # ARRAY[e1, e2, ...] constructor — per-row list assembly
            cols = [self.broadcast(x).to_pylist() for x in a]
            return pa.array([list(vs) for vs in zip(*cols)]) if cols \
                else pa.array([[]] * self._rows())
        if name == "map":
            # MAP[k1, v1, k2, v2, ...] constructor
            if len(a) % 2:
                raise SQLError("MAP[...] needs an even number of items")
            cols = [self.broadcast(x).to_pylist() for x in a]
            rows = []
            for vs in zip(*cols):
                rows.append(list(zip(vs[0::2], vs[1::2])))
            return pa.array(rows, pa.map_(pa.array(cols[0]).type if cols
                                          else pa.string(),
                                          pa.array(cols[1]).type if cols
                                          else pa.string()))
        raise SQLError(f"unknown function {name}()")


# ---------------------------------------------------------------------------
# WHERE -> paimon predicate pushdown
# ---------------------------------------------------------------------------

def _flip(op: str) -> str:
    return {"<": ">", "<=": ">=", ">": "<", ">=": "<=",
            "=": "=", "<>": "<>"}[op]


def expr_to_predicate(e, scope: Scope, base_qualifier: str,
                      exact: bool = False) -> Optional[P.Predicate]:
    """Convert an expression into a paimon Predicate over bare column
    names of the base table, or None when any part is not convertible.

    exact=False (pushdown): an AND may convert PARTIALLY — a superset
    predicate is fine for pruning because the full WHERE re-applies
    after decode.  exact=True (DELETE): every conjunct must convert or
    the whole conversion fails — a partial predicate would act on rows
    the full WHERE does not match."""

    def bare(col: ast.Column) -> Optional[str]:
        try:
            q = scope.resolve(col)
        except SQLError:
            return None
        qual, _, name = q.rpartition(".")
        return name if qual == base_qualifier else None

    def lit(x) -> Tuple[bool, Any]:
        if isinstance(x, ast.Literal):
            return True, x.value
        if isinstance(x, ast.Unary) and x.op == "NEG" and \
                isinstance(x.operand, ast.Literal):
            return True, -x.operand.value
        return False, None

    def conv(e) -> Optional[P.Predicate]:
        if isinstance(e, ast.Binary) and e.op in ("AND", "OR"):
            l_, r_ = conv(e.left), conv(e.right)
            if e.op == "AND":
                if l_ is not None and r_ is not None:
                    return P.and_(l_, r_)
                if exact:
                    return None                       # all-or-nothing
                return l_ if l_ is not None else r_   # partial AND prunes
            if l_ is not None and r_ is not None:     # OR needs both arms
                return P.or_(l_, r_)
            return None
        if isinstance(e, ast.Unary) and e.op == "NOT":
            # NOT over AND/OR is never pushed: conv() may convert those
            # subtrees PARTIALLY (a pruning subset), and negating a
            # subset over-prunes.  Simple leaves convert exactly, so
            # their negation is sound.
            if isinstance(e.operand, ast.Binary) and \
                    e.operand.op in ("AND", "OR"):
                return None
            inner = conv(e.operand)
            if inner is not None and isinstance(e.operand,
                                                (ast.Binary, ast.IsNull,
                                                 ast.InList, ast.LikeExpr,
                                                 ast.BetweenExpr)):
                return P.not_(inner)
            return None
        if isinstance(e, ast.Binary):
            left_col = isinstance(e.left, ast.Column)
            right_col = isinstance(e.right, ast.Column)
            if left_col and not right_col:
                ok, v = lit(e.right)
                f = bare(e.left)
                if ok and f:
                    return _leaf(e.op, f, v)
            elif right_col and not left_col:
                ok, v = lit(e.left)
                f = bare(e.right)
                if ok and f:
                    return _leaf(_flip(e.op), f, v)
            return None
        if isinstance(e, ast.IsNull):
            if isinstance(e.expr, ast.Column):
                f = bare(e.expr)
                if f:
                    return P.is_not_null(f) if e.negated else P.is_null(f)
            return None
        if isinstance(e, ast.InList):
            if isinstance(e.expr, ast.Column):
                f = bare(e.expr)
                vals = []
                for x in e.values:
                    ok, v = lit(x)
                    if not ok:
                        return None
                    vals.append(v)
                if f:
                    return P.not_in(f, vals) if e.negated \
                        else P.in_(f, vals)
            return None
        if isinstance(e, ast.BetweenExpr):
            if isinstance(e.expr, ast.Column):
                f = bare(e.expr)
                ok1, lo = lit(e.lo)
                ok2, hi = lit(e.hi)
                if f and ok1 and ok2:
                    b = P.between(f, lo, hi)
                    return P.not_(b) if e.negated else b
            return None
        if isinstance(e, ast.LikeExpr) and not e.negated:
            if isinstance(e.expr, ast.Column):
                f = bare(e.expr)
                m = re.fullmatch(r"([^%_]*)%", e.pattern)
                if f and m:
                    return P.starts_with(f, m.group(1))
            return None
        return None

    def _leaf(op, f, v):
        return {"=": P.equal, "<>": P.not_equal, "<": P.less_than,
                "<=": P.less_or_equal, ">": P.greater_than,
                ">=": P.greater_or_equal}[op](f, v)

    return conv(e)


# ---------------------------------------------------------------------------
# SQLContext
# ---------------------------------------------------------------------------

class SQLContext:
    """Run SQL against a catalog.  `sql()` returns a pyarrow Table for
    queries; DDL/DML return a one-column result table."""

    def __init__(self, catalog: Catalog, database: str = "default"):
        self.catalog = catalog
        self.database = database
        self._views: Dict[str, pa.Table] = {}
        self._view_stack: List[str] = []      # cycle detection

    # -- public -------------------------------------------------------------
    def register(self, name: str, table: pa.Table):
        """Register an in-memory Arrow table as a queryable view."""
        self._views[name] = table

    def sql(self, query: str) -> pa.Table:
        stmt = parse(query)
        self._expand_udfs(stmt)
        handler = {
            ast.Select: self._exec_select_stmt,
            ast.Explain: self._exec_explain,
            ast.Insert: self._exec_insert,
            ast.CreateTable: self._exec_create_table,
            ast.CreateDatabase: self._exec_create_database,
            ast.CreateView: self._exec_create_view,
            ast.DropView: self._exec_drop_view,
            ast.ShowViews: self._exec_show_views,
            ast.CreateFunction: self._exec_create_function,
            ast.DropFunction: self._exec_drop_function,
            ast.ShowFunctions: self._exec_show_functions,
            ast.DropTable: self._exec_drop_table,
            ast.DropDatabase: self._exec_drop_database,
            ast.ShowTables: self._exec_show_tables,
            ast.ShowDatabases: self._exec_show_databases,
            ast.ShowCreateTable: self._exec_show_create,
            ast.Describe: self._exec_describe,
            ast.Use: self._exec_use,
            ast.Delete: self._exec_delete,
            ast.MergeInto: self._exec_merge,
            ast.Truncate: self._exec_truncate,
            ast.Update: self._exec_update,
            ast.AlterTable: self._exec_alter,
            ast.Call: self._exec_call,
        }.get(type(stmt))
        if handler is None:
            raise SQLError(f"unsupported statement {type(stmt).__name__}")
        return handler(stmt)

    # -- helpers ------------------------------------------------------------
    def _ident(self, name: str) -> Identifier:
        if "." in name:
            db, t = name.split(".", 1)
            return Identifier(db, t)
        return Identifier(self.database, name)

    def _load_relation(self, ref: ast.TableRef) -> Tuple[pa.Table, str]:
        """Resolve a table reference to (arrow table, qualifier)."""
        alias = ref.alias or ref.name.split(".")[-1]
        if ref.name in self._views:
            return self._views[ref.name], alias
        name = ref.name
        if name.startswith("sys."):
            # catalog-level system tables (reference `sys` database);
            # they have no history — a time-travel clause would be
            # silently wrong, so reject it
            if ref.snapshot_id is not None or ref.tag is not None or \
                    ref.timestamp_ms is not None:
                raise SQLError("sys.* tables do not support time "
                               "travel")
            return self.catalog.system_table(name[4:]), alias
        system = None
        if "$" in name.split(".")[-1]:
            base, system = name.rsplit("$", 1)
            name = base
            alias = ref.alias or f"{base.split('.')[-1]}${system}"
        try:
            table = self.catalog.get_table(self._ident(name))
        except Exception as table_err:        # noqa: BLE001
            expanded = self._try_expand_view(ref, name)
            if expanded is None:
                raise table_err
            return expanded, alias
        dyn: Dict[str, str] = {}
        if ref.snapshot_id is not None:
            dyn["scan.snapshot-id"] = str(ref.snapshot_id)
        if ref.tag is not None:
            dyn["scan.tag-name"] = ref.tag
        if ref.timestamp_ms is not None:
            dyn["scan.timestamp-millis"] = str(ref.timestamp_ms)
        if dyn:
            table = table.copy(dyn)
        if system is not None:
            return table.system_table(system), alias
        return table, alias

    def _try_expand_view(self, ref: ast.TableRef,
                         name: str) -> Optional[pa.Table]:
        """Expand a catalog view (None when no such view): executed in
        the view's DEFINING database, with cycle detection."""
        ident = self._ident(name)
        try:
            view = self.catalog.get_view(ident)
        except (NotImplementedError, FileNotFoundError, KeyError,
                ValueError):
            return None
        if ref.snapshot_id is not None or ref.tag is not None or \
                ref.timestamp_ms is not None:
            raise SQLError("views do not support time travel")
        key = ident.full_name
        if key in self._view_stack:
            raise SQLError(
                f"cyclic view reference: "
                f"{' -> '.join(self._view_stack + [key])}")
        prev_db = self.database
        self._view_stack.append(key)
        try:
            self.database = view.options.get("default-database",
                                             prev_db)
            return self.sql(view.query)
        finally:
            self.database = prev_db
            self._view_stack.pop()

    def _pushed_predicate(self, table, alias: str, select: ast.Select):
        """WHERE -> pruning predicate, resolution-only (no I/O)."""
        if select.where is None or select.joins:
            return None
        cols = [f.name for f in table.row_type().fields]
        return expr_to_predicate(select.where, _probe_scope(cols, alias),
                                 alias)

    @staticmethod
    def _pushed_limit(select: ast.Select):
        """LIMIT safe to push into the scan: only a bare
        `SELECT <row-exprs> FROM t LIMIT n` — any WHERE/ORDER/GROUP/
        DISTINCT/OFFSET/set-op/aggregate/window consumes the full
        relation first, so those shapes read everything.  A pushed
        limit lets the pipelined reader (parallel/scan_pipeline.py)
        stop admitting splits early; the executor's final slice still
        applies and stays a no-op."""
        if select.limit is None or select.offset or select.joins or \
                select.where is not None or select.group_by or \
                select.having or select.distinct or select.order_by or \
                select.union_all is not None:
            return None
        for item in select.items:
            if _find_aggs(item.expr) or _find_windows(item.expr):
                return None
        return select.limit

    def _relation_scope(self, ref, select: ast.Select,
                        collect_plan: Optional[dict] = None) -> Scope:
        if isinstance(ref, ast.SubqueryRef):
            sub = self._exec_select(ref.select)
            q = sub.rename_columns(
                [f"{ref.alias}.{c}" for c in sub.column_names])
            return Scope(q, list(q.column_names))
        if isinstance(ref, ast.TableRef):
            rel, alias = self._load_relation(ref)
            if isinstance(rel, pa.Table):
                out = rel
            else:
                from paimon_tpu.table.table import FileStoreTable
                pushed = self._pushed_predicate(rel, alias, select)
                pushed_limit = self._pushed_limit(select) \
                    if isinstance(rel, FileStoreTable) else None
                if collect_plan is not None:
                    collect_plan["pushed"] = repr(pushed) \
                        if pushed is not None else None
                    collect_plan["pushed_limit"] = pushed_limit
                if pushed_limit is not None:
                    out = rel.to_arrow(predicate=pushed,
                                       limit=pushed_limit)
                else:
                    out = rel.to_arrow(predicate=pushed)
            q = out.rename_columns(
                [f"{alias}.{c}" for c in out.column_names])
            return Scope(q, list(q.column_names))
        raise SQLError(f"unsupported FROM item {ref!r}")

    # -- catalog UDF expansion ----------------------------------------------
    def _expand_udfs(self, stmt) -> None:
        """Rewrite calls to catalog functions (sql dialect) into their
        bound definition expressions; nested/composed definitions
        resolve through the fixed-point loop below."""
        cache: Dict[str, Any] = {}        # name -> Function | None

        def lookup(name: str):
            if name not in cache:
                try:
                    cache[name] = self.catalog.get_function(
                        self._ident(name))
                except (NotImplementedError, FileNotFoundError):
                    cache[name] = None    # genuinely absent; a corrupt
                    # definition file raises out of get_function instead
            return cache[name]

        def expand(node):
            if not isinstance(node, ast.Func) or node.over is not None \
                    or node.name in _BUILTIN_FUNCS:
                return node
            fn = lookup(node.name)
            if fn is None:
                return node
            d = fn.definition("sql")
            if d is None or not d.definition:
                raise SQLError(f"function {node.name}() has no sql-"
                               f"dialect definition this engine can run")
            if len(node.args) != len(fn.input_params):
                raise SQLError(
                    f"{node.name}() takes {len(fn.input_params)} "
                    f"argument(s), got {len(node.args)}")
            body = _parse_expr_full(d.definition)
            bound = _substitute_params(
                body, {p: a for (p, _), a in
                       zip(fn.input_params, node.args)})
            self._changed = True
            return bound

        for _ in range(9):
            self._changed = False
            if isinstance(stmt, ast.Select):
                _rewrite_select_exprs(stmt, expand)
            elif isinstance(stmt, ast.Insert) and stmt.select is not None:
                _rewrite_select_exprs(stmt.select, expand)
            elif isinstance(stmt, ast.Insert) and stmt.rows is not None:
                stmt.rows = [[_transform(c, expand) for c in row]
                             for row in stmt.rows]
            elif isinstance(stmt, ast.Update):
                stmt.assignments = [(c, _transform(e, expand))
                                    for c, e in stmt.assignments]
                if stmt.where is not None:
                    stmt.where = _transform(stmt.where, expand)
            elif isinstance(stmt, ast.Delete) and stmt.where is not None:
                stmt.where = _transform(stmt.where, expand)
            elif isinstance(stmt, ast.Explain):
                _rewrite_select_exprs(stmt.select, expand)
            else:
                return
            if not self._changed:
                return
        raise SQLError("catalog function expansion did not converge "
                       "(cyclic definitions?)")

    # -- SELECT -------------------------------------------------------------
    def _exec_select_stmt(self, s: ast.Select) -> pa.Table:
        return self._exec_select(s)

    def _subquery_rewriter(self):
        """fn for _transform: evaluate uncorrelated expression
        subqueries — scalar `(SELECT ...)` to a Literal (one column,
        at most one row, NULL when empty) and `x [NOT] IN (SELECT
        ...)` to literal comparisons. A correlated subquery fails
        inside its own execution with an unknown-column error. SQL
        three-valued logic is preserved when an IN result set
        contains NULL — `x IN (.., NULL)` is TRUE
        on a match else NULL (never FALSE), `x NOT IN (.., NULL)` is
        FALSE on a match else NULL (never TRUE) — via a CASE over the
        non-null match set."""
        def fn(e):
            if isinstance(e, ast.ExistsSubquery):
                return self._rewrite_exists(e, fn)
            if isinstance(e, ast.ScalarSubquery):
                sub = self._exec_select(e.select)
                if sub.num_columns != 1:
                    raise SQLError(
                        "scalar subquery must return exactly one "
                        f"column, got {sub.num_columns}")
                if sub.num_rows > 1:
                    raise SQLError(
                        "scalar subquery returned more than one row")
                return ast.Literal(
                    sub.column(0)[0].as_py() if sub.num_rows else None)
            if not isinstance(e, ast.InSubquery):
                return e
            sub = self._exec_select(e.select)
            if sub.num_columns != 1:
                raise SQLError(
                    "IN subquery must return exactly one column, "
                    f"got {sub.num_columns}")
            raw = sub.column(0).to_pylist()
            vals = [ast.Literal(v) for v in raw if v is not None]
            has_null = len(vals) != len(raw)
            match = ast.InList(e.expr, vals, negated=False)
            if not has_null:
                return ast.InList(e.expr, vals, e.negated)
            return ast.Case(
                whens=[(match, ast.Literal(e.negated is False))],
                default=ast.Literal(None))
        return fn

    def _rewrite_exists(self, e: "ast.ExistsSubquery", fn):
        """[NOT] EXISTS handling. Uncorrelated: evaluate once with
        LIMIT 1 -> boolean literal. Correlated on ONE outer-column
        equality over a single-table subquery: decorrelate to
        `outer [NOT] IN (SELECT inner FROM ... WHERE rest AND inner IS
        NOT NULL)` — the IS NOT NULL keeps NOT EXISTS semantics exact
        (a NULL inner value can never satisfy the equality, and a
        null-free set sidesteps NOT IN's three-valued trap)."""
        sub = e.select

        def conjuncts(x):
            if isinstance(x, ast.Binary) and x.op == "AND":
                return conjuncts(x.left) + conjuncts(x.right)
            return [x] if x is not None else []

        inner_cols = inner_alias = None
        if isinstance(sub.from_, ast.TableRef) and not sub.joins:
            try:
                tbl = self.catalog.get_table(
                    self._ident(sub.from_.name))
                inner_cols = {f.name for f in tbl.row_type().fields}
                inner_alias = sub.from_.alias or \
                    sub.from_.name.split(".")[-1]
            # lint-ok: swallow EXISTS rewrite probe: any failure here
            # just falls back to the unoptimized (correct) plan
            except Exception:
                pass

        def is_inner(col: "ast.Column") -> bool:
            if col.qualifier:
                return col.qualifier == inner_alias
            return inner_cols is not None and col.name in inner_cols

        outer_col = inner_col = None
        rest = []
        for c in conjuncts(sub.where):
            if isinstance(c, ast.Binary) and c.op == "=" and \
                    isinstance(c.left, ast.Column) and \
                    isinstance(c.right, ast.Column) and \
                    inner_cols is not None:
                li, ri = is_inner(c.left), is_inner(c.right)
                if li != ri:
                    if outer_col is not None:
                        raise SQLError(
                            "EXISTS with multiple correlated "
                            "equalities is not supported")
                    inner_col = c.left if li else c.right
                    outer_col = c.right if li else c.left
                    continue
            rest.append(c)

        if outer_col is None:
            # uncorrelated: one probe row decides the constant. Keep
            # the WHOLE query shape (UNION branches, LIMIT/OFFSET
            # semantics) — only add LIMIT 1 when none was given
            import copy as _copy
            probe = _copy.deepcopy(sub)
            if probe.limit is None and probe.offset is None and \
                    probe.union_all is None:
                probe.limit = 1
            t = self._exec_select(probe)
            return ast.Literal((t.num_rows > 0) != e.negated)

        def has_aggregate(x) -> bool:
            return bool(_find_funcs(
                x, lambda f: f.name in _AGG_FUNCS and f.over is None))

        if sub.group_by or sub.having or sub.distinct or \
                any(has_aggregate(i.expr) for i in sub.items):
            # an ungrouped aggregate always yields one row, making
            # EXISTS unconditionally true — decorrelation would
            # silently change that, so refuse
            raise SQLError("correlated EXISTS does not support "
                           "GROUP BY/HAVING/DISTINCT/aggregates")
        if sub.limit is not None or sub.offset:
            raise SQLError("correlated EXISTS does not support "
                           "LIMIT/OFFSET")
        where = ast.IsNull(inner_col, negated=True)
        for c in rest:
            where = ast.Binary("AND", where, c)
        inner_sel = ast.Select(
            items=[ast.SelectItem(inner_col)], from_=sub.from_,
            where=where)
        # feed the result back through the rewriter so the IN subquery
        # materializes in the same pass; then pin the OUTER-null case
        # explicitly — NULL probe means the equality can never hold,
        # so EXISTS is FALSE and NOT EXISTS is TRUE, independent of
        # the engine's IN null propagation
        materialized = fn(ast.InSubquery(outer_col, inner_sel,
                                         e.negated))
        return ast.Case(
            whens=[(ast.IsNull(outer_col), ast.Literal(e.negated))],
            default=materialized)

    def _materialize_subqueries(self, s: ast.Select) -> None:
        """In place and idempotent — leaves no InSubquery,
        ScalarSubquery or ExistsSubquery behind."""
        _rewrite_select_exprs(s, self._subquery_rewriter())

    def _exec_select(self, s: ast.Select,
                     collect_plan: Optional[dict] = None) -> pa.Table:
        self._materialize_subqueries(s)
        if s.union_all is not None:
            left = self._exec_select(
                ast.Select(s.items, s.from_, s.joins, s.where, s.group_by,
                           s.having, [], None, None, s.distinct))
            right = self._exec_select(s.union_all)
            right = right.rename_columns(left.column_names)
            right = right.cast(left.schema)
            setop = s.setop
            if setop == "union_all":
                out = pa.concat_tables([left, right],
                                       promote_options="none")
            elif setop == "union":
                out = pa.concat_tables(
                    [left, right], promote_options="none").group_by(
                    left.column_names, use_threads=False).aggregate([])
            else:
                # INTERSECT / EXCEPT: distinct set semantics with
                # NULL = NULL (python tuples, exactly SQL's set-op
                # grouping rules — arrow joins would drop null keys).
                # Keys are built POSITIONALLY from columns (duplicate
                # output names must not collapse) and made hashable
                # (ARRAY/MAP values arrive as lists/dicts).
                rset = set(_row_keys(right))
                seen = set()
                keep = []
                for i, key in enumerate(_row_keys(left)):
                    if key in seen:
                        continue
                    if (key in rset) == (setop == "intersect"):
                        seen.add(key)
                        keep.append(i)
                out = left.take(pa.array(keep, pa.int64()))
            # trailing ORDER BY / LIMIT bind to the whole set-op
            if s.order_by:
                keys = []
                for e, asc, pl in s.order_by:
                    direction = "ascending" if asc else "descending"
                    if isinstance(e, ast.Literal) and \
                            isinstance(e.value, int):
                        name = out.column_names[
                            _ordinal(e.value, out.num_columns) - 1]
                    elif isinstance(e, ast.Column) and \
                            e.qualifier is None and \
                            e.name in out.column_names:
                        name = e.name
                    else:
                        raise SQLError("ORDER BY over a UNION must "
                                       "reference output columns")
                    keys.append((name, direction, pl))
                out = out.take(_sort_indices(out, keys))
            if s.limit is not None:
                out = out.slice(s.offset or 0, s.limit)
            elif s.offset:
                out = out.slice(s.offset)
            return out
        if s.from_ is None:
            scope = Scope(pa.table({"__dual": pa.array([0])}), ["__dual"])
            comp = Compiler(scope)
            cols, names = [], []
            for item in s.items:
                names.append(item.alias or _display_name(item.expr))
                cols.append(comp.as_array(item.expr))
            return pa.table(dict(zip(names, cols)))

        scope = self._relation_scope(s.from_, s, collect_plan)
        for j in s.joins:
            scope = self._join(scope, j, s)
        # full WHERE on the decoded relation (pushdown already pruned)
        if s.where is not None:
            mask = Compiler(scope).as_array(s.where)
            scope = Scope(scope.table.filter(pc.fill_null(mask, False)),
                          scope.order)

        has_agg = any(_find_aggs(i.expr) for i in s.items) or \
            (s.having is not None and _find_aggs(s.having)) or s.group_by
        if s.having is not None and not has_agg:
            raise SQLError("HAVING requires GROUP BY or an aggregate; "
                           "use WHERE for row filters")
        windows: Dict[str, ast.Func] = {}
        for item in s.items:
            for f in _find_windows(item.expr):
                windows.setdefault(repr(f), f)
        for e, _, _ in s.order_by:
            for f in _find_windows(e):
                windows.setdefault(repr(f), f)
        if windows and has_agg:
            raise SQLError("window functions cannot be mixed with "
                           "GROUP BY / aggregates in one SELECT; use a "
                           "subquery")
        if has_agg:
            out = self._aggregate(scope, s)
        elif windows:
            scope, win_subst = self._apply_windows(scope, windows)
            out = self._project(scope, s, subst=win_subst)
        else:
            out = self._project(scope, s, subst=None)
        if s.distinct:
            out = out.group_by(out.column_names,
                               use_threads=False).aggregate([])
        if s.limit is not None:
            off = s.offset or 0
            out = out.slice(off, s.limit)
        elif s.offset:
            out = out.slice(s.offset)
        return out

    def _join(self, left: Scope, j: ast.JoinClause, s: ast.Select) -> Scope:
        right = self._relation_scope(j.right, s)
        lt, rt = left.table, right.table
        if j.kind == "cross":
            lk = lt.append_column("__cj", pa.array([1] * lt.num_rows))
            rk = rt.append_column("__cj", pa.array([1] * rt.num_rows))
            out = lk.join(rk, keys=["__cj"], join_type="inner")
            out = out.drop_columns(["__cj"])
            return Scope(out, left.order + right.order)
        if j.condition is None:
            raise SQLError(f"{j.kind} JOIN requires ON")
        # split ON into equi-conjuncts (one side each) + residual
        probe_cols = {q: pa.array([], lt.column(q).type)
                      for q in left.order}
        probe_cols.update({q: pa.array([], rt.column(q).type)
                           for q in right.order})
        probe = Scope(pa.table(probe_cols), left.order + right.order)
        equi, residual = [], []
        for conj in _split_conjuncts(j.condition):
            pair = _equi_pair(conj, probe, left, right)
            if pair:
                equi.append(pair)
            else:
                residual.append(conj)
        if not equi:
            raise SQLError("JOIN ON requires at least one equality "
                           "between the two sides")
        # join on temp key copies so both sides' original (qualified)
        # columns survive Arrow's key coalescing
        order = left.order + right.order
        # residual (non-equi) ON conditions participate in the MATCH:
        # for outer joins, run an inner join + residual filter, then add
        # back unmatched rows null-padded — filtering the outer result
        # would wrongly drop its null rows
        aug = bool(residual) and j.kind != "inner"
        if aug:
            import numpy as np
            lt = lt.append_column("__lrow",
                                  pa.array(np.arange(lt.num_rows)))
            rt = rt.append_column("__rrow",
                                  pa.array(np.arange(rt.num_rows)))
        for i, (lq, rq) in enumerate(equi):
            lt = lt.append_column(f"__jk{i}", lt.column(lq))
            rt = rt.append_column(f"__jk{i}", rt.column(rq))
        jk = [f"__jk{i}" for i in range(len(equi))]
        out = lt.join(rt, keys=jk, join_type="inner" if aug else j.kind,
                      coalesce_keys=True)
        out = out.drop_columns(jk)
        keep = order + (["__lrow", "__rrow"] if aug else [])
        out = out.select(keep)        # Arrow join may reorder columns
        if residual:
            mask = None
            comp = Compiler(Scope(out, keep))
            for conj in residual:
                m = comp.as_array(conj)
                mask = m if mask is None else pc.and_kleene(mask, m)
            out = out.filter(pc.fill_null(mask, False))
        if aug:
            import numpy as np
            parts = [out.select(order)]
            if j.kind in ("left outer", "full outer"):
                miss = ~np.isin(np.arange(lt.num_rows),
                                np.asarray(out.column("__lrow")))
                missing = lt.filter(pa.array(miss))
                pad = {q: missing.column(q) for q in left.order}
                pad.update({q: pa.nulls(missing.num_rows,
                                        rt.column(q).type)
                            for q in right.order})
                parts.append(pa.table(pad).select(order))
            if j.kind in ("right outer", "full outer"):
                miss = ~np.isin(np.arange(rt.num_rows),
                                np.asarray(out.column("__rrow")))
                missing = rt.filter(pa.array(miss))
                pad = {q: pa.nulls(missing.num_rows, lt.column(q).type)
                       for q in left.order}
                pad.update({q: missing.column(q) for q in right.order})
                parts.append(pa.table(pad).select(order))
            out = pa.concat_tables(parts, promote_options="none")
        else:
            out = out.select(order)
        return Scope(out, order)

    def _project(self, scope: Scope, s: ast.Select,
                 subst: Optional[Dict[str, str]]) -> pa.Table:
        comp = Compiler(scope, subst)
        names: List[str] = []
        cols: List[Any] = []
        for item in s.items:
            if isinstance(item.expr, ast.Star):
                q = item.expr.qualifier
                for qual_name in scope.order:
                    if qual_name.startswith("__"):
                        continue
                    qualifier, _, bare = qual_name.rpartition(".")
                    if q is None or qualifier == q:
                        names.append(bare)
                        cols.append(scope.table.column(qual_name))
                continue
            names.append(item.alias or _display_name(item.expr))
            cols.append(comp.as_array(item.expr))
        out = pa.table(dict(zip(_dedup(names), cols)))
        if s.order_by:
            out = self._order(out, scope, s, subst, names)
        return out

    def _order(self, out: pa.Table, scope: Scope, s: ast.Select,
               subst: Optional[Dict[str, str]],
               names: List[str]) -> pa.Table:
        comp = Compiler(scope, subst)
        sort_cols, keys = [], []
        tmp = out
        for idx, (e, asc, pl) in enumerate(s.order_by):
            direction = "ascending" if asc else "descending"
            if isinstance(e, ast.Literal) and isinstance(e.value, int):
                pos = _ordinal(e.value, out.num_columns)
                keys.append((out.column_names[pos - 1], direction, pl))
                continue
            if isinstance(e, ast.Column) and e.qualifier is None and \
                    e.name in out.column_names:
                keys.append((e.name, direction, pl))
                continue
            col = comp.as_array(e)
            cn = f"__ord{idx}"
            tmp = tmp.append_column(cn, col)
            sort_cols.append(cn)
            keys.append((cn, direction, pl))
        idxs = _sort_indices(tmp, keys)
        return tmp.take(idxs).drop_columns(sort_cols) if sort_cols \
            else tmp.take(idxs)

    def _aggregate(self, scope: Scope, s: ast.Select) -> pa.Table:
        aggs: Dict[str, ast.Func] = {}
        for item in s.items:
            for f in _find_aggs(item.expr):
                aggs.setdefault(repr(f), f)
        if s.having is not None:
            for f in _find_aggs(s.having):
                aggs.setdefault(repr(f), f)
        for e, _, _ in s.order_by:
            for f in _find_aggs(e):
                aggs.setdefault(repr(f), f)
        comp = Compiler(scope)
        work = scope.table
        subst: Dict[str, str] = {}
        for i, ge in enumerate(s.group_by):
            cn = f"__g{i}"
            # GROUP BY may name a select alias or a position
            target = ge
            if isinstance(ge, ast.Literal) and isinstance(ge.value, int):
                target = s.items[_ordinal(ge.value, len(s.items)) - 1].expr
            elif isinstance(ge, ast.Column) and ge.qualifier is None:
                for item in s.items:
                    if item.alias == ge.name:
                        target = item.expr
                        break
            work = work.append_column(cn, comp.as_array(target))
            subst[repr(target)] = cn
            if repr(ge) != repr(target):
                subst[repr(ge)] = cn
        specs: List[Tuple[str, str]] = []
        out_names: List[Tuple[str, str]] = []     # (arrow result, subst key)
        for k, (key, f) in enumerate(aggs.items()):
            cn = f"__a{k}"
            if f.name == "count" and (not f.args or
                                      isinstance(f.args[0], ast.Star)):
                ones = pa.chunked_array(
                    [pa.repeat(pa.scalar(1), work.num_rows)])
                work = work.append_column(cn, ones)
                specs.append((cn, "sum"))
                out_names.append((f"{cn}_sum", key))
                continue
            work = work.append_column(cn, comp.as_array(f.args[0]))
            if f.distinct:
                fname = "count_distinct"
            else:
                fname = {"count": "count", "sum": "sum", "min": "min",
                         "max": "max", "avg": "mean"}[f.name]
            specs.append((cn, fname))
            out_names.append((f"{cn}_{fname}", key))
        if not s.group_by:
            work = work.append_column("__gall",
                                      pa.chunked_array(
                                          [pa.repeat(pa.scalar(1),
                                                     work.num_rows)]))
            keys = ["__gall"]
        else:
            keys = [f"__g{i}" for i in range(len(s.group_by))]
        gtable = work.group_by(keys, use_threads=False).aggregate(specs)
        order = list(gtable.column_names)
        if not s.group_by and gtable.num_rows == 0:
            # a global aggregate over empty input still yields one row
            # (counts become 0 below, other aggregates NULL)
            gtable = pa.table({name: pa.nulls(1, gtable.column(name).type)
                               for name in order})
        # substitution: each aggregate expression (by structural repr)
        # resolves to its arrow result column (e.g. "__a0_sum")
        agg_subst = {key: name for name, key in out_names}
        agg_subst.update(subst)
        # count()/count(*) never return NULL — fill empty groups with 0
        for key, f in aggs.items():
            cn = agg_subst[key]
            if f.name == "count":
                filled = pc.fill_null(pc.cast(gtable.column(cn),
                                              pa.int64()), 0)
                gtable = gtable.set_column(
                    gtable.column_names.index(cn), cn, filled)
        gscope = Scope(gtable, order)
        if s.having is not None:
            mask = Compiler(gscope, agg_subst).as_array(s.having)
            gtable = gtable.filter(pc.fill_null(mask, False))
            gscope = Scope(gtable, order)
        return self._project(gscope, s, subst=agg_subst)

    # -- window functions ----------------------------------------------------
    def _apply_windows(self, scope: Scope,
                       wfuncs: Dict[str, ast.Func]
                       ) -> Tuple[Scope, Dict[str, str]]:
        """Evaluate each window expression into a temp column of the
        scope; returns (augmented scope, repr->column substitution).

        Frames follow the engines' defaults: with ORDER BY, aggregates
        use the running RANGE frame (UNBOUNDED PRECEDING..CURRENT ROW,
        peers included) and last_value means "last peer"; without
        ORDER BY the frame is the whole partition.  Functions sharing
        an identical OVER spec share one sort."""
        import numpy as np

        table = scope.table
        n = table.num_rows
        comp = Compiler(scope)
        subst: Dict[str, str] = {}
        order_names = list(scope.order)

        by_spec: Dict[str, List[Tuple[str, ast.Func]]] = {}
        for key, f in wfuncs.items():
            by_spec.setdefault(repr(f.over), []).append((key, f))

        k = 0
        for group in by_spec.values():
            w = group[0][1].over
            seg = _WindowSegments(comp, w, n)
            for key, f in group:
                col = self._window_column(comp, f, seg, n)
                cname = f"__w{k}"
                k += 1
                table = table.append_column(cname, col)
                order_names.append(cname)
                subst[key] = cname
        return Scope(table, order_names), subst

    def _window_column(self, comp, f: ast.Func, seg: "_WindowSegments",
                       n: int):
        """One window function's values, in ORIGINAL row order."""
        import numpy as np

        name = f.name
        order = seg.order
        pos = np.arange(n)
        if name == "row_number":
            return seg.scatter(pos - seg.seg_first + 1)
        if name in ("rank", "dense_rank"):
            kc = seg.key_change
            if name == "rank":
                return seg.scatter(np.maximum.accumulate(
                    np.where(kc, pos, 0)) - seg.seg_first + 1)
            c = np.cumsum(kc)
            return seg.scatter(c - c[seg.seg_first] + 1)
        if name in ("lag", "lead"):
            off = 1
            if len(f.args) > 1:
                off = int(comp._literal(f.args[1]))
            default = comp._literal(f.args[2]) if len(f.args) > 2 \
                else None
            shift = -off if name == "lag" else off
            cand = pos + shift
            valid = (cand >= 0) & (cand < n)
            cand_c = np.clip(cand, 0, max(n - 1, 0))
            valid &= seg.seg_id[cand_c] == seg.seg_id
            src_sorted = np.where(valid, order[cand_c], -1)
            return seg.gather_arg(comp, f, src_sorted, default)
        if name == "first_value":
            return seg.gather_arg(comp, f, order[seg.seg_first], None)
        if name == "last_value":
            # with ORDER BY: last PEER of the current row; without:
            # partition last
            last = seg.peer_last if seg.has_order else seg.seg_last
            return seg.gather_arg(comp, f, order[last], None)
        if name in _AGG_FUNCS:
            return self._window_aggregate(comp, f, seg, n)
        raise SQLError(f"unsupported window function {name}()")

    def _window_aggregate(self, comp, f: ast.Func,
                          seg: "_WindowSegments", n: int):
        import numpy as np

        name = f.name
        order = seg.order
        star = name == "count" and (not f.args or
                                    isinstance(f.args[0], ast.Star))
        if star:
            nn = np.ones(n, dtype=np.float64)
            vals = nn
            int_result = True
        else:
            v = comp.as_array(f.args[0])
            if isinstance(v, pa.ChunkedArray):
                v = v.combine_chunks()
            nn = (~np.asarray(pc.is_null(v)))[order].astype(np.float64)
            if name == "count":
                vals = nn
            else:
                if not (pa.types.is_integer(v.type) or
                        pa.types.is_floating(v.type) or
                        pa.types.is_boolean(v.type)):
                    raise SQLError(
                        f"window {name}() needs a numeric argument")
                int_result = pa.types.is_integer(v.type)
                vals = np.asarray(pc.fill_null(
                    pc.cast(v, pa.float64()), 0.0))[order]
        if name == "count":
            if seg.has_order:
                cum = np.cumsum(nn)
                res = seg.running(cum)
            else:
                res = np.add.reduceat(nn, seg.starts_idx)[seg.seg_id]
            return seg.scatter(res.astype(np.int64))
        if name in ("sum", "avg"):
            if seg.has_order:
                tot = seg.running(np.cumsum(vals * nn))
                cnt = seg.running(np.cumsum(nn))
            else:
                tot = np.add.reduceat(vals * nn,
                                      seg.starts_idx)[seg.seg_id]
                cnt = np.add.reduceat(nn, seg.starts_idx)[seg.seg_id]
            res = tot if name == "sum" else tot / np.maximum(cnt, 1)
            if name == "sum" and not star and int_result:
                res = res.astype(np.int64)
            return seg.scatter(res, null_mask=cnt == 0)
        # min / max
        if seg.has_order:
            raise SQLError(f"window {name}() with ORDER BY (running "
                           f"frame) is not supported; omit ORDER BY "
                           f"for the whole-partition value")
        fillv = np.inf if name == "min" else -np.inf
        vv = np.where(nn > 0, vals, fillv)
        red = np.minimum if name == "min" else np.maximum
        cnt = np.add.reduceat(nn, seg.starts_idx)[seg.seg_id]
        res = red.reduceat(vv, seg.starts_idx)[seg.seg_id]
        if int_result:
            res = np.where(cnt == 0, 0, res).astype(np.int64)
        return seg.scatter(res, null_mask=cnt == 0)

    # -- EXPLAIN ------------------------------------------------------------
    def _exec_explain(self, e: ast.Explain) -> pa.Table:
        s = e.select
        lines = ["== Logical Plan =="]
        if isinstance(s.from_, ast.TableRef):
            # resolution only — EXPLAIN never reads data files
            rel, alias = self._load_relation(s.from_)
            lines.append(f"Scan: {s.from_.name}")
            pushed = None if isinstance(rel, pa.Table) else \
                self._pushed_predicate(rel, alias, s)
            if pushed is not None:
                lines.append(f"  pushed predicate: {pushed!r}")
            elif s.where is not None:
                lines.append("  pushed predicate: none")
            if not isinstance(rel, pa.Table):
                from paimon_tpu.table.table import FileStoreTable
                if isinstance(rel, FileStoreTable) and \
                        self._pushed_limit(s) is not None:
                    lines.append(f"  pushed limit: {s.limit}")
        if s.where is not None:
            lines.append(f"Filter: {s.where!r}")
        for j in s.joins:
            lines.append(f"Join[{j.kind}]: {j.condition!r}")
        if s.group_by or any(_find_aggs(i.expr) for i in s.items):
            lines.append(f"Aggregate: group_by={s.group_by!r}")
        if s.order_by:
            lines.append(f"Sort: {len(s.order_by)} key(s)")
        if s.limit is not None:
            lines.append(f"Limit: {s.limit}")
        return _result(lines, "plan")

    # -- DML ----------------------------------------------------------------
    def _exec_insert(self, ins: ast.Insert) -> pa.Table:
        table = self.catalog.get_table(self._ident(ins.table))
        schema = table.arrow_schema()
        if ins.select is not None:
            data = self._exec_select(ins.select)
            if ins.columns is None:
                # positional mapping onto the table's leading fields
                cols = [f.name for f in schema][:data.num_columns]
                data = data.rename_columns(cols)
            else:
                cols = ins.columns
        else:
            scope = Scope(pa.table({"__dual": pa.array([0])}), ["__dual"])
            comp = Compiler(scope)
            n_cols = len(ins.rows[0])
            cols = ins.columns or [f.name for f in schema][:n_cols]
            arrays: List[List[Any]] = [[] for _ in range(n_cols)]
            rewrite = self._subquery_rewriter()
            for row in ins.rows:
                if len(row) != n_cols:
                    raise SQLError("VALUES rows have inconsistent arity")
                for i, cell in enumerate(row):
                    v = comp.compile(_transform(cell, rewrite))
                    if isinstance(v, pa.Scalar):
                        v = v.as_py()
                    elif isinstance(v, (pa.Array, pa.ChunkedArray)):
                        # 1-row dual scope: unwrap the single cell
                        v = v[0].as_py()
                    arrays[i].append(v)
            # build with the target field type when known — inference
            # cannot reconstruct map<> / nested types from python cells
            ftypes = {f.name: f.type for f in schema}

            def _build(c, vals):
                if c in ftypes:
                    try:
                        return pa.array(vals, ftypes[c])
                    except (pa.ArrowInvalid, pa.ArrowTypeError):
                        pass        # fall back to inference + later cast
                return pa.array(vals)

            data = pa.table({c: _build(c, vals)
                             for c, vals in zip(cols, arrays)})
        batch: Dict[str, pa.ChunkedArray] = {}
        for field in schema:
            if field.name in cols:
                src = data.column(cols.index(field.name)) \
                    if isinstance(data, pa.Table) else None
                batch[field.name] = pc.cast(src, field.type)
            else:
                batch[field.name] = pa.nulls(data.num_rows, field.type)
        out = pa.table(batch)
        wb = table.new_batch_write_builder()
        if ins.overwrite:
            wb = wb.with_overwrite()
        # context-managed: a failed flush must still join the pipelined
        # writer's pool (parallel/write_pipeline.py), not leak it
        with wb.new_write() as w:
            w.write_arrow(out)
            wb.new_commit().commit(w.prepare_commit())
        return _result([f"{out.num_rows} rows inserted"])

    def _exec_merge(self, m: "ast.MergeInto") -> pa.Table:
        """MERGE INTO over one right-outer join of target x source:
        pairs with a live target row feed the WHEN MATCHED clauses
        (first match wins), source rows with no target match feed WHEN
        NOT MATCHED; one upsert/delete batch commits atomically
        (reference MergeIntoProcedure semantics on pk tables)."""
        import numpy as np

        table = self.catalog.get_table(self._ident(m.target))
        if not table.primary_keys:
            raise SQLError("MERGE INTO requires a primary-key table")
        t_alias = m.target_alias or m.target.split(".")[-1]
        sel = ast.Select(
            items=[ast.SelectItem(ast.Star())],
            from_=ast.TableRef(m.target, alias=t_alias),
            joins=[ast.JoinClause("right outer", m.source, m.on)])
        self._materialize_subqueries(sel)
        scope = self._relation_scope(sel.from_, sel)
        scope = self._join(scope, sel.joins[0], sel)
        comp = Compiler(scope)
        n = scope.table.num_rows
        target_cols = [f.name for f in table.row_type().fields]
        schema = table.arrow_schema()

        # a pk column is NOT NULL in the target, so its null-ness in
        # the outer join identifies unmatched source rows
        pk_q = f"{t_alias}.{table.primary_keys[0]}"
        matched = np.asarray(
            pc.is_valid(scope.table.column(pk_q)).combine_chunks(),
            dtype=bool) if n else np.zeros(0, bool)

        def cond_mask(cond) -> np.ndarray:
            if cond is None:
                return np.ones(n, bool)
            v = comp.as_array(cond)
            return np.asarray(pc.fill_null(v, False).combine_chunks(),
                              dtype=bool)

        # statement-level validation runs regardless of what the data
        # currently matches — an invalid MERGE must fail deterministically
        for clause in m.clauses:
            if clause.action == "update":
                bad = set(dict(clause.assignments)) & (
                    set(table.primary_keys) |
                    set(table.partition_keys or []))
                if bad:
                    raise SQLError(
                        f"cannot UPDATE key column(s) {sorted(bad)}")

        out_tables, out_kinds = [], []
        remaining_m = matched.copy()
        remaining_nm = ~matched
        for clause in m.clauses:
            remaining = remaining_m if clause.matched else remaining_nm
            mask = remaining & cond_mask(clause.condition)
            if clause.matched:
                remaining_m = remaining_m & ~mask
            else:
                remaining_nm = remaining_nm & ~mask
            if not mask.any():
                continue
            sub = scope.table.filter(pa.array(mask))
            sub_scope = Scope(sub, scope.order)
            sub_comp = Compiler(sub_scope)
            if clause.action == "update":
                assigns = dict(clause.assignments)
                cols = {}
                for c in target_cols:
                    if c in assigns:
                        cols[c] = pc.cast(sub_comp.as_array(assigns[c]),
                                          schema.field(c).type)
                    else:
                        cols[c] = sub.column(f"{t_alias}.{c}")
                out_tables.append(pa.table(cols, schema=schema))
                out_kinds.append(np.zeros(sub.num_rows, np.int8))
            elif clause.action == "delete":
                cols = {c: sub.column(f"{t_alias}.{c}")
                        for c in target_cols}
                out_tables.append(pa.table(cols, schema=schema))
                out_kinds.append(np.full(sub.num_rows, RowKind.DELETE,
                                         np.int8))
            else:                       # insert
                cols_order = clause.insert_columns or target_cols
                if len(cols_order) != len(clause.insert_values):
                    raise SQLError("INSERT arity mismatch in MERGE")
                vals = dict(zip(cols_order, clause.insert_values))
                unknown = set(vals) - set(target_cols)
                if unknown:
                    raise SQLError(f"unknown INSERT column(s) "
                                   f"{sorted(unknown)}")
                cols = {}
                for c in target_cols:
                    if c in vals:
                        cols[c] = pc.cast(sub_comp.as_array(vals[c]),
                                          schema.field(c).type)
                    else:
                        cols[c] = pa.nulls(sub.num_rows,
                                           schema.field(c).type)
                out_tables.append(pa.table(cols, schema=schema))
                out_kinds.append(np.zeros(sub.num_rows, np.int8))
        if not out_tables:
            return _result(["0 rows merged"])
        batch = pa.concat_tables(out_tables, promote_options="none")
        kinds = np.concatenate(out_kinds)
        # SQL MERGE forbids touching one target row twice (duplicate
        # source join keys would make the outcome order-dependent)
        pk_cols = [batch.column(k).to_pylist()
                   for k in table.primary_keys]
        seen_keys = set()
        for key in zip(*pk_cols):
            if key in seen_keys:
                raise SQLError(
                    f"MERGE INTO affected target row {key} more than "
                    f"once (duplicate keys in the source?)")
            seen_keys.add(key)
        wb = table.new_batch_write_builder()
        w = wb.new_write()
        try:
            w.write_arrow(batch, row_kinds=kinds)
            wb.new_commit().commit(w.prepare_commit())
        finally:
            w.close()
        return _result([f"{batch.num_rows} rows merged"])

    def _exec_truncate(self, t: "ast.Truncate") -> pa.Table:
        """TRUNCATE TABLE: one OVERWRITE snapshot that drops every live
        file (reference TRUNCATE via INSERT OVERWRITE / purge)."""
        _purge_all(self.catalog.get_table(self._ident(t.table)))
        return _result(["OK"])

    def _exec_delete(self, d: ast.Delete) -> pa.Table:
        table = self.catalog.get_table(self._ident(d.table))
        if d.where is None:
            raise SQLError("DELETE without WHERE is not supported; "
                           "DROP TABLE or overwrite instead")
        cols = [f.name for f in table.row_type().fields]
        alias = d.table.split(".")[-1]
        # IN (SELECT ...) materializes to a literal list first (same
        # rewrite the SELECT/UPDATE paths get)
        where = _transform(d.where, self._subquery_rewriter())
        pred = expr_to_predicate(where, _probe_scope(cols, alias),
                                 alias, exact=True)
        if pred is None:
            raise SQLError("DELETE WHERE must be expressible as column/"
                           f"literal comparisons, got: {d.where!r}")
        # delete_where returns a snapshot id; count matches for the
        # rows-affected result with a pushdown scan projected to the
        # predicate's own columns (the filter runs after projection)
        count_cols = sorted(set(pred.fields())) or [cols[0]]
        n = table.to_arrow(projection=count_cols, predicate=pred).num_rows
        table.delete_where(pred)
        return _result([f"{n} rows deleted"])

    def _exec_update(self, u: ast.Update) -> pa.Table:
        table = self.catalog.get_table(self._ident(u.table))
        if not table.primary_keys:
            raise SQLError("UPDATE requires a primary-key table")
        alias = u.table.split(".")[-1]
        sel = ast.Select(items=[ast.SelectItem(ast.Star())],
                         from_=ast.TableRef(u.table, alias=alias),
                         where=u.where)
        matched = self._exec_select(sel)
        if matched.num_rows == 0:
            return _result(["0 rows updated"])
        q = matched.rename_columns(
            [f"{alias}.{c}" for c in matched.column_names])
        scope = Scope(q, list(q.column_names))
        comp = Compiler(scope)
        out = matched
        schema = table.arrow_schema()
        rewrite = self._subquery_rewriter()
        for col, e in u.assignments:
            if col in (table.partition_keys or []) or \
                    col in table.primary_keys:
                raise SQLError(f"cannot UPDATE key column {col!r}")
            idx = out.column_names.index(col)
            e = _transform(e, rewrite)
            val = pc.cast(comp.as_array(e), schema.field(col).type)
            out = out.set_column(idx, col, val)
        wb = table.new_batch_write_builder()
        with wb.new_write() as w:
            w.write_arrow(out.cast(schema))
            wb.new_commit().commit(w.prepare_commit())
        return _result([f"{out.num_rows} rows updated"])

    # -- DDL ----------------------------------------------------------------
    def _exec_create_table(self, c: ast.CreateTable) -> pa.Table:
        b = Schema.builder()
        for col in c.columns:
            b.column(col.name, parse_data_type(col.type_str),
                     description=col.comment)
        if c.primary_key:
            b.primary_key(*c.primary_key)
        if c.partitioned_by:
            b.partition_keys(*c.partitioned_by)
        b.options(c.options)
        if c.comment:
            b.comment(c.comment)
        self.catalog.create_table(self._ident(c.table), b.build(),
                                  ignore_if_exists=c.if_not_exists)
        return _result(["OK"])

    def _exec_create_database(self, c: ast.CreateDatabase) -> pa.Table:
        self.catalog.create_database(c.name,
                                     ignore_if_exists=c.if_not_exists)
        return _result(["OK"])

    def _exec_create_view(self, c: ast.CreateView) -> pa.Table:
        from paimon_tpu.catalog.view import View
        ident = self._ident(c.name)
        if c.or_replace:
            self.catalog.drop_view(ident, ignore_if_not_exists=True)
        self.catalog.create_view(
            ident, View(query=c.query_text, comment=c.comment,
                        options={"default-database": ident.database}))
        return _result(["OK"])

    def _exec_drop_view(self, d: ast.DropView) -> pa.Table:
        self.catalog.drop_view(self._ident(d.name),
                               ignore_if_not_exists=d.if_exists)
        return _result(["OK"])

    def _exec_show_views(self, s: ast.ShowViews) -> pa.Table:
        db = s.database or self.database
        return pa.table({"view_name":
                         pa.array(sorted(self.catalog.list_views(db)),
                                  pa.string())})

    def _exec_create_function(self, c: ast.CreateFunction) -> pa.Table:
        from paimon_tpu.catalog.function import (Function,
                                                 FunctionDefinition)
        ident_name = c.name.split(".")[-1].lower()
        if ident_name in _BUILTIN_FUNCS:
            raise SQLError(f"cannot create function {ident_name!r}: "
                           f"built-in functions cannot be shadowed")
        # validate the body parses as an expression now, not at call
        _parse_expr_full(c.body)
        for _, tstr in c.params:
            parse_data_type(tstr)
        if c.return_type:
            parse_data_type(c.return_type)
        ident = self._ident(c.name)
        if c.or_replace:
            self.catalog.drop_function(ident, ignore_if_not_exists=True)
        fn = Function(
            input_params=list(c.params), return_type=c.return_type,
            definitions={"sql": FunctionDefinition(
                "sql", definition=c.body)},
            comment=c.comment)
        self.catalog.create_function(ident, fn)
        return _result(["OK"])

    def _exec_drop_function(self, d: ast.DropFunction) -> pa.Table:
        self.catalog.drop_function(self._ident(d.name),
                                   ignore_if_not_exists=d.if_exists)
        return _result(["OK"])

    def _exec_show_functions(self, s: ast.ShowFunctions) -> pa.Table:
        db = s.database or self.database
        return pa.table({"function_name": pa.array(
            sorted(self.catalog.list_functions(db)), pa.string())})

    def _exec_drop_table(self, d: ast.DropTable) -> pa.Table:
        self.catalog.drop_table(self._ident(d.table),
                                ignore_if_not_exists=d.if_exists)
        return _result(["OK"])

    def _exec_drop_database(self, d: ast.DropDatabase) -> pa.Table:
        self.catalog.drop_database(d.name,
                                   ignore_if_not_exists=d.if_exists)
        return _result(["OK"])

    def _exec_show_tables(self, s: ast.ShowTables) -> pa.Table:
        db = s.database or self.database
        return pa.table({"table_name":
                         pa.array(sorted(self.catalog.list_tables(db)))})

    def _exec_show_databases(self, s: ast.ShowDatabases) -> pa.Table:
        return pa.table({"database_name":
                         pa.array(sorted(self.catalog.list_databases()))})

    def _exec_show_create(self, s: ast.ShowCreateTable) -> pa.Table:
        table = self.catalog.get_table(self._ident(s.table))
        schema = table.schema
        lines = [f"CREATE TABLE `{s.table}` ("]
        defs = []
        for f in schema.fields:
            d = f"  `{f.name}` {f.type}"
            if getattr(f, "description", None):
                d += f" COMMENT '{f.description}'"
            defs.append(d)
        if schema.primary_keys:
            defs.append("  PRIMARY KEY (" +
                        ", ".join(f"`{k}`" for k in schema.primary_keys) +
                        ") NOT ENFORCED")
        lines.append(",\n".join(defs))
        lines.append(")")
        if schema.partition_keys:
            lines.append("PARTITIONED BY (" +
                         ", ".join(f"`{k}`"
                                   for k in schema.partition_keys) + ")")
        if schema.options:
            opts = ",\n".join(f"  '{k}' = '{v}'"
                              for k, v in sorted(schema.options.items()))
            lines.append(f"WITH (\n{opts}\n)")
        return _result(["\n".join(lines)], "create_table")

    def _exec_describe(self, d: ast.Describe) -> pa.Table:
        table = self.catalog.get_table(self._ident(d.table))
        schema = table.schema
        pk = set(schema.primary_keys or [])
        part = set(schema.partition_keys or [])
        return pa.table({
            "name": pa.array([f.name for f in schema.fields]),
            "type": pa.array([str(f.type) for f in schema.fields]),
            "key": pa.array(["PRI" if f.name in pk else
                             ("PAR" if f.name in part else "")
                             for f in schema.fields]),
            "comment": pa.array([getattr(f, "description", None)
                                 for f in schema.fields], pa.string()),
        })

    def _exec_use(self, u: ast.Use) -> pa.Table:
        if not self.catalog.database_exists(u.database):
            raise SQLError(f"database {u.database!r} does not exist")
        self.database = u.database
        return _result(["OK"])

    def _exec_alter(self, a: ast.AlterTable) -> pa.Table:
        ident = self._ident(a.table)
        changes: List[SchemaChange] = []
        if a.action == "set-options":
            changes = [SchemaChange.set_option(k, v)
                       for k, v in a.payload.items()]
        elif a.action == "reset":
            changes = [SchemaChange.remove_option(k) for k in a.payload]
        elif a.action == "add-column":
            cd: ast.ColumnDef = a.payload
            changes = [SchemaChange.add_column(cd.name,
                                               parse_data_type(cd.type_str))]
        elif a.action == "drop-column":
            changes = [SchemaChange.drop_column(a.payload)]
        elif a.action == "rename-column":
            changes = [SchemaChange.rename_column(*a.payload)]
        self.catalog.alter_table(ident, changes)
        return _result(["OK"])

    # -- CALL procedures ----------------------------------------------------
    def _exec_call(self, c: ast.Call) -> pa.Table:
        proc = c.procedure.lower()
        if proc.startswith("sys."):
            proc = proc[4:]
        args = list(c.args)
        if not args:
            raise SQLError("CALL procedures take the table name first")
        if proc == "migrate_table":
            # CALL sys.migrate_table('/path/to/hive_dir', 'db.t'
            #   [, 'parquet'[, move]]) — reference
            # MigrateTableProcedure (ours takes the source DIRECTORY;
            # no Hive metastore exists in this environment)
            from paimon_tpu.maintenance.migrate import migrate_table
            if len(args) < 2:
                raise SQLError("migrate_table needs (source_dir, "
                               "'db.table')")
            fmt = str(args[2]) if len(args) > 2 else "parquet"
            move = str(args[3]).lower() in ("true", "1") \
                if len(args) > 3 else True
            t = migrate_table(self.catalog, str(args[0]),
                              self._ident(str(args[1])),
                              file_format=fmt, move=move)
            snap = t.latest_snapshot()
            return _result([f"migrated {snap.total_record_count} rows "
                            f"into {args[1]}"])
        if proc == "compact_database":
            # reference CompactDatabaseProcedure: compact every table
            # in the database (full when the second arg says so)
            db = str(args[0])
            full = len(args) > 1 and str(args[1]).lower() in ("true",
                                                              "1",
                                                              "full")
            done = []
            for name in self.catalog.list_tables(db):
                t = self.catalog.get_table(f"{db}.{name}")
                sid = t.compact(full=full)
                if sid is not None:
                    done.append(f"{name}@{sid}")
            return _result(
                [f"{len(done)} tables compacted"] + done)
        if proc == "clone":
            # CALL sys.clone('db.src', 'db.dst') — reference
            # CloneProcedure: independent copy of the current state
            from paimon_tpu.maintenance.clone import clone_table
            if len(args) < 2:
                raise SQLError("clone needs (source, target)")
            t = clone_table(self.catalog, self._ident(str(args[0])),
                            self._ident(str(args[1])))
            snap = t.latest_snapshot()
            rows = snap.total_record_count if snap else 0
            return _result([f"cloned {rows} rows into {args[1]}"])
        table = self.catalog.get_table(self._ident(str(args[0])))
        rest = args[1:]
        if proc == "compact":
            sid = table.compact(full=bool(rest[0]) if rest else False)
            return _result([f"snapshot {sid}" if sid else "nothing to do"])
        if proc == "sort_compact":
            order_by = [c.strip() for c in str(rest[0]).split(",")]
            strategy = str(rest[1]) if len(rest) > 1 else "order"
            sid = table.sort_compact(order_by, strategy=strategy)
            return _result([f"snapshot {sid}" if sid else "nothing to do"])
        if proc == "create_tag":
            table.create_tag(str(rest[0]),
                             int(rest[1]) if len(rest) > 1 else None)
            return _result(["OK"])
        if proc == "delete_tag":
            table.delete_tag(str(rest[0]))
            return _result(["OK"])
        if proc == "create_branch":
            table.create_branch(str(rest[0]),
                                str(rest[1]) if len(rest) > 1 else None)
            return _result(["OK"])
        if proc == "delete_branch":
            table.delete_branch(str(rest[0]))
            return _result(["OK"])
        if proc == "fast_forward":
            table.fast_forward(str(rest[0]))
            return _result(["OK"])
        if proc == "rollback_to":
            table.rollback_to(int(rest[0]))
            return _result(["OK"])
        if proc == "expire_snapshots":
            n = table.expire_snapshots(
                retain_max=int(rest[0]) if rest else None)
            return _result([f"{n or 0} snapshots expired"])
        if proc == "expire_partitions":
            n = table.expire_partitions(
                expiration_ms=int(rest[0]) if rest else None)
            return _result([f"{n or 0} partitions expired"])
        if proc == "remove_orphan_files":
            n = table.remove_orphan_files(
                older_than_ms=int(rest[0]) if rest else None)
            return _result([f"{n or 0} orphan files removed"])
        if proc == "rescale":
            table.rescale_buckets(int(rest[0]))
            return _result(["OK"])
        if proc == "analyze":
            n = table.analyze()
            return _result([f"{n or 0} rows analyzed"])
        if proc == "full_text_search":
            # CALL sys.full_text_search('db.t', 'col', 'query'[, k])
            # (reference flink/procedure/FullTextSearchProcedure.java)
            from paimon_tpu.index.fulltext import full_text_search
            return full_text_search(table, str(rest[0]), str(rest[1]),
                                    k=int(rest[2]) if len(rest) > 2
                                    else 10)
        if proc == "vector_search":
            # CALL sys.vector_search('db.t', 'col', '0.1,0.2,...'[, k])
            # (reference flink/procedure/VectorSearchProcedure.java)
            from paimon_tpu.vector import vector_search
            vec = [float(x) for x in str(rest[1]).split(",")]
            return vector_search(table, str(rest[0]), vec,
                                 k=int(rest[2]) if len(rest) > 2 else 10)
        if proc == "hybrid_search":
            # CALL sys.hybrid_search('db.t', 'vcol', '0.1,...', 'tcol',
            #                        'terms'[, k[, ranker]])
            from paimon_tpu.vector import hybrid_search
            vec = [float(x) for x in str(rest[1]).split(",")]
            kk = int(rest[4]) if len(rest) > 4 else 10
            return hybrid_search(
                table,
                routes=[{"type": "vector", "column": str(rest[0]),
                         "query": vec, "limit": kk},
                        {"type": "text", "column": str(rest[2]),
                         "query": str(rest[3]), "limit": kk}],
                k=kk,
                ranker=str(rest[5]) if len(rest) > 5 else "rrf")
        if proc == "create_vector_index":
            # CALL sys.create_vector_index('db.t', 'col'[, m[, metric
            #   [, kind]]]) — kind in ivfpq|ivfsq|hnsw — builds +
            # persists the index in the table layout (reference
            # NativeVectorIndexLoader.java:28 + IvfHnswSq/Flat
            # factories)
            from paimon_tpu.vector.ann import PersistedVectorIndex
            p = PersistedVectorIndex(table, str(rest[0]))
            kind = str(rest[3]) if len(rest) > 3 else "ivfpq"
            idx = p.build(m=int(rest[1]) if len(rest) > 1 else 8,
                          metric=str(rest[2]) if len(rest) > 2
                          else "l2", kind=kind)
            mem = (f", {idx.memory_bytes()} bytes resident"
                   if hasattr(idx, "memory_bytes") else "")
            return _result([f"{kind} index built: {len(idx)} vectors"
                            f"{mem}"])
        if proc == "mark_partition_done":
            # reference flink/procedure/MarkPartitionDoneProcedure.java:
            # CALL sys.mark_partition_done('db.t', 'dt=2026-07-29', ...)
            if not rest:
                raise SQLError("mark_partition_done needs partitions")
            marked = table.mark_partitions_done([str(p) for p in rest])
            return _result([f"{len(marked)} partitions marked done"])
        if proc == "expire_changelogs":
            # reference flink/procedure/ExpireChangelogsProcedure
            from paimon_tpu.maintenance.expire import expire_changelogs
            r = expire_changelogs(
                table,
                retain_max=int(rest[0]) if len(rest) > 0 else None,
                retain_min=int(rest[1]) if len(rest) > 1 else None)
            return _result([f"{len(r.expired_snapshots)} changelogs "
                            f"expired"])
        if proc == "expire_tags":
            # reference flink/procedure/ExpireTagsProcedure: drop tags
            # whose tag.time-retained elapsed
            expired = table.tag_manager.expire_tags()
            return _result([f"{len(expired)} tags expired"] +
                           [str(t) for t in expired])
        if proc == "rename_tag":
            # reference flink/procedure/RenameTagProcedure
            if len(rest) != 2:
                raise SQLError("rename_tag needs (old, new)")
            old, new = str(rest[0]), str(rest[1])
            table.tag_manager.rename_tag(old, new)
            return _result([f"tag {old} renamed to {new}"])
        if proc == "clear_consumers":
            # reference flink/procedure/ClearConsumersProcedure:
            # optional regex filter over consumer ids
            import re as _re
            cm = table.consumer_manager
            pattern = _re.compile(str(rest[0])) if rest else None
            cleared = []
            for cid in list(cm.consumers()):
                if pattern is None or pattern.fullmatch(cid):
                    cm.delete_consumer(cid)
                    cleared.append(cid)
            return _result([f"{len(cleared)} consumers cleared"])
        def _scan_snapshots():
            """Yield existing snapshots, earliest to latest (expired
            ids are skipped)."""
            sm = table.snapshot_manager
            for sid in range(sm.earliest_snapshot_id() or 1,
                             (sm.latest_snapshot_id() or 0) + 1):
                try:
                    yield sm.snapshot(sid)
                except FileNotFoundError:
                    continue

        if proc == "create_tag_from_watermark":
            # reference CreateTagFromWatermarkProcedure: first snapshot
            # whose watermark reached the bound
            if len(rest) < 2:
                raise SQLError(
                    "create_tag_from_watermark needs (tag, watermark)")
            bound = int(rest[1])
            pick = None
            for s_ in _scan_snapshots():
                if s_.watermark is not None and s_.watermark >= bound:
                    pick = s_
                    break              # watermarks only advance
            if pick is None:
                raise SQLError(f"no snapshot with watermark >= {bound}")
            table.create_tag(str(rest[0]), snapshot_id=pick.id)
            return _result([f"tag {rest[0]} -> snapshot {pick.id} "
                            f"(watermark {pick.watermark})"])
        if proc in ("rollback_to_timestamp", "create_tag_from_timestamp"):
            # reference RollbackToTimestampProcedure /
            # CreateTagFromTimestampProcedure: latest snapshot with
            # time_millis <= ts
            need = 1 if proc.startswith("rollback") else 2
            if len(rest) < need:
                raise SQLError(f"{proc} needs a timestamp (millis)"
                               if need == 1
                               else f"{proc} needs (tag, millis)")
            ts = int(rest[-1])
            best = None
            for s in _scan_snapshots():
                if s.time_millis <= ts:
                    best = s
                else:
                    break          # commit times are non-decreasing
            if best is None:
                raise SQLError(f"no snapshot at or before {ts}")
            if proc == "rollback_to_timestamp":
                table.rollback_to(best.id)
                return _result([f"rolled back to snapshot {best.id}"])
            table.create_tag(str(rest[0]), snapshot_id=best.id)
            return _result([f"tag {rest[0]} -> snapshot {best.id}"])
        if proc == "remove_unexisting_files":
            # reference RemoveUnexistingFilesProcedure: reconcile
            # manifests with storage after out-of-band deletions
            from paimon_tpu.maintenance.repair import (
                remove_unexisting_files,
            )
            dry = bool(rest) and str(rest[0]).lower() in ("true", "1")
            gone = remove_unexisting_files(table, dry_run=dry)
            verb = "missing" if dry else "removed"
            return _result([f"{len(gone)} files {verb}"] + gone)
        if proc == "purge_files":
            # reference PurgeFilesProcedure: drop all live data in one
            # OVERWRITE snapshot (time travel to earlier snapshots
            # keeps working until expiry)
            _purge_all(table)
            return _result(["table purged"])
        if proc == "remove_unexisting_manifests":
            # reference RemoveUnexistingManifestsProcedure
            from paimon_tpu.maintenance.repair import (
                remove_unexisting_manifests,
            )
            sid = remove_unexisting_manifests(table)
            return _result(
                ["table has no snapshots; nothing to repair"]
                if sid is None
                else [f"manifest chain repaired in snapshot {sid}"])
        if proc == "rename_branch":
            # reference RenameBranchProcedure
            if len(rest) != 2:
                raise SQLError("rename_branch needs (old, new)")
            table.rename_branch(str(rest[0]), str(rest[1]))
            return _result([f"branch {rest[0]} renamed to {rest[1]}"])
        if proc == "rewrite_file_index":
            # reference RewriteFileIndexProcedure: retrofit per-file
            # indexes after enabling file-index.* on an existing table
            from paimon_tpu.maintenance.repair import rewrite_file_index
            force = bool(rest) and str(rest[0]).lower() in ("true", "1")
            n = rewrite_file_index(table, force=force)
            return _result([f"{n} files indexed"])
        if proc == "compact_manifest":
            # reference CompactManifestProcedure
            from paimon_tpu.maintenance.repair import compact_manifests
            sid = compact_manifests(table)
            return _result(
                ["table has no snapshots; nothing to compact"]
                if sid is None
                else [f"manifests compacted in snapshot {sid}"])
        if proc == "trigger_tag_automatic_creation":
            # reference TriggerTagAutomaticCreationProcedure
            from paimon_tpu.maintenance.tag_auto import maybe_create_tags
            created = maybe_create_tags(table)
            return _result([f"{len(created)} tags created"] + created)
        raise SQLError(f"unknown procedure {c.procedure!r}")


class _WindowSegments:
    """Shared per-OVER-spec machinery: the partition/order sort, the
    segment (partition) and peer (tie-group) structure in sorted order,
    and scatter/gather back to original row order."""

    def __init__(self, comp: Compiler, w, n: int):
        import numpy as np

        self.n = n
        self.has_order = bool(w.order_by)
        cols: Dict[str, Any] = {}
        sort_keys = []
        for i, pe in enumerate(w.partition_by):
            cols[f"__wp{i}"] = comp.as_array(pe)
            sort_keys.append((f"__wp{i}", "ascending", "at_end"))
        for j, (oe, asc) in enumerate(w.order_by):
            cols[f"__wo{j}"] = comp.as_array(oe)
            sort_keys.append(
                (f"__wo{j}", "ascending" if asc else "descending",
                 "at_end"))
        cols["__wi"] = pa.array(np.arange(n))
        sort_keys.append(("__wi", "ascending", "at_end"))   # stable
        self._st = pa.table(cols)
        self.order = np.asarray(_sort_indices(self._st, sort_keys))

        seg_start = np.zeros(n, dtype=bool)
        if n:
            seg_start[0] = True
        if w.partition_by and n > 1:
            seg_start[1:] |= self._changed(
                [f"__wp{i}" for i in range(len(w.partition_by))])
        self.seg_start = seg_start
        pos = np.arange(n)
        self.seg_first = np.maximum.accumulate(
            np.where(seg_start, pos, 0))
        self.starts_idx = np.flatnonzero(seg_start)
        self.seg_id = np.cumsum(seg_start) - 1
        ends = np.append(self.starts_idx[1:] - 1, n - 1) if n else \
            np.zeros(0, dtype=np.int64)
        self.seg_last = ends[self.seg_id] if n else ends

        # peer groups: rows equal on (partition, order) keys; without
        # ORDER BY the whole partition is one peer group
        kc = seg_start.copy()
        if self.has_order and n > 1:
            kc[1:] |= self._changed(
                [f"__wo{j}" for j in range(len(w.order_by))])
        self.key_change = kc
        gstarts = np.flatnonzero(kc)
        gid = np.cumsum(kc) - 1
        gends = np.append(gstarts[1:] - 1, n - 1) if n else gstarts
        self.peer_last = gends[gid] if n else gends

    def _changed(self, names) -> "Any":
        """bool[n-1]: sorted row i+1 differs from i on any named column
        (nulls compare equal to nulls)."""
        import numpy as np

        n = self.n
        out = np.zeros(max(n - 1, 0), dtype=bool)
        for name in names:
            colv = self._st.column(name).take(pa.array(self.order))
            a, b = colv.slice(0, n - 1), colv.slice(1)
            eq = np.asarray(pc.fill_null(pc.equal(a, b), False))
            nulls = np.asarray(pc.is_null(colv))
            eq |= nulls[:-1] & nulls[1:]
            out |= ~eq
        return out

    def running(self, cum):
        """RANGE-frame running value from a global cumsum over sorted
        rows: the cumulative through the row's LAST PEER, minus
        everything before its partition."""
        import numpy as np

        prev = np.where(self.seg_first > 0,
                        cum[np.maximum(self.seg_first - 1, 0)], 0.0)
        return cum[self.peer_last] - prev

    def scatter(self, sorted_res, null_mask=None):
        """sorted-order values -> arrow array in original row order."""
        import numpy as np

        out = np.empty(self.n, dtype=np.asarray(sorted_res).dtype)
        out[self.order] = sorted_res
        if null_mask is None:
            return pa.array(out)
        m = np.empty(self.n, dtype=bool)
        m[self.order] = null_mask
        return pa.array(out, mask=m)

    def gather_arg(self, comp: Compiler, f, src_sorted, default):
        """Type-preserving gather of f's first argument by
        original-table row index (sorted-order indices; -1 = out of
        frame -> `default` or null)."""
        import numpy as np

        if not f.args:
            raise SQLError(f"{f.name}() needs an argument")
        base = comp.as_array(f.args[0])
        if isinstance(base, pa.ChunkedArray):
            base = base.combine_chunks()
        src = np.empty(self.n, dtype=np.int64)
        src[self.order] = src_sorted
        taken = base.take(pa.array(np.where(src < 0, 0, src)))
        missing = pa.array(src < 0)
        if default is not None:
            return pc.if_else(missing, pa.scalar(default, base.type),
                              taken)
        return pc.if_else(missing, pa.nulls(self.n, base.type), taken)


# ---------------------------------------------------------------------------
# small AST utilities
# ---------------------------------------------------------------------------

def _ordinal(v: int, n: int) -> int:
    """Validate a 1-based positional reference (ORDER BY 2, GROUP BY 1)."""
    if not 1 <= v <= n:
        raise SQLError(f"positional reference {v} out of range 1..{n}")
    return v


def _probe_scope(cols: List[str], alias: str) -> Scope:
    """A zero-row Scope for name resolution during predicate
    conversion (pushdown / DELETE), shared by both conversion sites."""
    return Scope(pa.table({f"{alias}.{c}": pa.array([], pa.null())
                           for c in cols}),
                 [f"{alias}.{c}" for c in cols])


def _split_conjuncts(e) -> List[Any]:
    if isinstance(e, ast.Binary) and e.op == "AND":
        return _split_conjuncts(e.left) + _split_conjuncts(e.right)
    return [e]


def _equi_pair(e, probe: Scope, left: Scope, right: Scope
               ) -> Optional[Tuple[str, str]]:
    """`a.x = b.y` with one side in each scope -> (left_q, right_q)."""
    if not (isinstance(e, ast.Binary) and e.op == "=" and
            isinstance(e.left, ast.Column) and
            isinstance(e.right, ast.Column)):
        return None
    try:
        lq = probe.resolve(e.left)
        rq = probe.resolve(e.right)
    except SQLError:
        return None
    if lq in left.table.column_names and rq in right.table.column_names:
        return (lq, rq)
    if rq in left.table.column_names and lq in right.table.column_names:
        return (rq, lq)
    return None


def _parse_expr_full(text: str):
    """Parse a COMPLETE expression (trailing garbage is an error —
    Parser.expr() alone would silently stop early)."""
    from paimon_tpu.sql.parser import Parser
    p = Parser(text)
    e = p.expr()
    if p.peek().kind != "EOF":
        raise SQLError(f"trailing input in expression at "
                       f"{p.peek().pos}: {text!r}")
    return e


def _transform(e, fn):
    """Bottom-up AST rewrite: fn(node) returns a replacement (or the
    node); children are rebuilt first."""
    import copy as _copy

    if isinstance(e, ast.Func):
        e = ast.Func(e.name, [_transform(a, fn) for a in e.args],
                     e.distinct,
                     None if e.over is None else ast.Window(
                         [_transform(p, fn)
                          for p in e.over.partition_by],
                         [(_transform(o, fn), asc)
                          for o, asc in e.over.order_by]))
    elif isinstance(e, ast.Binary):
        e = ast.Binary(e.op, _transform(e.left, fn),
                       _transform(e.right, fn))
    elif isinstance(e, ast.Unary):
        e = ast.Unary(e.op, _transform(e.operand, fn))
    elif isinstance(e, ast.Case):
        e = ast.Case([(_transform(c, fn), _transform(v, fn))
                      for c, v in e.whens],
                     None if e.default is None
                     else _transform(e.default, fn))
    elif isinstance(e, ast.Cast):
        e = ast.Cast(_transform(e.expr, fn), e.type_str)
    elif isinstance(e, ast.IsNull):
        e = ast.IsNull(_transform(e.expr, fn), e.negated)
    elif isinstance(e, ast.LikeExpr):
        e = ast.LikeExpr(_transform(e.expr, fn), e.pattern, e.negated)
    elif isinstance(e, ast.InList):
        e = ast.InList(_transform(e.expr, fn),
                       [_transform(v, fn) for v in e.values], e.negated)
    elif isinstance(e, ast.InSubquery):
        # the rewrite (UDF expansion, parameter substitution) applies
        # inside the subquery's expression positions too
        _rewrite_select_exprs(e.select, fn)
        e = ast.InSubquery(_transform(e.expr, fn), e.select, e.negated)
    elif isinstance(e, ast.ScalarSubquery):
        _rewrite_select_exprs(e.select, fn)
    elif isinstance(e, ast.ExistsSubquery):
        _rewrite_select_exprs(e.select, fn)
    elif isinstance(e, ast.BetweenExpr):
        e = ast.BetweenExpr(_transform(e.expr, fn),
                            _transform(e.lo, fn), _transform(e.hi, fn),
                            e.negated)
    else:
        e = _copy.copy(e) if isinstance(e, (ast.Column, ast.Literal,
                                            ast.Star)) else e
    return fn(e)


def _substitute_params(body, bindings: Dict[str, Any]):
    def rep(node):
        if isinstance(node, ast.Column) and node.qualifier is None and \
                node.name in bindings:
            return bindings[node.name]
        return node
    return _transform(body, rep)


def _rewrite_select_exprs(sel: "ast.Select", fn) -> None:
    """Apply an expression rewrite to every expression position of a
    Select tree, in place (recursing into subqueries/unions)."""
    sel.items = [ast.SelectItem(_transform(i.expr, fn), i.alias)
                 for i in sel.items]
    if sel.where is not None:
        sel.where = _transform(sel.where, fn)
    sel.group_by = [_transform(g, fn) for g in sel.group_by]
    if sel.having is not None:
        sel.having = _transform(sel.having, fn)
    sel.order_by = [(_transform(e, fn), asc, pl)
                    for e, asc, pl in sel.order_by]
    for j in sel.joins:
        if j.condition is not None:
            j.condition = _transform(j.condition, fn)
        if isinstance(j.right, ast.SubqueryRef):
            _rewrite_select_exprs(j.right.select, fn)
    if isinstance(sel.from_, ast.SubqueryRef):
        _rewrite_select_exprs(sel.from_.select, fn)
    if sel.union_all is not None:
        _rewrite_select_exprs(sel.union_all, fn)


def _purge_all(table) -> None:
    """One empty OVERWRITE commit dropping every live file (TRUNCATE
    TABLE and sys.purge_files share this)."""
    wb = table.new_batch_write_builder().with_overwrite()
    w = wb.new_write()
    try:
        wb.new_commit().commit(w.prepare_commit())
    finally:
        w.close()


def _hashable(v):
    if isinstance(v, list):
        return tuple(_hashable(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((k, _hashable(x)) for k, x in v.items()))
    return v


def _row_keys(t: pa.Table):
    """Positional, hashable row keys for set-op comparison."""
    cols = [t.column(i).to_pylist() for i in range(t.num_columns)]
    for row in zip(*cols):
        yield tuple(_hashable(v) for v in row)


def _find_funcs(e, pred) -> List[ast.Func]:
    """Func nodes matching `pred`, top-down; a matched node's arguments
    are not descended into (no nested aggregates/windows)."""
    out: List[ast.Func] = []

    def walk(x):
        if isinstance(x, ast.Func):
            if pred(x):
                out.append(x)
                return
            for a in x.args:
                walk(a)
        elif isinstance(x, ast.Binary):
            walk(x.left)
            walk(x.right)
        elif isinstance(x, ast.Unary):
            walk(x.operand)
        elif isinstance(x, ast.Case):
            for c, v in x.whens:
                walk(c)
                walk(v)
            if x.default is not None:
                walk(x.default)
        elif isinstance(x, (ast.Cast, ast.IsNull, ast.LikeExpr,
                            ast.InList)):
            walk(x.expr)
        elif isinstance(x, ast.BetweenExpr):
            walk(x.expr)
            walk(x.lo)
            walk(x.hi)
    walk(e)
    return out


def _find_windows(e) -> List[ast.Func]:
    """Window-function nodes (any func with an OVER clause)."""
    return _find_funcs(e, lambda f: f.over is not None)


def _find_aggs(e) -> List[ast.Func]:
    """Plain aggregate calls (windowed aggregates are NOT aggregates)."""
    return _find_funcs(e, lambda f: f.name in _AGG_FUNCS and
                       f.over is None)


def _display_name(e) -> str:
    if isinstance(e, ast.Column):
        return e.name
    if isinstance(e, ast.Func):
        return e.name
    if isinstance(e, ast.Literal):
        return str(e.value)
    return "expr"


def _dedup(names: List[str]) -> List[str]:
    seen: Dict[str, int] = {}
    out = []
    for n in names:
        if n in seen:
            seen[n] += 1
            out.append(f"{n}_{seen[n]}")
        else:
            seen[n] = 0
            out.append(n)
    return out
