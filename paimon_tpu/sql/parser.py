"""SQL tokenizer + recursive-descent parser.

Grammar coverage (what the reference surfaces through its SQL layers —
DataFusion in pypaimon, Flink/Spark SQL on the JVM; see
pypaimon/cli/cli_sql.py for the statement set the CLI drives):

  SELECT [DISTINCT] items FROM ref [JOIN ...] [WHERE] [GROUP BY]
      [HAVING] [ORDER BY] [LIMIT [OFFSET]] [UNION ALL ...]
  INSERT [OVERWRITE] INTO t [(cols)] VALUES (...) | SELECT ...
  CREATE TABLE [IF NOT EXISTS] t (col TYPE [NOT NULL] [COMMENT '..'], ..
      [, PRIMARY KEY (..)]) [PARTITIONED BY (..)] [WITH ('k'='v', ..)]
  CREATE DATABASE / DROP TABLE|DATABASE / SHOW / DESCRIBE / USE
  DELETE FROM t WHERE ..     UPDATE t SET c = e, .. [WHERE ..]
  ALTER TABLE t SET|RESET|ADD COLUMN|DROP COLUMN|RENAME COLUMN
  CALL sys.proc(args)        EXPLAIN SELECT ..

Time travel on a table reference: `t VERSION AS OF 3`,
`t VERSION AS OF 'tag'`, `t FOR SYSTEM_TIME AS OF TIMESTAMP '...'|millis`.
"""

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

# ---------------------------------------------------------------------------
# Tokenizer
# ---------------------------------------------------------------------------

_KEYWORDS = {
    "SELECT", "DISTINCT", "FROM", "WHERE", "GROUP", "BY", "HAVING", "ORDER",
    "LIMIT", "OFFSET", "AS", "AND", "OR", "NOT", "NULL", "IS", "IN",
    "BETWEEN", "LIKE", "TRUE", "FALSE", "CASE", "WHEN", "THEN", "ELSE",
    "END", "CAST", "JOIN", "INNER", "LEFT", "RIGHT", "FULL", "OUTER",
    "CROSS", "ON", "UNION", "ALL", "INTERSECT", "EXCEPT", "ASC", "DESC",
    "NULLS", "FIRST", "LAST",
    "INSERT", "INTO", "OVERWRITE", "VALUES", "CREATE", "TABLE", "DATABASE",
    "IF", "EXISTS", "PRIMARY", "KEY", "ENFORCED", "PARTITIONED", "WITH",
    "COMMENT", "DROP", "SHOW", "TABLES", "DATABASES", "DESCRIBE", "DESC",
    "USE", "DELETE", "UPDATE", "SET", "RESET", "ALTER", "COLUMN", "RENAME",
    "TO", "CALL", "EXPLAIN", "VERSION", "OF", "FOR", "SYSTEM_TIME",
    "TIMESTAMP", "ADD", "TRUNCATE", "MERGE", "USING", "MATCHED", "THEN",
}


@dataclass
class Token:
    kind: str          # KEYWORD | IDENT | NUMBER | STRING | OP | EOF
    value: Any
    pos: int


class SQLError(ValueError):
    pass


def tokenize(text: str) -> List[Token]:
    toks: List[Token] = []
    i, n = 0, len(text)
    while i < n:
        c = text[i]
        if c.isspace():
            i += 1
            continue
        if text.startswith("--", i):
            j = text.find("\n", i)
            i = n if j < 0 else j + 1
            continue
        if text.startswith("/*", i):
            j = text.find("*/", i + 2)
            if j < 0:
                raise SQLError(f"unterminated comment at {i}")
            i = j + 2
            continue
        if c == "'":
            j, buf = i + 1, []
            while j < n:
                if text[j] == "'" and j + 1 < n and text[j + 1] == "'":
                    buf.append("'")
                    j += 2
                elif text[j] == "'":
                    break
                else:
                    buf.append(text[j])
                    j += 1
            if j >= n:
                raise SQLError(f"unterminated string at {i}")
            toks.append(Token("STRING", "".join(buf), i))
            i = j + 1
            continue
        if c == '`' or c == '"':
            j = text.find(c, i + 1)
            if j < 0:
                raise SQLError(f"unterminated quoted identifier at {i}")
            toks.append(Token("IDENT", text[i + 1:j], i))
            i = j + 1
            continue
        if c.isdigit() or (c == "." and i + 1 < n and text[i + 1].isdigit()):
            j = i
            seen_dot = seen_exp = False
            while j < n and (text[j].isdigit() or text[j] in ".eE+-"):
                if text[j] == ".":
                    if seen_dot:
                        break
                    seen_dot = True
                elif text[j] in "eE":
                    if seen_exp or j + 1 >= n or text[j + 1] not in \
                            "0123456789+-":
                        break
                    seen_exp = True
                elif text[j] in "+-" and text[j - 1] not in "eE":
                    break
                j += 1
            lit = text[i:j]
            toks.append(Token("NUMBER",
                              float(lit) if seen_dot or seen_exp
                              else int(lit), i))
            i = j
            continue
        if c.isalpha() or c == "_":
            # `$` allowed inside identifiers for system tables
            # (t$snapshots — reference table/system/SystemTableLoader.java)
            j = i
            while j < n and (text[j].isalnum() or text[j] in "_$"):
                j += 1
            word = text[i:j]
            up = word.upper()
            if up in _KEYWORDS:
                toks.append(Token("KEYWORD", up, i))
            else:
                toks.append(Token("IDENT", word, i))
            i = j
            continue
        for op in ("<>", "!=", ">=", "<=", "||"):
            if text.startswith(op, i):
                toks.append(Token("OP", "<>" if op == "!=" else op, i))
                i += 2
                break
        else:
            if c in "+-*/%(),.=<>;[]":
                toks.append(Token("OP", c, i))
                i += 1
            else:
                raise SQLError(f"unexpected character {c!r} at {i}")
    toks.append(Token("EOF", None, n))
    return toks


# ---------------------------------------------------------------------------
# AST
# ---------------------------------------------------------------------------

@dataclass
class Literal:
    value: Any


@dataclass
class Column:
    name: str
    qualifier: Optional[str] = None

    def key(self):
        return f"{self.qualifier}.{self.name}" if self.qualifier \
            else self.name


@dataclass
class Star:
    qualifier: Optional[str] = None


@dataclass
class Unary:
    op: str            # NOT | NEG
    operand: Any


@dataclass
class Binary:
    op: str            # + - * / % = <> < <= > >= AND OR ||
    left: Any
    right: Any


@dataclass
class Window:
    partition_by: List[Any] = field(default_factory=list)
    order_by: List[Tuple[Any, bool]] = field(default_factory=list)


@dataclass
class Func:
    name: str
    args: List[Any]
    distinct: bool = False
    over: Optional[Window] = None       # window function when set


@dataclass
class Case:
    whens: List[Tuple[Any, Any]]
    default: Optional[Any]


@dataclass
class Cast:
    expr: Any
    type_str: str


@dataclass
class InList:
    expr: Any
    values: List[Any]
    negated: bool = False


@dataclass
class InSubquery:
    """x [NOT] IN (SELECT ...) — uncorrelated; materialized to an
    InList by the executor before evaluation."""
    expr: Any
    select: Any
    negated: bool = False


@dataclass
class ScalarSubquery:
    """(SELECT expr FROM ...) in expression position — uncorrelated;
    must return one column and at most one row (NULL when empty)."""
    select: Any


@dataclass
class ExistsSubquery:
    """[NOT] EXISTS (SELECT ...). Uncorrelated: evaluated once.
    Correlated on a single outer-column equality: decorrelated to a
    semi-join-shaped IN by the executor."""
    select: Any
    negated: bool = False


@dataclass
class BetweenExpr:
    expr: Any
    lo: Any
    hi: Any
    negated: bool = False


@dataclass
class LikeExpr:
    expr: Any
    pattern: str
    negated: bool = False


@dataclass
class IsNull:
    expr: Any
    negated: bool = False


@dataclass
class SelectItem:
    expr: Any
    alias: Optional[str] = None


@dataclass
class TableRef:
    name: str                      # possibly db-qualified "db.t"
    alias: Optional[str] = None
    snapshot_id: Optional[int] = None
    tag: Optional[str] = None
    timestamp_ms: Optional[int] = None


@dataclass
class Truncate:
    table: str


@dataclass
class MergeClause:
    """WHEN [NOT] MATCHED [AND cond] THEN action."""
    matched: bool
    condition: Optional[Any]
    action: str                    # update | delete | insert
    assignments: List[Tuple[str, Any]] = field(default_factory=list)
    insert_columns: Optional[List[str]] = None
    insert_values: List[Any] = field(default_factory=list)


@dataclass
class MergeInto:
    target: str
    target_alias: Optional[str]
    source: Any                    # TableRef | SubqueryRef
    on: Any
    clauses: List[MergeClause] = field(default_factory=list)


@dataclass
class SubqueryRef:
    select: "Select"
    alias: str


@dataclass
class JoinClause:
    kind: str                      # inner | left outer | right outer |
    right: Any                     # full outer | cross
    condition: Optional[Any]


def _apply_ctes(sel: "Select", ctes: Dict[str, "Select"]) -> "Select":
    """Replace references to CTE names with subqueries, in place,
    recursing through nested subqueries, UNION branches AND selects
    embedded in expressions (IN (SELECT ...)). A time-traveled
    reference (VERSION AS OF ...) is never a CTE."""
    import copy as _copy
    import dataclasses as _dc

    def rewrite(ref):
        if isinstance(ref, TableRef) and ref.name in ctes and \
                ref.snapshot_id is None and ref.tag is None and \
                ref.timestamp_ms is None:
            return SubqueryRef(select=_copy.deepcopy(ctes[ref.name]),
                               alias=ref.alias or ref.name)
        if isinstance(ref, SubqueryRef):
            _apply_ctes(ref.select, ctes)
        return ref

    def walk_expr(e):
        if isinstance(e, Select):
            _apply_ctes(e, ctes)
        elif isinstance(e, (list, tuple)):
            for x in e:
                walk_expr(x)
        elif _dc.is_dataclass(e) and not isinstance(e, type):
            for f in _dc.fields(e):
                walk_expr(getattr(e, f.name))

    if sel.from_ is not None:
        sel.from_ = rewrite(sel.from_)
    for j in sel.joins:
        j.right = rewrite(j.right)
        walk_expr(j.condition)
    for item in sel.items:
        walk_expr(item.expr)
    walk_expr(sel.where)
    walk_expr(sel.group_by)
    walk_expr(sel.having)
    walk_expr([e for e, _, _ in sel.order_by])
    if sel.union_all is not None:
        _apply_ctes(sel.union_all, ctes)
    return sel


@dataclass
class Select:
    items: List[SelectItem]
    from_: Optional[Any] = None    # TableRef | SubqueryRef
    joins: List[JoinClause] = field(default_factory=list)
    where: Optional[Any] = None
    group_by: List[Any] = field(default_factory=list)
    having: Optional[Any] = None
    order_by: List[Tuple[Any, bool, str]] = field(default_factory=list)
    limit: Optional[int] = None
    offset: Optional[int] = None
    distinct: bool = False
    union_all: Optional["Select"] = None   # right branch of a set-op
    setop: str = "union_all"               # union_all|union|intersect|except


@dataclass
class Insert:
    table: str
    columns: Optional[List[str]]
    rows: Optional[List[List[Any]]]      # VALUES
    select: Optional[Select]             # INSERT .. SELECT
    overwrite: bool = False


@dataclass
class ColumnDef:
    name: str
    type_str: str
    comment: Optional[str] = None


@dataclass
class CreateTable:
    table: str
    columns: List[ColumnDef]
    primary_key: List[str]
    partitioned_by: List[str]
    options: dict
    if_not_exists: bool = False
    comment: Optional[str] = None


@dataclass
class CreateDatabase:
    name: str
    if_not_exists: bool = False


@dataclass
class CreateView:
    name: str
    query_text: str
    select: "Select"
    or_replace: bool = False
    comment: Optional[str] = None


@dataclass
class DropView:
    name: str
    if_exists: bool = False


@dataclass
class CreateFunction:
    name: str
    params: List[Tuple[str, str]]          # (name, type string)
    return_type: Optional[str]
    body: str                              # sql-dialect expression
    or_replace: bool = False
    comment: Optional[str] = None


@dataclass
class DropFunction:
    name: str
    if_exists: bool = False


@dataclass
class ShowFunctions:
    database: Optional[str] = None


@dataclass
class ShowViews:
    database: Optional[str] = None


@dataclass
class DropTable:
    table: str
    if_exists: bool = False


@dataclass
class DropDatabase:
    name: str
    if_exists: bool = False


@dataclass
class ShowTables:
    database: Optional[str] = None


@dataclass
class ShowDatabases:
    pass


@dataclass
class ShowCreateTable:
    table: str


@dataclass
class Describe:
    table: str


@dataclass
class Use:
    database: str


@dataclass
class Delete:
    table: str
    where: Optional[Any]


@dataclass
class Update:
    table: str
    assignments: List[Tuple[str, Any]]
    where: Optional[Any]


@dataclass
class AlterTable:
    table: str
    action: str        # set-options | reset | add-column | drop-column |
    payload: Any       # rename-column


@dataclass
class Call:
    procedure: str
    args: List[Any]


@dataclass
class Explain:
    select: Select


# ---------------------------------------------------------------------------
# Parser
# ---------------------------------------------------------------------------

class Parser:
    def __init__(self, text: str):
        self.text = text
        self.toks = tokenize(text)
        self.i = 0

    # -- token helpers ------------------------------------------------------
    def peek(self, ahead: int = 0) -> Token:
        return self.toks[min(self.i + ahead, len(self.toks) - 1)]

    def next(self) -> Token:
        t = self.toks[self.i]
        if t.kind != "EOF":
            self.i += 1
        return t

    def at_kw(self, *kws: str) -> bool:
        t = self.peek()
        return t.kind == "KEYWORD" and t.value in kws

    def accept_kw(self, *kws: str) -> bool:
        if self.at_kw(*kws):
            self.next()
            return True
        return False

    def expect_kw(self, kw: str):
        if not self.accept_kw(kw):
            raise SQLError(f"expected {kw}, got {self.peek().value!r}")

    def accept_op(self, op: str) -> bool:
        t = self.peek()
        if t.kind == "OP" and t.value == op:
            self.next()
            return True
        return False

    def expect_op(self, op: str):
        if not self.accept_op(op):
            raise SQLError(f"expected {op!r}, got {self.peek().value!r}")

    def at_word(self, word: str) -> bool:
        """Contextual (non-reserved) keyword: an IDENT matching `word`
        case-insensitively (VIEW/VIEWS/REPLACE/OVER/PARTITION stay
        usable as identifiers and function names)."""
        t = self.peek()
        return t.kind == "IDENT" and t.value.upper() == word

    def accept_word(self, word: str) -> bool:
        if self.at_word(word):
            self.next()
            return True
        return False

    def ident(self) -> str:
        t = self.next()
        if t.kind == "IDENT":
            return t.value
        # non-reserved use of keywords as identifiers (e.g. a column
        # named "comment" or "key")
        if t.kind == "KEYWORD" and t.value in (
                "COMMENT", "KEY", "TABLES", "DATABASES", "VERSION", "ALL",
                "FIRST", "LAST", "TIMESTAMP", "SET", "TRUNCATE",
                "MERGE", "USING", "MATCHED"):
            return t.value.lower()
        raise SQLError(f"expected identifier, got {t.value!r}")

    def qualified_name(self) -> str:
        parts = [self.ident()]
        while self.accept_op("."):
            parts.append(self.ident())
        return ".".join(parts)

    # -- entry --------------------------------------------------------------
    def parse(self):
        stmt = self.statement()
        self.accept_op(";")
        if self.peek().kind != "EOF":
            raise SQLError(f"trailing input at {self.peek().pos}")
        return stmt

    def statement(self):
        if self.at_kw("SELECT") or self.at_kw("WITH"):
            return self.select_or_with()
        if self.accept_kw("EXPLAIN"):
            return Explain(self.select_or_with())
        if self.accept_kw("INSERT"):
            return self.insert()
        if self.accept_kw("CREATE"):
            return self.create()
        if self.accept_kw("DROP"):
            return self.drop()
        if self.accept_kw("SHOW"):
            return self.show()
        if self.accept_kw("DESCRIBE") or (
                self.at_kw("DESC") and self.peek(1).kind in ("IDENT",)):
            self.accept_kw("DESC")
            return Describe(self.qualified_name())
        if self.accept_kw("USE"):
            return Use(self.ident())
        if self.accept_kw("MERGE"):
            return self.merge_into()
        if self.accept_kw("TRUNCATE"):
            self.expect_kw("TABLE")
            return Truncate(self.qualified_name())
        if self.accept_kw("DELETE"):
            self.expect_kw("FROM")
            tbl = self.qualified_name()
            where = self.expr() if self.accept_kw("WHERE") else None
            return Delete(tbl, where)
        if self.accept_kw("UPDATE"):
            return self.update()
        if self.accept_kw("ALTER"):
            return self.alter()
        if self.accept_kw("CALL"):
            return self.call()
        raise SQLError(f"unsupported statement start: {self.peek().value!r}")

    # -- MERGE INTO ---------------------------------------------------------
    def merge_into(self) -> MergeInto:
        """MERGE INTO target [AS] t USING source [AS] s ON cond
        WHEN MATCHED [AND c] THEN UPDATE SET col=e,.. | DELETE
        WHEN NOT MATCHED [AND c] THEN INSERT [(cols)] VALUES (e,..)
        (reference MergeIntoProcedure / flink MERGE INTO)."""
        self.expect_kw("INTO")
        target = self.qualified_name()
        target_alias = None
        if self.accept_kw("AS") or self.peek().kind == "IDENT":
            target_alias = self.ident()
        self.expect_kw("USING")
        if self.accept_op("("):
            sub = self.select_or_with()
            self.expect_op(")")
            self.accept_kw("AS")
            source = SubqueryRef(sub, self.ident())
        else:
            source = TableRef(self.qualified_name())
            if self.accept_kw("AS") or self.peek().kind == "IDENT":
                source.alias = self.ident()
        self.expect_kw("ON")
        on = self.expr()
        clauses: List[MergeClause] = []
        while self.accept_kw("WHEN"):
            matched = not self.accept_kw("NOT")
            self.expect_kw("MATCHED")
            cond = self.expr() if self.accept_kw("AND") else None
            self.expect_kw("THEN")
            if matched and self.accept_kw("UPDATE"):
                self.expect_kw("SET")
                assigns = [(self.ident(),
                            (self.expect_op("="), self.expr())[1])]
                while self.accept_op(","):
                    assigns.append((self.ident(),
                                    (self.expect_op("="),
                                     self.expr())[1]))
                clauses.append(MergeClause(True, cond, "update",
                                           assignments=assigns))
            elif matched and self.accept_kw("DELETE"):
                clauses.append(MergeClause(True, cond, "delete"))
            elif not matched and self.accept_kw("INSERT"):
                cols = None
                if self.accept_op("("):
                    cols = [self.ident()]
                    while self.accept_op(","):
                        cols.append(self.ident())
                    self.expect_op(")")
                self.expect_kw("VALUES")
                self.expect_op("(")
                vals = [self.expr()]
                while self.accept_op(","):
                    vals.append(self.expr())
                self.expect_op(")")
                clauses.append(MergeClause(False, cond, "insert",
                                           insert_columns=cols,
                                           insert_values=vals))
            else:
                raise SQLError(
                    "WHEN MATCHED takes UPDATE SET or DELETE; "
                    "WHEN NOT MATCHED takes INSERT")
        if not clauses:
            raise SQLError("MERGE INTO needs at least one WHEN clause")
        return MergeInto(target, target_alias, source, on, clauses)

    # -- WITH (common table expressions) ------------------------------------
    def with_select(self) -> Select:
        """WITH name AS (select) [, name2 AS (select)] select —
        desugared at parse time: references to a CTE name in FROM/JOIN
        positions become subqueries (reference SQL front-ends treat
        non-recursive CTEs exactly as named subqueries)."""
        self.expect_kw("WITH")
        ctes: Dict[str, Select] = {}
        while True:
            name = self.ident()
            if name in ctes:
                raise SQLError(
                    f"WITH query name {name!r} specified more than once")
            self.expect_kw("AS")
            self.expect_op("(")
            sub = self.select()
            self.expect_op(")")
            # earlier CTEs are visible inside later bodies; the dict
            # only grows after this call returns
            _apply_ctes(sub, ctes)
            ctes[name] = sub
            if not self.accept_op(","):
                break
        return _apply_ctes(self.select(), ctes)

    def select_or_with(self) -> Select:
        """A query body anywhere a SELECT is accepted (INSERT ...
        SELECT, CREATE VIEW ... AS, EXPLAIN): WITH is valid there in
        every reference front-end."""
        return self.with_select() if self.at_kw("WITH") else self.select()

    # -- SELECT -------------------------------------------------------------
    def select(self) -> Select:
        self.expect_kw("SELECT")
        s = Select(items=[])
        s.distinct = self.accept_kw("DISTINCT")
        s.items.append(self.select_item())
        while self.accept_op(","):
            s.items.append(self.select_item())
        if self.accept_kw("FROM"):
            s.from_ = self.table_factor()
            while True:
                kind = self.join_kind()
                if kind is None:
                    break
                right = self.table_factor()
                cond = self.expr() if kind != "cross" and \
                    self.accept_kw("ON") else None
                s.joins.append(JoinClause(kind, right, cond))
        if self.accept_kw("WHERE"):
            s.where = self.expr()
        if self.accept_kw("GROUP"):
            self.expect_kw("BY")
            s.group_by.append(self.expr())
            while self.accept_op(","):
                s.group_by.append(self.expr())
        if self.accept_kw("HAVING"):
            s.having = self.expr()
        setop = None
        if self.accept_kw("UNION"):
            if self.accept_kw("ALL"):
                setop = "union_all"
            else:
                self.accept_kw("DISTINCT")
                setop = "union"
        elif self.accept_kw("INTERSECT"):
            self.accept_kw("DISTINCT")
            setop = "intersect"
        elif self.accept_kw("EXCEPT"):
            self.accept_kw("DISTINCT")
            setop = "except"
        if setop is not None:
            right = self.select()
            # the recursive parse is right-associative; SQL set-ops are
            # LEFT-associative with INTERSECT binding tighter. Chains of
            # one associative op (union all / union / intersect) give
            # identical results either way; anything else would return
            # silently wrong rows — refuse with a workaround.
            if right.union_all is not None and \
                    (right.setop != setop or setop == "except"):
                raise SQLError(
                    "chained mixed or EXCEPT set operations are not "
                    "supported directly; parenthesize via a subquery: "
                    "SELECT * FROM (a <op> b) t <op> c")
            s.union_all = right
            s.setop = setop
            # a trailing ORDER BY / LIMIT binds to the WHOLE set-op;
            # the recursive parse attached it to the right branch
            # (which itself already hoisted from any deeper chain)
            s.order_by, right.order_by = right.order_by, []
            s.limit, right.limit = right.limit, None
            s.offset, right.offset = right.offset, None
            return s
        if self.accept_kw("ORDER"):
            self.expect_kw("BY")
            s.order_by.append(self.order_item())
            while self.accept_op(","):
                s.order_by.append(self.order_item())
        if self.accept_kw("LIMIT"):
            s.limit = int(self._number())
            if self.accept_kw("OFFSET"):
                s.offset = int(self._number())
        return s

    def _number(self):
        t = self.next()
        if t.kind != "NUMBER":
            raise SQLError(f"expected number, got {t.value!r}")
        return t.value

    def order_item(self):
        e = self.expr()
        asc = True
        if self.accept_kw("DESC"):
            asc = False
        else:
            self.accept_kw("ASC")
        placement = "at_end"
        if self.accept_kw("NULLS"):
            placement = "at_start" if self.accept_kw("FIRST") else \
                (self.expect_kw("LAST") or "at_end")
        return (e, asc, placement)

    def select_item(self) -> SelectItem:
        if self.accept_op("*"):
            return SelectItem(Star())
        # qualified star: ident . *
        if self.peek().kind == "IDENT" and \
                self.peek(1).kind == "OP" and self.peek(1).value == "." and \
                self.peek(2).kind == "OP" and self.peek(2).value == "*":
            q = self.ident()
            self.next()
            self.next()
            return SelectItem(Star(q))
        e = self.expr()
        alias = None
        if self.accept_kw("AS"):
            alias = self.ident()
        elif self.peek().kind == "IDENT":
            alias = self.ident()
        return SelectItem(e, alias)

    def join_kind(self) -> Optional[str]:
        if self.accept_kw("JOIN") or (self.at_kw("INNER") and
                                      (self.next(), self.expect_kw("JOIN"))):
            return "inner"
        if self.at_kw("LEFT"):
            self.next()
            self.accept_kw("OUTER")
            self.expect_kw("JOIN")
            return "left outer"
        if self.at_kw("RIGHT"):
            self.next()
            self.accept_kw("OUTER")
            self.expect_kw("JOIN")
            return "right outer"
        if self.at_kw("FULL"):
            self.next()
            self.accept_kw("OUTER")
            self.expect_kw("JOIN")
            return "full outer"
        if self.at_kw("CROSS"):
            self.next()
            self.expect_kw("JOIN")
            return "cross"
        return None

    def table_factor(self):
        if self.accept_op("("):
            sub = self.select()
            self.expect_op(")")
            self.accept_kw("AS")
            return SubqueryRef(sub, self.ident())
        name = self.qualified_name()
        ref = TableRef(name)
        if self.accept_kw("VERSION"):
            self.expect_kw("AS")
            self.expect_kw("OF")
            t = self.next()
            if t.kind == "NUMBER":
                ref.snapshot_id = int(t.value)
            elif t.kind == "STRING":
                ref.tag = t.value
            else:
                raise SQLError("VERSION AS OF expects a snapshot id or tag")
        elif self.accept_kw("FOR"):
            self.expect_kw("SYSTEM_TIME")
            self.expect_kw("AS")
            self.expect_kw("OF")
            self.accept_kw("TIMESTAMP")
            t = self.next()
            if t.kind == "NUMBER":
                ref.timestamp_ms = int(t.value)
            elif t.kind == "STRING":
                import datetime as _dt
                dt = _dt.datetime.fromisoformat(t.value)
                if dt.tzinfo is None:
                    dt = dt.replace(tzinfo=_dt.timezone.utc)
                ref.timestamp_ms = int(dt.timestamp() * 1000)
            else:
                raise SQLError("FOR SYSTEM_TIME AS OF expects a timestamp")
        if self.accept_kw("AS"):
            ref.alias = self.ident()
        elif self.peek().kind == "IDENT":
            ref.alias = self.ident()
        return ref

    # -- expressions (precedence climbing) ----------------------------------
    def expr(self):
        return self.or_expr()

    def or_expr(self):
        left = self.and_expr()
        while self.accept_kw("OR"):
            left = Binary("OR", left, self.and_expr())
        return left

    def and_expr(self):
        left = self.not_expr()
        while self.accept_kw("AND"):
            left = Binary("AND", left, self.not_expr())
        return left

    def not_expr(self):
        if self.at_kw("NOT") and self.peek(1).kind == "KEYWORD" and \
                self.peek(1).value == "EXISTS":
            self.next()
            return self._exists(negated=True)
        if self.accept_kw("NOT"):
            return Unary("NOT", self.not_expr())
        if self.at_kw("EXISTS") and self.peek(1).kind == "OP" and \
                self.peek(1).value == "(":
            return self._exists(negated=False)
        return self.comparison()

    def _exists(self, negated: bool) -> "ExistsSubquery":
        self.expect_kw("EXISTS")
        self.expect_op("(")
        sub = self.select_or_with()
        self.expect_op(")")
        return ExistsSubquery(sub, negated)

    def comparison(self):
        left = self.additive()
        negated = self.accept_kw("NOT")
        if self.accept_kw("IS"):
            neg2 = self.accept_kw("NOT")
            self.expect_kw("NULL")
            return IsNull(left, negated=neg2 or negated)
        if self.accept_kw("IN"):
            self.expect_op("(")
            if self.at_kw("SELECT") or self.at_kw("WITH"):
                sub = self.select_or_with()
                self.expect_op(")")
                return InSubquery(left, sub, negated)
            vals = [self.expr()]
            while self.accept_op(","):
                vals.append(self.expr())
            self.expect_op(")")
            return InList(left, vals, negated)
        if self.accept_kw("BETWEEN"):
            lo = self.additive()
            self.expect_kw("AND")
            hi = self.additive()
            return BetweenExpr(left, lo, hi, negated)
        if self.accept_kw("LIKE"):
            t = self.next()
            if t.kind != "STRING":
                raise SQLError("LIKE expects a string pattern")
            return LikeExpr(left, t.value, negated)
        if negated:
            raise SQLError("dangling NOT before comparison")
        for op in ("=", "<>", "<=", ">=", "<", ">"):
            if self.accept_op(op):
                return Binary(op, left, self.additive())
        return left

    def additive(self):
        left = self.multiplicative()
        while True:
            if self.accept_op("+"):
                left = Binary("+", left, self.multiplicative())
            elif self.accept_op("-"):
                left = Binary("-", left, self.multiplicative())
            elif self.accept_op("||"):
                left = Binary("||", left, self.multiplicative())
            else:
                return left

    def multiplicative(self):
        left = self.unary()
        while True:
            if self.accept_op("*"):
                left = Binary("*", left, self.unary())
            elif self.accept_op("/"):
                left = Binary("/", left, self.unary())
            elif self.accept_op("%"):
                left = Binary("%", left, self.unary())
            else:
                return left

    def unary(self):
        if self.accept_op("-"):
            return Unary("NEG", self.unary())
        self.accept_op("+")
        return self.primary()

    def primary(self):
        t = self.peek()
        if t.kind == "NUMBER" or t.kind == "STRING":
            self.next()
            return Literal(t.value)
        if t.kind == "KEYWORD":
            if self.accept_kw("NULL"):
                return Literal(None)
            if self.accept_kw("TRUE"):
                return Literal(True)
            if self.accept_kw("FALSE"):
                return Literal(False)
            if self.accept_kw("CASE"):
                return self.case_expr()
            if self.accept_kw("CAST"):
                self.expect_op("(")
                e = self.expr()
                self.expect_kw("AS")
                type_str = self.type_string()
                self.expect_op(")")
                return Cast(e, type_str)
            if self.accept_kw("TIMESTAMP"):
                s = self.next()
                if s.kind != "STRING":
                    raise SQLError("TIMESTAMP literal expects a string")
                import datetime as _dt
                return Literal(_dt.datetime.fromisoformat(s.value))
        if self.accept_op("("):
            if self.at_kw("SELECT") or self.at_kw("WITH"):
                # scalar subquery: (SELECT max(x) FROM t) in expression
                # position — materialized to a Literal by the executor
                sub = self.select_or_with()
                self.expect_op(")")
                return ScalarSubquery(sub)
            e = self.expr()
            self.expect_op(")")
            return e
        if t.kind == "IDENT" or (t.kind == "KEYWORD" and t.value in (
                "COMMENT", "KEY", "VERSION", "FIRST", "LAST",
                "TRUNCATE", "MERGE", "USING", "MATCHED")):
            name = self.ident()
            if name.upper() in ("ARRAY", "MAP") and \
                    self.peek().kind == "OP" and self.peek().value == "[":
                # ARRAY[e1, ...] / MAP[k1, v1, ...] constructors
                self.next()
                args = []
                if not (self.peek().kind == "OP" and
                        self.peek().value == "]"):
                    args.append(self.expr())
                    while self.accept_op(","):
                        args.append(self.expr())
                self.expect_op("]")
                return Func(name.lower(), args)
            if self.accept_op("("):
                return self.func_call(name)
            if self.peek().kind == "OP" and self.peek().value == "." and \
                    self.peek(1).kind in ("IDENT", "KEYWORD"):
                self.next()
                col = self.ident()
                if self.accept_op("("):
                    return self.func_call(f"{name}.{col}")
                return Column(col, qualifier=name)
            return Column(name)
        raise SQLError(f"unexpected token {t.value!r} at {t.pos}")

    def func_call(self, name: str):
        distinct = self.accept_kw("DISTINCT")
        args: List[Any] = []
        if self.accept_op("*"):
            args.append(Star())
        elif not (self.peek().kind == "OP" and self.peek().value == ")"):
            args.append(self.expr())
            while self.accept_op(","):
                args.append(self.expr())
        self.expect_op(")")
        over = None
        if self.peek().kind == "IDENT" and \
                self.peek().value.upper() == "OVER":
            self.next()
            self.expect_op("(")
            over = Window()
            if self.peek().kind == "IDENT" and \
                    self.peek().value.upper() == "PARTITION":
                self.next()
                self.expect_kw("BY")
                over.partition_by.append(self.expr())
                while self.accept_op(","):
                    over.partition_by.append(self.expr())
            if self.accept_kw("ORDER"):
                self.expect_kw("BY")
                while True:
                    e = self.expr()
                    asc = True
                    if self.accept_kw("DESC"):
                        asc = False
                    else:
                        self.accept_kw("ASC")
                    over.order_by.append((e, asc))
                    if not self.accept_op(","):
                        break
            self.expect_op(")")
        return Func(name.lower(), args, distinct, over)

    def case_expr(self):
        whens = []
        # simple CASE (CASE x WHEN v THEN r) rewritten to searched form
        operand = None
        if not self.at_kw("WHEN"):
            operand = self.expr()
        while self.accept_kw("WHEN"):
            cond = self.expr()
            if operand is not None:
                cond = Binary("=", operand, cond)
            self.expect_kw("THEN")
            whens.append((cond, self.expr()))
        default = self.expr() if self.accept_kw("ELSE") else None
        self.expect_kw("END")
        return Case(whens, default)

    def type_string(self) -> str:
        """Consume a type name (possibly parameterized / NOT NULL) and
        return it as the string form `types.parse_data_type` accepts."""
        parts = []
        t = self.next()
        if t.kind not in ("IDENT", "KEYWORD"):
            raise SQLError(f"expected type name, got {t.value!r}")
        parts.append(str(t.value).upper())
        name = parts[0]
        # parameterized complex types: ARRAY<T>, MAP<K, V>, MULTISET<T>,
        # ROW<name T, ...>, VECTOR<T, n> (reference DataTypeJsonParser grammar)
        if name in ("ARRAY", "MULTISET", "MAP", "ROW", "VECTOR") and \
                self.peek().kind == "OP" and self.peek().value in ("<", "("):
            open_op = self.next().value
            close_op = ">" if open_op == "<" else ")"
            inner = []
            if name == "ROW":
                while True:
                    fname = self.ident()
                    ftype = self.type_string()
                    inner.append(f"{fname} {ftype}")
                    if not self.accept_op(","):
                        break
            elif name == "MAP":
                inner.append(self.type_string())
                self.expect_op(",")
                inner.append(self.type_string())
            elif name == "VECTOR":
                inner.append(self.type_string())
                self.expect_op(",")
                inner.append(str(int(self._number())))
            else:
                inner.append(self.type_string())
            self.expect_op(close_op)
            out = f"{name}<{', '.join(inner)}>"
            if self.accept_kw("NOT"):
                self.expect_kw("NULL")
                out += " NOT NULL"
            return out
        # multi-word types: DOUBLE PRECISION, TIMESTAMP WITH LOCAL TIME ZONE
        while self.peek().kind == "IDENT" and \
                self.peek().value.upper() in ("PRECISION", "WITH", "LOCAL",
                                              "TIME", "ZONE", "VARYING"):
            parts.append(self.next().value.upper())
        if self.accept_op("("):
            nums = [str(int(self._number()))]
            while self.accept_op(","):
                nums.append(str(int(self._number())))
            self.expect_op(")")
            parts[-1] += f"({', '.join(nums)})"
        if self.accept_kw("NOT"):
            self.expect_kw("NULL")
            parts.append("NOT NULL")
        return " ".join(parts)

    # -- INSERT / CREATE / ALTER / CALL -------------------------------------
    def insert(self) -> Insert:
        overwrite = self.accept_kw("OVERWRITE")
        if not overwrite:
            self.expect_kw("INTO")
        else:
            self.accept_kw("INTO")
        table = self.qualified_name()
        columns = None

        def at_paren_select() -> bool:
            return self.peek().kind == "OP" and \
                self.peek().value == "(" and \
                self.peek(1).kind == "KEYWORD" and \
                self.peek(1).value == "SELECT"

        if self.peek().kind == "OP" and self.peek().value == "(" and \
                not at_paren_select():
            self.next()
            columns = [self.ident()]
            while self.accept_op(","):
                columns.append(self.ident())
            self.expect_op(")")
        if self.accept_kw("VALUES"):
            rows = [self.value_row()]
            while self.accept_op(","):
                rows.append(self.value_row())
            return Insert(table, columns, rows, None, overwrite)
        if at_paren_select():
            # INSERT INTO t [(cols)] (SELECT ...)
            self.next()
            sel = self.select_or_with()
            self.expect_op(")")
            return Insert(table, columns, None, sel, overwrite)
        return Insert(table, columns, None, self.select_or_with(),
                      overwrite)

    def value_row(self) -> List[Any]:
        self.expect_op("(")
        row = [self.expr()]
        while self.accept_op(","):
            row.append(self.expr())
        self.expect_op(")")
        return row

    def create(self):
        if self.accept_kw("DATABASE"):
            ine = False
            if self.accept_kw("IF"):
                self.expect_kw("NOT")
                self.expect_kw("EXISTS")
                ine = True
            return CreateDatabase(self.ident(), ine)
        or_replace = False
        if self.accept_kw("OR"):
            if not self.accept_word("REPLACE"):
                raise SQLError("expected REPLACE after CREATE OR")
            or_replace = True
        if self.accept_word("VIEW"):
            name = self.qualified_name()
            comment = None
            if self.accept_kw("COMMENT"):
                t = self.next()
                comment = t.value
            self.expect_kw("AS")
            start = self.peek().pos
            sel = self.select_or_with()
            return CreateView(name, self.text[start:].rstrip().rstrip(";"),
                              sel, or_replace, comment)
        if self.accept_word("FUNCTION"):
            name = self.qualified_name()
            params = []
            self.expect_op("(")
            if not (self.peek().kind == "OP" and
                    self.peek().value == ")"):
                while True:
                    pname = self.ident()
                    params.append((pname, self.type_string()))
                    if not self.accept_op(","):
                        break
            self.expect_op(")")
            rtype = None
            if self.accept_word("RETURNS"):
                rtype = self.type_string()
            comment = None
            if self.accept_kw("COMMENT"):
                comment = self.next().value
            self.expect_kw("AS")
            t = self.next()
            if t.kind != "STRING":
                raise SQLError("CREATE FUNCTION body must be a string "
                               "expression: AS 'expr over params'")
            return CreateFunction(name, params, rtype, t.value,
                                  or_replace, comment)
        if or_replace:
            raise SQLError("OR REPLACE is only valid for CREATE "
                           "VIEW/FUNCTION")
        self.expect_kw("TABLE")
        ine = False
        if self.accept_kw("IF"):
            self.expect_kw("NOT")
            self.expect_kw("EXISTS")
            ine = True
        table = self.qualified_name()
        self.expect_op("(")
        columns: List[ColumnDef] = []
        pk: List[str] = []
        while True:
            if self.accept_kw("PRIMARY"):
                self.expect_kw("KEY")
                self.expect_op("(")
                pk.append(self.ident())
                while self.accept_op(","):
                    pk.append(self.ident())
                self.expect_op(")")
                if self.accept_kw("NOT"):
                    self.expect_kw("ENFORCED")
            else:
                name = self.ident()
                type_str = self.type_string()
                comment = None
                if self.accept_kw("COMMENT"):
                    t = self.next()
                    if t.kind != "STRING":
                        raise SQLError("COMMENT expects a string")
                    comment = t.value
                columns.append(ColumnDef(name, type_str, comment))
            if not self.accept_op(","):
                break
        self.expect_op(")")
        comment = None
        if self.accept_kw("COMMENT"):
            t = self.next()
            comment = t.value
        partitioned: List[str] = []
        if self.accept_kw("PARTITIONED"):
            self.expect_kw("BY")
            self.expect_op("(")
            partitioned.append(self.ident())
            while self.accept_op(","):
                partitioned.append(self.ident())
            self.expect_op(")")
        options: dict = {}
        if self.accept_kw("WITH"):
            self.expect_op("(")
            while True:
                k = self.next()
                self.expect_op("=")
                v = self.next()
                if k.kind != "STRING" or v.kind != "STRING":
                    raise SQLError("WITH options must be 'key' = 'value'")
                options[k.value] = v.value
                if not self.accept_op(","):
                    break
            self.expect_op(")")
        return CreateTable(table, columns, pk, partitioned, options, ine,
                           comment)

    def drop(self):
        if self.accept_kw("DATABASE"):
            ie = self._if_exists()
            return DropDatabase(self.ident(), ie)
        if self.accept_word("VIEW"):
            ie = self._if_exists()
            return DropView(self.qualified_name(), ie)
        if self.accept_word("FUNCTION"):
            ie = self._if_exists()
            return DropFunction(self.qualified_name(), ie)
        self.expect_kw("TABLE")
        ie = self._if_exists()
        return DropTable(self.qualified_name(), ie)

    def _if_exists(self) -> bool:
        if self.accept_kw("IF"):
            self.expect_kw("EXISTS")
            return True
        return False

    def show(self):
        if self.accept_kw("DATABASES"):
            return ShowDatabases()
        if self.accept_kw("TABLES"):
            db = None
            if self.accept_kw("FROM") or self.accept_kw("IN"):
                db = self.ident()
            return ShowTables(db)
        if self.accept_word("VIEWS"):
            db = None
            if self.accept_kw("FROM") or self.accept_kw("IN"):
                db = self.ident()
            return ShowViews(db)
        if self.accept_word("FUNCTIONS"):
            db = None
            if self.accept_kw("FROM") or self.accept_kw("IN"):
                db = self.ident()
            return ShowFunctions(db)
        if self.accept_kw("CREATE"):
            self.expect_kw("TABLE")
            return ShowCreateTable(self.qualified_name())
        raise SQLError("SHOW expects DATABASES | TABLES | VIEWS | "
                       "CREATE TABLE")

    def update(self) -> Update:
        table = self.qualified_name()
        self.expect_kw("SET")
        assignments = []
        while True:
            col = self.ident()
            self.expect_op("=")
            assignments.append((col, self.expr()))
            if not self.accept_op(","):
                break
        where = self.expr() if self.accept_kw("WHERE") else None
        return Update(table, assignments, where)

    def alter(self) -> AlterTable:
        self.expect_kw("TABLE")
        table = self.qualified_name()
        if self.accept_kw("SET"):
            self.expect_op("(")
            opts = {}
            while True:
                k = self.next()
                self.expect_op("=")
                v = self.next()
                opts[k.value] = v.value
                if not self.accept_op(","):
                    break
            self.expect_op(")")
            return AlterTable(table, "set-options", opts)
        if self.accept_kw("RESET"):
            self.expect_op("(")
            keys = [self.next().value]
            while self.accept_op(","):
                keys.append(self.next().value)
            self.expect_op(")")
            return AlterTable(table, "reset", keys)
        if self.accept_kw("ADD"):
            self.accept_kw("COLUMN")
            name = self.ident()
            return AlterTable(table, "add-column",
                              ColumnDef(name, self.type_string()))
        if self.accept_kw("DROP"):
            self.accept_kw("COLUMN")
            return AlterTable(table, "drop-column", self.ident())
        if self.accept_kw("RENAME"):
            self.accept_kw("COLUMN")
            old = self.ident()
            self.expect_kw("TO")
            return AlterTable(table, "rename-column", (old, self.ident()))
        raise SQLError("unsupported ALTER TABLE action")

    def call(self) -> Call:
        proc = self.qualified_name()
        self.expect_op("(")
        args: List[Any] = []
        if not (self.peek().kind == "OP" and self.peek().value == ")"):
            args.append(self._call_arg())
            while self.accept_op(","):
                args.append(self._call_arg())
        self.expect_op(")")
        return Call(proc, args)

    def _call_arg(self):
        t = self.next()
        if t.kind in ("STRING", "NUMBER"):
            return t.value
        if t.kind == "KEYWORD" and t.value in ("TRUE", "FALSE"):
            return t.value == "TRUE"
        if t.kind == "KEYWORD" and t.value == "NULL":
            return None
        raise SQLError("CALL arguments must be literals")


def parse(text: str):
    return Parser(text).parse()
