"""SQL layer: a from-scratch SQL front-end over the catalog/table API.

The reference exposes SQL through a native DataFusion binding
(paimon-python/pypaimon/sql/__init__.py -> pypaimon_rust.datafusion
.SQLContext) and through Flink/Spark SQL on the JVM side.  This module
provides the same capability natively: a hand-rolled parser
(`sql/parser.py`) and an Arrow-compute executor (`sql/executor.py`) with
predicate pushdown into table scans, aggregation, equi-joins, time
travel, DDL/DML, and CALL procedures for maintenance actions.
"""

from paimon_tpu.sql.executor import SQLContext  # noqa: F401
