"""Mesh-sharded maintenance plane: lease-based, takeover-capable
bucket ownership for compaction, expiry and changelog serving.

PR 10 sharded the WRITE path across the multi-host mesh
(parallel/distributed.py); this module extends the same deterministic
(partition, bucket) ownership to every background plane, so one host's
death no longer stalls compaction table-wide or kills the streaming
daemon (the reference runs dedicated compactor/committer operators for
exactly this reason, and "A Host-SSD Collaborative Write Accelerator
for LSM-Tree-Based Key-Value Stores" (arxiv 2410.21760) makes the
broader point: background LSM work should never ride the ingest
host's fate).

The protocol, in store terms only (a dead host cannot join a
collective, so nothing here requires one):

**Leases.**  Every plane-issued commit — stream checkpoints,
compaction snapshots, heartbeats — stamps `multihost.lease.p<i>`
properties through `FileStoreCommit.properties_provider`: the
committer's wall-clock renewal plus its last-known view of every
peer's renewal (a max-merge CRDT — readers fold the last few
snapshots, so concurrent committers cannot regress each other).  An
idle host publishes a small heartbeat snapshot every
`multihost.lease.interval` so silence is never ambiguous.

**Failure detection.**  A participant whose newest renewal is older
than `multihost.lease.timeout` is presumed dead.  The detector input
is pure store state (the max-merged lease view), so every survivor
reaches the same verdict independently; the barrier/allgather
primitives of parallel/multihost.py arbitrate only LIVE-cohort
transitions (bring-up, distributed rescale), never death — a gloo
collective with a dead member hangs, which is exactly the failure
being tolerated.

**Takeover.**  A dead host's groups are re-sharded over the survivors
by the same salted crc32 that sharded them in the first place
(`distributed.owner_of(dead=...)`): deterministic, so N survivors
compute the identical takeover map with no communication.  The
adoption bumps the ownership version and records the dead set in
snapshot properties; both ride the adopter's next commit, so a
survivor restarting mid-takeover resumes the adopted generation.
A dead host that comes back must NOT silently rejoin — its id stays
in the dead set until it is READMITTED through the coordinated rejoin
protocol: the resurrected host constructs its plane in a `rejoining`
state (it owns nothing), publishes a rejoin-request property whose
liveness rides its own lease, and the elected alive host bumps the
generation with the returner re-sharded back in.  The salted-crc32
map hands the returner exactly its old primary groups back, so its
SSD-tier blocks and plan-cache state are warm on re-entry (the
host-SSD collaborative design of arxiv 2410.21760).  Every
generation — bring-up, takeover, readmission, rescale — is persisted
in `multihost.ownership.history`, so `owner_of` at any historical
version is EXACT and chained multi-death adoptions use the map that
actually governed each victim's writes (see docs/multihost.md for
the state machine).  `multihost.rejoin.enabled=false` restores the
refuse-with-`OwnershipError` behavior.

Everything degrades to single-process: the map owns everything, the
detector sees no peers, and heartbeats are the only observable
difference (disabled when process_count == 1).
"""

from __future__ import annotations

import time as _time
from typing import Callable, Dict, FrozenSet, Optional, Tuple

from paimon_tpu.options import CoreOptions
from paimon_tpu.parallel.distributed import (
    GenerationHistory, OwnershipError, OwnershipMap, lease_props,
    merge_lease_view, merge_rejoin_requests, rejoin_request_props,
    resume_generation_history,
)

__all__ = ["MaintenancePlane"]


def _now_ms() -> int:
    return int(_time.time() * 1000)


class MaintenancePlane:
    """One process's slice of the sharded maintenance plane over a
    fixed-bucket table.

    Usage (identical on every host; no collectives required):

        plane = MaintenancePlane(table, base_user="stream-daemon")
        plane.ensure_lease()                  # initial renewal
        ...
        if plane.owns(partition, bucket): compact/serve it
        if plane.owns_expiry(): expire snapshots
        newly_dead = plane.detect_and_take_over()
        plane.maybe_heartbeat()               # idle renewal
    """

    def __init__(self, table, base_user: str = "maint",
                 process_index: Optional[int] = None,
                 process_count: Optional[int] = None,
                 clock: Optional[Callable[[], int]] = None):
        import jax

        self.table = table
        self.base_user = base_user
        self.process_index = (jax.process_index()
                              if process_index is None else process_index)
        self.process_count = (jax.process_count()
                              if process_count is None else process_count)
        self._clock = clock or _now_ms
        o = table.options
        self.lease_interval_ms = o.get(
            CoreOptions.MULTIHOST_LEASE_INTERVAL)
        self.lease_timeout_ms = o.get(CoreOptions.MULTIHOST_LEASE_TIMEOUT)
        self.takeover_enabled = o.get(
            CoreOptions.MULTIHOST_MAINTENANCE_TAKEOVER)
        self.lease_walk = o.get(
            CoreOptions.MULTIHOST_MAINTENANCE_LEASE_WALK)
        if table.options.bucket < 1:
            raise OwnershipError(
                "the maintenance plane needs a fixed-bucket table "
                f"(bucket={table.options.bucket})")

        from paimon_tpu.metrics import (
            FLEET_FSCK_INCREMENTAL_RUNS, FLEET_FSCK_OBJECTS_CHECKED,
            FLEET_FSCK_WATERMARK_AGE_MS, FLEET_GENERATIONS,
            FLEET_REJOINS, MULTIHOST_LEASE_EXPIRED,
            MULTIHOST_LEASE_RENEWALS, MULTIHOST_MAINTENANCE_TAKEOVERS,
            MULTIHOST_OWNED_BUCKETS, global_registry,
        )
        self._metrics = global_registry().multihost_metrics()
        # pre-allocate the maintenance series (PR 10 pattern): a run
        # with zero takeovers must render maintenance_takeovers 0 on
        # Prometheus, not omit the series
        for c in (MULTIHOST_MAINTENANCE_TAKEOVERS,
                  MULTIHOST_LEASE_RENEWALS, MULTIHOST_LEASE_EXPIRED):
            self._metrics.counter(c)
        self._metrics.gauge(MULTIHOST_OWNED_BUCKETS)
        # the fleet group rides the same pre-allocation rule: a soak
        # with zero rejoins must render rejoins 0, and the fsck
        # series exist even before the first incremental sweep
        self._fleet = global_registry().fleet_metrics()
        for c in (FLEET_REJOINS, FLEET_FSCK_INCREMENTAL_RUNS,
                  FLEET_FSCK_OBJECTS_CHECKED):
            self._fleet.counter(c)
        self._fleet.gauge(FLEET_GENERATIONS)
        self._fleet.gauge(FLEET_FSCK_WATERMARK_AGE_MS)

        # a host the recorded map calls DEAD owns nothing until the
        # elected survivor readmits it; `rejoining` gates that state
        self.rejoining = False
        rejoin_enabled = o.get(CoreOptions.MULTIHOST_REJOIN_ENABLED)
        recorded_history = resume_generation_history(table)
        recorded = (recorded_history.current()
                    if recorded_history is not None else None)
        buckets = table.options.bucket
        if recorded is None:
            self.ownership = OwnershipMap(1, self.process_count, buckets)
        elif (recorded.num_processes, recorded.num_buckets) == \
                (self.process_count, buckets):
            if self.process_index in recorded.dead:
                if not rejoin_enabled:
                    raise OwnershipError(
                        f"process {self.process_index} is recorded "
                        f"DEAD in ownership generation "
                        f"{recorded.version}; its buckets were adopted "
                        f"by survivors and multihost.rejoin.enabled is "
                        f"false.  Rejoin is a coordinated new plane "
                        f"generation across the whole cohort, not a "
                        f"silent restart (docs/multihost.md)")
                # coordinated rejoin: keep the recorded generation
                # (self still dead, owning nothing) and wait to be
                # readmitted — request_rejoin() publishes the ask
                self.rejoining = True
            # survivors keep the recorded generation — INCLUDING its
            # dead set; the dead host is still dead across restarts
            self.ownership = recorded
        else:
            # topology changed (resized cohort / legacy tip): a new
            # ownership function needs a new version
            self.ownership = OwnershipMap(recorded.version + 1,
                                          self.process_count, buckets)
        self.history = (recorded_history
                        or GenerationHistory.initial(self.ownership)
                        ).with_map(self.ownership)
        self._start_ms = self._clock()
        # last-known lease view, max-merged from the store at refresh
        # points + own in-memory renewals (never regress own entry)
        self._view: Dict[int, int] = merge_lease_view(
            table, self.lease_walk)
        # peers THIS detector already declared dead (lease_expired is
        # counted once per peer, and detect_expired never re-returns
        # a declaration the caller is still acting on)
        self._declared: set = set(self.ownership.dead)
        self._commit = None
        # trace context of the detector round that adopted a dead
        # peer; rides the NEXT stamped commit as `trace.context` so
        # the published takeover links back to the detection span in
        # the merged fleet trace (volatile like the adoption itself)
        self._takeover_ctx: Optional[str] = None
        self._update_owned_gauge()
        self._update_generation_gauge()

    # -- wiring --------------------------------------------------------------

    @property
    def commit_user(self) -> str:
        return f"{self.base_user}-p{self.process_index}"

    def stamp_properties(self) -> Dict[str, str]:
        """Ownership + lease properties for one plane-issued commit —
        hang this on `FileStoreCommit.properties_provider` (or merge
        into explicit commit properties) so EVERY commit the plane
        issues stamps them: under plane-only traffic the tip is
        always stamped and `resume_ownership_map` never has to walk
        past foreign snapshots (the long-maintenance-run regression).

        Refreshes the generation from the store first: a commit that
        lost its CAS race to a peer's takeover re-evaluates this per
        attempt (core/commit.py), and WITHOUT the refresh it would
        stamp its stale in-memory version at the new tip — an
        ownership regression `resume_ownership_map` would resume and
        fsck would (rightly) flag.  Cheap in the common case: the tip
        itself is stamped, so the walk is one snapshot deep."""
        self.refresh_ownership()
        props = self.history.to_properties()
        props.update(lease_props(self.process_index, self._clock(),
                                 self._view))
        if self._takeover_ctx is not None:
            props.setdefault("trace.context", self._takeover_ctx)
            self._takeover_ctx = None
        return props

    def attach(self, file_store_commit) -> None:
        """Stamp every commit the given FileStoreCommit publishes."""
        file_store_commit.properties_provider = self.stamp_properties

    def note_renewal(self, now_ms: Optional[int] = None) -> None:
        """Record that a stamped commit LANDED (the renewal is durable)."""
        from paimon_tpu.metrics import MULTIHOST_LEASE_RENEWALS
        now = self._clock() if now_ms is None else now_ms
        self._view[self.process_index] = max(
            now, self._view.get(self.process_index, 0))
        self._metrics.counter(MULTIHOST_LEASE_RENEWALS).inc()

    # -- ownership filters ---------------------------------------------------

    def owns(self, partition: Tuple, bucket: int) -> bool:
        return self.ownership.owner_of(tuple(partition), int(bucket)) \
            == self.process_index

    def group_filter(self) -> Callable[[Tuple, int], bool]:
        """(partition, bucket) -> owned?  — the scheduling filter for
        compact_table / compact_table_mesh / changelog serving."""
        return self.owns

    def owns_expiry(self) -> bool:
        """Snapshot/changelog expiry is table-global, not per-bucket:
        it is ELECTED — the lowest-ranked ALIVE process runs it, so a
        dead expiry owner's duty fails over deterministically."""
        alive = self.ownership.alive()
        return bool(alive) and self.process_index == min(alive)

    def _update_owned_gauge(self):
        from paimon_tpu.metrics import MULTIHOST_OWNED_BUCKETS
        owned = sum(1 for b in range(self.ownership.num_buckets)
                    if self.ownership.owner_of((), b)
                    == self.process_index)
        self._metrics.gauge(MULTIHOST_OWNED_BUCKETS).set(owned)

    def _update_generation_gauge(self):
        from paimon_tpu.metrics import FLEET_GENERATIONS
        self._fleet.gauge(FLEET_GENERATIONS).set(self.ownership.version)

    # -- leases + failure detection ------------------------------------------

    def refresh_view(self) -> Dict[int, int]:
        """Max-merge the store's recent lease stamps into the local
        view (detector input).  Own entries never regress."""
        stored = merge_lease_view(self.table, self.lease_walk)
        for p, ms in stored.items():
            if ms > self._view.get(p, -1):
                self._view[p] = ms
        return dict(self._view)

    def refresh_ownership(self) -> bool:
        """Adopt a HIGHER ownership generation recorded in the store
        (another survivor completed a takeover first, readmitted a
        rejoiner, or the write plane rescaled).  Returns True when the
        map changed.  Versions only ever move forward — the fsck
        ownership check relies on chain monotonicity."""
        recorded_history = resume_generation_history(self.table)
        recorded = (recorded_history.current()
                    if recorded_history is not None else None)
        if recorded is None or recorded.version <= self.ownership.version:
            return False
        if (recorded.num_processes, recorded.num_buckets) != \
                (self.process_count, self.ownership.num_buckets):
            return False          # foreign topology: not ours to adopt
        self.ownership = recorded
        self.history = recorded_history
        # a peer the new generation readmitted is declarable AGAIN if
        # it dies again — forget the old declaration
        self._declared = {p for p in self._declared
                          if p in recorded.dead}
        if self.rejoining and self.process_index not in recorded.dead:
            # the elected survivor readmitted us: we own our groups
            # again (the caller still replays its offset gap before
            # forward work — service/stream_daemon.py)
            self.rejoining = False
        self._update_owned_gauge()
        self._update_generation_gauge()
        return True

    def lease_age_ms(self, process: int,
                     now_ms: Optional[int] = None) -> int:
        """Ms since `process` last renewed; a process never seen ages
        from plane construction (grace for slow bring-up)."""
        now = self._clock() if now_ms is None else now_ms
        return now - self._view.get(process, self._start_ms)

    def expired_processes(self, now_ms: Optional[int] = None
                          ) -> FrozenSet[int]:
        """Peers (never self) whose lease is older than the timeout
        and who are not already recorded dead."""
        now = self._clock() if now_ms is None else now_ms
        return frozenset(
            p for p in range(self.process_count)
            if p != self.process_index
            and p not in self.ownership.dead
            and self.lease_age_ms(p, now) > self.lease_timeout_ms)

    def detect_expired(self, now_ms: Optional[int] = None,
                       refresh: bool = True) -> FrozenSet[int]:
        """One failure-detector round WITHOUT adoption: refresh the
        lease view and return peers newly past the timeout (each is
        declared — and counted into lease_expired — exactly once).
        The distributed stream daemon uses this split so the
        ownership bump can ride the SAME commit as its offset
        backfill: declaring and adopting in one step would let a
        heartbeat stamp a takeover whose backfill never published."""
        from paimon_tpu.metrics import MULTIHOST_LEASE_EXPIRED
        if self.process_count <= 1:
            return frozenset()
        if refresh:
            self.refresh_view()
            self.refresh_ownership()
        newly = frozenset(p for p in self.expired_processes(now_ms)
                          if p not in self._declared)
        if newly:
            self._declared |= newly
            self._metrics.counter(MULTIHOST_LEASE_EXPIRED).inc(
                len(newly))
            from paimon_tpu.obs.flight import EV_LEASE_EXPIRED, record
            record(EV_LEASE_EXPIRED, detector=self.process_index,
                   peers=sorted(newly))
        return newly

    def adopt(self, dead) -> None:
        """Bump the in-memory generation with `dead` adopted (one
        takeover).  The caller must publish the new map on its next
        stamped commit — until then the adoption is volatile and a
        restart re-detects + redoes it, which is the exactly-once
        shape the daemon's backfill relies on."""
        from paimon_tpu.metrics import MULTIHOST_MAINTENANCE_TAKEOVERS
        before = self.ownership
        self.ownership = before.with_dead(dead)
        if self.ownership is not before:
            self.history = self.history.with_map(self.ownership)
            self._metrics.counter(
                MULTIHOST_MAINTENANCE_TAKEOVERS).inc()
            self._update_owned_gauge()
            self._update_generation_gauge()
            from paimon_tpu.obs.flight import EV_TAKEOVER, record
            record(EV_TAKEOVER, survivor=self.process_index,
                   dead=sorted(self.ownership.dead),
                   generation=self.ownership.version)

    def detect_and_take_over(self, now_ms: Optional[int] = None,
                             refresh: bool = True) -> FrozenSet[int]:
        """Detector + immediate adoption, for standalone maintenance
        loops (no offset backfill to synchronize with): declare peers
        past the timeout dead and bump the in-memory generation; the
        new map rides the next stamped commit.  Deterministic: every
        survivor computes the same verdict and the same successor map
        from store state alone."""
        newly = self.detect_expired(now_ms, refresh)
        if newly and self.takeover_enabled:
            from paimon_tpu.obs.trace import (
                current_context_token, span,
            )
            with span("maintenance.takeover", cat="maintenance",
                      detector=self.process_index, dead=sorted(newly)):
                self.adopt(newly)
                self._takeover_ctx = current_context_token()
        return newly

    # -- coordinated rejoin --------------------------------------------------

    def request_rejoin(self) -> Optional[int]:
        """Publish (or refresh) this dead-recorded host's rejoin
        request: a forced empty snapshot stamping
        `multihost.rejoin.request.p<i>` PLUS the usual lease renewal,
        so the request's liveness rides the requester's own lease —
        a rejoiner that dies again goes stale with its lease and is
        never readmitted from a stale ask.  Returns the snapshot id,
        or None when this plane is not in the rejoining state."""
        if not self.rejoining:
            return None
        props = rejoin_request_props(self.process_index, self._clock())
        sid = self._file_store_commit().commit(
            [], properties=props, force_create=True)
        self.note_renewal()
        return sid

    def pending_rejoin_requests(self) -> FrozenSet[int]:
        """Dead-recorded peers asking to rejoin whose lease is FRESH
        (their request commit renews it, so a live rejoiner keeps its
        ask actionable and a re-dead one ages out).  Detector input is
        pure store state, like death: every survivor computes the
        same set."""
        if self.process_count <= 1 or not self.ownership.dead:
            return frozenset()
        self.refresh_view()
        reqs = merge_rejoin_requests(self.table, self.lease_walk)
        now = self._clock()
        return frozenset(
            p for p in reqs
            if p != self.process_index
            and p in self.ownership.dead
            and self.lease_age_ms(p, now) <= self.lease_timeout_ms)

    def owns_rejoin_grant(self) -> bool:
        """Readmission is table-global like expiry: the lowest-ranked
        ALIVE process grants it, so the granter role itself fails
        over deterministically."""
        return self.owns_expiry()

    def readmit(self, returning) -> FrozenSet[int]:
        """Bump the in-memory generation with `returning` back ALIVE
        (the granter side of rejoin).  The salted-crc32 map hands the
        returner exactly its old primary groups back — warm SSD-tier
        state by construction.  Returns the set actually readmitted
        (exactly-once: a peer not currently dead is a no-op, so a
        granter retrying after a CAS loss cannot double-count).  As
        with `adopt`, the new generation is volatile until the caller
        publishes it on a stamped commit — the stream daemon rides it
        on the same forced commit as its rejoin floor."""
        from paimon_tpu.metrics import FLEET_REJOINS
        returning = frozenset(returning) & frozenset(self.ownership.dead)
        if not returning:
            return frozenset()
        self.ownership = self.ownership.without_dead(returning)
        self.history = self.history.with_map(self.ownership)
        self._declared -= set(returning)
        self._fleet.counter(FLEET_REJOINS).inc(len(returning))
        self._update_owned_gauge()
        self._update_generation_gauge()
        from paimon_tpu.obs.flight import EV_REJOIN_GRANT, record
        record(EV_REJOIN_GRANT, granter=self.process_index,
               returning=sorted(returning),
               generation=self.ownership.version)
        return returning

    # -- heartbeats ----------------------------------------------------------

    def _file_store_commit(self):
        if self._commit is None:
            from paimon_tpu.core.commit import FileStoreCommit
            self._commit = FileStoreCommit(
                self.table.file_io, self.table.path, self.table.schema,
                self.table.options, commit_user=self.commit_user,
                branch=self.table.branch)
            self.attach(self._commit)
        return self._commit

    def heartbeat_due(self, now_ms: Optional[int] = None) -> bool:
        if self.process_count <= 1:
            return False          # nobody is watching the lease
        now = self._clock() if now_ms is None else now_ms
        last = self._view.get(self.process_index, 0)
        return now - last >= self.lease_interval_ms

    def maybe_heartbeat(self, now_ms: Optional[int] = None
                        ) -> Optional[int]:
        """Publish a forced empty snapshot carrying the lease/ownership
        stamps when no plane commit renewed the lease within
        multihost.lease.interval.  Returns the snapshot id, or None
        when no heartbeat was due.  Heartbeats deliberately carry NO
        stream offset property, so checkpoint-offset audits and
        recovery walks skip them."""
        if not self.heartbeat_due(now_ms):
            return None
        sid = self._file_store_commit().commit([], force_create=True)
        self.note_renewal()
        return sid

    def ensure_lease(self) -> Optional[int]:
        """Initial renewal at plane bring-up: peers' failure detectors
        must see a lease before the construction grace runs out."""
        if self.process_count <= 1:
            return None
        sid = self._file_store_commit().commit([], force_create=True)
        self.note_renewal()
        return sid
