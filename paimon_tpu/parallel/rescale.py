"""Bucket rescale via an all_to_all collective repartition.

reference: changing a table's bucket count requires a full shuffle —
each row re-hashes to `Math.abs(hash % newBuckets)` and moves to its
new owner task (table/sink/ChannelComputer.java routing, executed as a
flink network shuffle by dedicated rescale jobs).

TPU shape: the shuffle IS the collective.  Each device receives an
equal slice of the table's row-hash vector; on device it computes every
row's new bucket (Java truncated `abs(h % B)` via lax.rem, bit-compat
with core/bucket.py), packs row REFERENCES into per-target-device slot
blocks, and one `jax.lax.all_to_all` over the mesh delivers each
device exactly the references it will own (ownership: new_bucket %
n_devices, round-robin).  Variable-length row bytes never cross the
device — the host moves Arrow rows per the mesh-computed routing
table, writes the new bucket files, and commits an overwrite.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional

import numpy as np

__all__ = ["rescale_dispatch_sharded", "rescale_table_buckets",
           "rescale_routing", "rescale_write_messages",
           "rescale_commit"]

_INVALID = np.uint32(0xFFFFFFFF)


def _dispatch_kernel(mesh, axis: str, n_per_dev: int, cap: int,
                     new_buckets: int):
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from paimon_tpu.parallel._compat import shard_map

    n_dev = mesh.shape[axis]

    @partial(shard_map, mesh=mesh,
             in_specs=(P(axis), P(axis), P(axis)),
             out_specs=(P(axis), P(axis), P(axis)))
    def step(hashes, valid, row_gid):
        h, v, gid = hashes[0], valid[0], row_gid[0]
        # Java `Math.abs(h % n)` with truncated division == abs(lax.rem)
        signed = h.astype(jnp.int32)
        new_bucket = jnp.abs(
            jax.lax.rem(signed, jnp.int32(new_buckets))).astype(jnp.uint32)
        target = (new_bucket % jnp.uint32(n_dev)).astype(jnp.uint32)
        target = jnp.where(v, target, jnp.uint32(n_dev))   # padding rows
        # contiguous per-target runs via one stable sort
        order = jnp.argsort(target, stable=True)
        s_target = target[order]
        s_gid = gid[order]
        s_bucket = new_bucket[order]
        starts = jnp.searchsorted(
            s_target, jnp.arange(n_dev, dtype=jnp.uint32))
        idx_in_run = jnp.arange(n_per_dev, dtype=jnp.int32) - starts[
            jnp.minimum(s_target, n_dev - 1).astype(jnp.int32)]
        ok = (s_target < n_dev) & (idx_in_run < cap)
        slot_gid = jnp.full((n_dev, cap), _INVALID, dtype=jnp.uint32)
        slot_bkt = jnp.full((n_dev, cap), _INVALID, dtype=jnp.uint32)
        # route not-ok rows to an out-of-range slot and let mode="drop"
        # discard them — an in-range dummy index would race the genuine
        # row scattered there (scatter order is unspecified)
        rows = jnp.where(ok, s_target.astype(jnp.int32), n_dev)
        cols = jnp.where(ok, idx_in_run, 0)
        slot_gid = slot_gid.at[rows, cols].set(s_gid, mode="drop")
        slot_bkt = slot_bkt.at[rows, cols].set(s_bucket, mode="drop")
        dropped = jnp.sum((s_target < n_dev) & ~(idx_in_run < cap))
        # THE collective: slot block d travels to device d
        recv_gid = jax.lax.all_to_all(slot_gid, axis, 0, 0)
        recv_bkt = jax.lax.all_to_all(slot_bkt, axis, 0, 0)
        total_dropped = jax.lax.psum(dropped, axis)
        return (recv_gid[None], recv_bkt[None],
                total_dropped.reshape(1, 1))

    return jax.jit(step)


def rescale_dispatch_sharded(hashes: np.ndarray, new_buckets: int,
                             mesh=None, axis: str = "buckets",
                             slack: float = 2.0
                             ) -> Dict[int, np.ndarray]:
    """Route every row to its new bucket with one all_to_all.

    hashes: uint32[total_rows] reference-compatible bucket hashes in
    global row order (core/bucket.KeyHasher.hashes low 32 bits).
    Returns {new_bucket: sorted global row indices} covering every row.
    Slot capacity doubles-and-retries on hash skew overflow."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from paimon_tpu.parallel.sharded_merge import bucket_mesh

    if mesh is None:
        mesh = bucket_mesh(axis=axis)
    n_dev = mesh.shape[axis]
    total = len(hashes)
    n_per_dev = max(1, -(-total // n_dev))
    # balanced load per (source, target) block is n_per_dev/n_dev;
    # worst case (every local row to one target) is n_per_dev
    cap = min(n_per_dev, max(16, int(n_per_dev / n_dev * slack)))

    padded = n_per_dev * n_dev
    h = np.zeros(padded, dtype=np.uint32)
    h[:total] = hashes.astype(np.uint32)
    valid = np.zeros(padded, dtype=bool)
    valid[:total] = True
    gid = np.arange(padded, dtype=np.uint32)

    fn = _dispatch_kernel(mesh, axis, n_per_dev, cap, new_buckets)
    sharding = NamedSharding(mesh, P(axis))
    args = [jax.device_put(a.reshape(n_dev, n_per_dev), sharding)
            for a in (h, valid, gid)]
    recv_gid, recv_bkt, dropped = fn(*args)
    jax.block_until_ready((recv_gid, recv_bkt, dropped))
    if int(np.asarray(dropped).sum()) > 0:
        if cap >= n_per_dev:
            raise RuntimeError("rescale slot capacity overflow")
        return rescale_dispatch_sharded(hashes, new_buckets, mesh, axis,
                                        slack * 4)

    gids = np.asarray(recv_gid).reshape(-1)   # [n_dev * n_dev * cap]
    bkts = np.asarray(recv_bkt).reshape(-1)
    ok = gids != _INVALID
    gids, bkts = gids[ok], bkts[ok]
    result: Dict[int, np.ndarray] = {}
    order = np.argsort(bkts, kind="stable")
    bkts_s, gids_s = bkts[order], gids[order]
    uniq, starts = np.unique(bkts_s, return_index=True)
    bounds = np.append(starts, len(bkts_s))
    for i, b in enumerate(uniq):
        result[int(b)] = np.sort(
            gids_s[bounds[i]:bounds[i + 1]]).astype(np.int64)
    routed = sum(len(v) for v in result.values())
    assert routed == total, (routed, total)
    return result


def _validate_rescale(table, new_buckets: int):
    if not table.primary_keys or table.options.bucket < 1:
        raise ValueError("rescale targets fixed-bucket pk tables")
    if table.partition_keys:
        raise NotImplementedError("rescale of partitioned tables: loop "
                                  "partitions")
    if new_buckets < 1:
        raise ValueError("new_buckets must be >= 1")


def rescale_routing(table, values, new_buckets: int,
                    mesh=None) -> Dict[int, np.ndarray]:
    """{new_bucket: global row indices into `values`} via the mesh
    all_to_all dispatch, bit-compat-checked against the host bucket
    formula.  Bucket membership is a pure function of the row keys, so
    every host of a multi-host plane computes an EQUIVALENT routing
    from the same pinned snapshot regardless of its local mesh shape —
    which is what lets the distributed rescale shard the rewrite by
    target-bucket ownership with no routing exchange."""
    from paimon_tpu.core.bucket import KeyHasher, _bucket_from_hash

    bucket_keys = table.schema.bucket_keys() or \
        table.schema.trimmed_primary_keys()
    rt = table.schema.logical_row_type()
    hasher = KeyHasher(bucket_keys,
                       [rt.get_field(k).type for k in bucket_keys])
    hashes = (hasher.hashes(values)
              & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    routing = rescale_dispatch_sharded(hashes, new_buckets, mesh)
    # bit-compat guard against the host formula
    host_buckets = _bucket_from_hash(hashes, new_buckets)
    for b, gids in routing.items():
        assert (host_buckets[gids] == b).all(), \
            "device routing diverged from reference bucket formula"
    return routing


def rescale_write_messages(table, values, routing, new_buckets: int,
                           buckets: Optional[List[int]] = None):
    """Write the rescaled bucket files for `buckets` (default: every
    routed bucket) and return their CommitMessages.  A multi-host
    plane passes each host the subset it will OWN under the bumped
    ownership map, so the rewrite IO shards across hosts and the
    elected committer only publishes."""
    import pyarrow as pa

    from paimon_tpu.core.kv_file import KeyValueFileWriter
    from paimon_tpu.core.read import MergeFileSplitRead
    from paimon_tpu.core.write import CommitMessage, build_kv_table
    from paimon_tpu.ops.merge import sort_table
    from paimon_tpu.options import CoreOptions

    reader = MergeFileSplitRead(table.file_io, table.path, table.schema,
                                table.options)
    writer = KeyValueFileWriter(
        table.file_io, reader.path_factory, table.schema,
        file_format=table.options.file_format,
        compression=table.options.file_compression,
        target_file_size=table.options.target_file_size,
        index_spec=table.options.file_index_spec,
        bloom_fpp=table.options.get(CoreOptions.FILE_INDEX_BLOOM_FPP),
        format_per_level=table.options.file_format_per_level,
        format_options=table.options.format_options,
        **table.options.kv_writer_kwargs())
    max_level = table.options.max_level

    wanted = None if buckets is None else {int(b) for b in buckets}
    messages: List[CommitMessage] = []
    for b, gids in sorted(routing.items()):
        if wanted is not None and int(b) not in wanted:
            continue
        rows = values.take(pa.array(gids))
        kv = build_kv_table(rows, table.schema,
                            np.arange(rows.num_rows, dtype=np.int64),
                            np.zeros(rows.num_rows, dtype=np.int8))
        order = sort_table(kv, reader.key_cols,
                          key_encoder=reader.key_encoder)
        kv = kv.take(pa.array(order))
        metas = writer.write((), int(b), kv, level=max_level)
        messages.append(CommitMessage((), int(b), new_buckets,
                                      new_files=metas))
    return messages


def rescale_commit(table, new_buckets: int, messages,
                   properties: Optional[Dict[str, str]] = None
                   ) -> Optional[int]:
    """Publish a rescale: ALTER the bucket option first, then INSERT
    OVERWRITE the reorganized data (reference procedure order; writers
    must be paused for the whole rescale, like the reference's offline
    rescale job).  If the overwrite fails, roll the option back so the
    pre-rescale layout stays consistent with the schema."""
    from paimon_tpu.core.commit import FileStoreCommit
    from paimon_tpu.schema import SchemaChange, SchemaManager

    sm = SchemaManager(table.file_io, table.path, table.branch)
    sm.commit_changes(SchemaChange.set_option("bucket", str(new_buckets)))
    try:
        commit = FileStoreCommit(table.file_io, table.path, table.schema,
                                 table.options, branch=table.branch)
        sid = commit.overwrite(messages, properties=properties)
    except BaseException:
        sm.commit_changes(SchemaChange.set_option(
            "bucket", str(table.options.bucket)))
        raise
    return sid


def rescale_table_buckets(table, new_buckets: int, mesh=None,
                          properties: Optional[Dict[str, str]] = None
                          ) -> Optional[int]:
    """Rewrite a fixed-bucket primary-key table to `new_buckets`: the
    mesh computes the routing (abs(hash % B) + all_to_all), the host
    moves rows, writes the new bucket files and commits an overwrite
    (stamped with `properties`, e.g. the distributed write plane's
    ownership-map generation), then records the new bucket count in
    the schema."""
    _validate_rescale(table, new_buckets)
    values = table.to_arrow()      # merged current state, value columns
    if values.num_rows == 0:
        return None
    routing = rescale_routing(table, values, new_buckets, mesh)
    messages = rescale_write_messages(table, values, routing,
                                      new_buckets)
    return rescale_commit(table, new_buckets, messages,
                          properties=properties)
