"""Shared thread/executor construction helpers.

Every pool and background thread in paimon_tpu goes through these two
functions — the tier-1 lint (tests/test_lint_swallow.py) bans bare
``threading.Thread(`` outside ``parallel/`` so thread creation stays
reviewable in one place: names are mandatory (leak checks and stack
dumps must be able to attribute a thread to its subsystem) and daemon
defaults are explicit instead of scattered per call site.
"""

from __future__ import annotations

import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Sequence

__all__ = ["spawn_thread", "new_thread_pool"]


def spawn_thread(target: Callable, *, name: str,
                 daemon: bool = True, start: bool = True,
                 args: Sequence = ()) -> threading.Thread:
    """Create (and by default start) a named background thread.

    `daemon=True` is the deliberate default: paimon background threads
    (HTTP servers, ingest workers, changelog pumps) must never block
    interpreter shutdown — owners that need a clean join call
    ``.join()`` themselves.
    """
    t = threading.Thread(target=target, name=name, daemon=daemon,
                         args=tuple(args))
    if start:
        t.start()
    return t


class _DeadlinePropagatingPool(ThreadPoolExecutor):
    """ThreadPoolExecutor that carries the SUBMITTER's request deadline
    (utils/deadline.py) into each task: contextvars do not cross pool
    boundaries on their own, and without this a worker-side retry
    ladder or byte-budget wait would happily outlive the request that
    queued it."""

    def submit(self, fn, /, *args, **kwargs):
        from paimon_tpu.utils.deadline import (
            current_deadline, run_with_deadline,
        )
        dl = current_deadline()
        if dl is None:
            return super().submit(fn, *args, **kwargs)
        return super().submit(run_with_deadline, dl, fn,
                              *args, **kwargs)


def new_thread_pool(workers: int, prefix: str) -> ThreadPoolExecutor:
    """A named ThreadPoolExecutor (`prefix` becomes the thread-name
    prefix, which the no-leaked-threads tier-1 tests key on).  Tasks
    inherit the submitting thread's request deadline."""
    return _DeadlinePropagatingPool(max_workers=max(1, int(workers)),
                                    thread_name_prefix=prefix)
