"""Shared thread/executor construction helpers.

Every pool and background thread in paimon_tpu goes through these two
functions — the tier-1 lint (tests/test_lint_swallow.py) bans bare
``threading.Thread(`` outside ``parallel/`` so thread creation stays
reviewable in one place: names are mandatory (leak checks and stack
dumps must be able to attribute a thread to its subsystem) and daemon
defaults are explicit instead of scattered per call site.
"""

from __future__ import annotations

import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Sequence

__all__ = ["spawn_thread", "new_thread_pool"]


def spawn_thread(target: Callable, *, name: str,
                 daemon: bool = True, start: bool = True,
                 args: Sequence = ()) -> threading.Thread:
    """Create (and by default start) a named background thread.

    `daemon=True` is the deliberate default: paimon background threads
    (HTTP servers, ingest workers, changelog pumps) must never block
    interpreter shutdown — owners that need a clean join call
    ``.join()`` themselves.
    """
    t = threading.Thread(target=target, name=name, daemon=daemon,
                         args=tuple(args))
    if start:
        t.start()
    return t


def new_thread_pool(workers: int, prefix: str) -> ThreadPoolExecutor:
    """A named ThreadPoolExecutor (`prefix` becomes the thread-name
    prefix, which the no-leaked-threads tier-1 tests key on)."""
    return ThreadPoolExecutor(max_workers=max(1, int(workers)),
                              thread_name_prefix=prefix)
