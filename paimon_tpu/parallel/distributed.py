"""Distributed write plane: sharded bucket ownership, commit
arbitration, snapshot-consistent cross-host scans, online rescale.

The reference scales writers across an engine cluster with a
committer-operator singleton serializing snapshot publication (SURVEY
§5; FileStoreCommit CAS).  "Fast Updates on Read-Optimized Databases
Using Multi-Core CPUs" (arxiv 1109.6885) partitions ownership so
writers never contend; this module lifts that model from cores to
hosts on a JAX multi-host mesh:

- **Ownership** (`OwnershipMap`): every (partition, bucket) is owned
  by exactly one process, deterministically (crc32 shard of the
  partition/bucket identity mod process count — NOT Python `hash()`,
  which is salted per process).  Owners never contend: each host's
  writers flush through the existing per-bucket actor pipeline
  (parallel/write_pipeline.py) on disjoint key ranges.  The map is
  versioned in snapshot properties (`multihost.ownership.*`) so a
  restarted or late-joining process can see which generation the
  table's tip was written under.

- **Routing**: rows arriving at a non-owner are handled per
  `multihost.write.routing` — 'exchange' reroutes them to their
  owners with one cross-host allgather per batch (disjoint input
  streams), 'spmd' keeps only owned rows (identical global batch on
  every process, the jax SPMD shape), 'local-only' raises.

- **Commit arbitration** (`multihost.commit.arbitration`): 'cas' has
  every process commit its own messages under a per-process commit
  user; the snapshot rename-CAS serializes them and FileStoreCommit's
  optimistic retry re-resolves conflicts (observed through
  `conflict_listener` into the multihost metric group).
  'coordinator' gathers every process's commit messages to an elected
  committer over the mesh and publishes ONE snapshot per global
  checkpoint — the reference's committer-operator singleton.  Both
  end in a barrier, so after `commit()` returns every process sees
  every peer's rows.

- **Pinned scans** (`pinned_scan_plan`): all processes agree on one
  snapshot id via a small broadcast, plan against it, and read their
  byte-balanced `assign_splits` share — a cross-host scan of exactly
  one consistent table version.

- **Online rescale** (`rescale_buckets`): drain-and-handoff — every
  writer drains and publishes under the OLD layout, one barrier, the
  elected process rewrites the table to the new bucket count
  (parallel/rescale.py all_to_all routing), another barrier, and
  every writer reopens under the new ownership map (version bumped,
  handoffs counted).  Live write traffic resumes immediately.

Everything degrades to single-process: ownership collapses to
process 0, routing is a no-op, arbitration is a plain commit, and the
barriers return without touching a collective.
"""

from __future__ import annotations

import pickle
import zlib
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa

from paimon_tpu.options import CoreOptions
from paimon_tpu.parallel import multihost as MH
from paimon_tpu.snapshot.snapshot import BATCH_COMMIT_IDENTIFIER

__all__ = ["OwnershipMap", "OwnershipError", "DistributedWritePlane",
           "GenerationHistory", "owner_of", "pinned_scan_plan",
           "OWNERSHIP_VERSION_PROP", "OWNERSHIP_PROCESSES_PROP",
           "OWNERSHIP_BUCKETS_PROP", "OWNERSHIP_DEAD_PROP",
           "OWNERSHIP_HISTORY_PROP",
           "REJOIN_REQUEST_PREFIX", "REJOIN_FLOOR_PREFIX",
           "LEASE_PROP_PREFIX", "lease_props", "merge_lease_view",
           "resume_generation_history", "stamp_from_properties",
           "has_ownership_stamp", "rejoin_request_props",
           "merge_rejoin_requests", "rejoin_floor_props",
           "merge_rejoin_floors"]

# snapshot property keys carrying the ownership-map generation: every
# distributed commit stamps them, so the table's tip records which map
# its files were routed under (rescale bumps the version).  The
# maintenance plane (parallel/maintenance_plane.py) adds two more
# planes of properties on the SAME commits:
#   multihost.ownership.dead   csv of process ids whose buckets have
#                              been taken over by survivors (monotone
#                              within one topology generation)
#   multihost.lease.p<i>       wall-clock ms of process i's last lease
#                              renewal as known by the committer — a
#                              max-merge CRDT: readers fold the last
#                              few snapshots so concurrent committers
#                              cannot regress each other's renewals
OWNERSHIP_VERSION_PROP = "multihost.ownership.version"
OWNERSHIP_PROCESSES_PROP = "multihost.ownership.processes"
OWNERSHIP_BUCKETS_PROP = "multihost.ownership.buckets"
OWNERSHIP_DEAD_PROP = "multihost.ownership.dead"
# the FULL generation chain (version -> processes/buckets/dead-set),
# compactly encoded (see GenerationHistory): chained takeovers and
# rejoins need the map that actually GOVERNED a dead peer's writes,
# which the flat current-generation properties above cannot answer
OWNERSHIP_HISTORY_PROP = "multihost.ownership.history"
# rejoin protocol properties: a resurrected host that finds itself in
# the recorded dead set publishes `...request.p<i> -> wall-clock ms`
# (its lease renews on the same commit, proving it is actually up);
# each alive survivor grants `...floor.p<i> -> "<version>:<granter>:
# <offset>"` once it has flushed everything it ever wrote into the
# rejoiner's groups, bounding the rejoiner's gap replay
REJOIN_REQUEST_PREFIX = "multihost.rejoin.request.p"
REJOIN_FLOOR_PREFIX = "multihost.rejoin.floor.p"
LEASE_PROP_PREFIX = "multihost.lease.p"

# generations are rare (one per takeover / rejoin / rescale); cap how
# many the history property carries so the stamp stays O(1) per commit
_HISTORY_CAP = 64

_ROUTINGS = ("exchange", "spmd", "local-only")
_ARBITRATIONS = ("cas", "coordinator")


class OwnershipError(RuntimeError):
    """A row reached a process that does not own its bucket (routing
    'local-only'), or peers disagree on the write-plane topology."""


def owner_of(partition: Tuple, bucket: int, process_count: int,
             dead: frozenset = frozenset()) -> int:
    """Deterministic owner of (partition, bucket): a crc32 shard over
    the group identity.  crc32, NOT `hash()` — Python string hashing
    is salted per process, and every process must compute the SAME
    map.  repr() of partition values (str/int/date/...) is stable
    across processes for the types partitions can hold.

    `dead` processes own nothing: a group whose primary owner is dead
    is re-sharded (same crc32, re-salted) over the SURVIVORS in rank
    order — every survivor computes the identical takeover map from
    the store-recorded dead set alone, with no communication (the
    dead peer cannot join a collective)."""
    if process_count <= 1:
        return 0
    key = repr((tuple(partition), int(bucket))).encode("utf-8")
    primary = zlib.crc32(key) % process_count
    if primary not in dead:
        return primary
    survivors = [p for p in range(process_count) if p not in dead]
    if not survivors:
        raise OwnershipError(
            "every process of the topology is recorded dead; the "
            "table needs a fresh plane bring-up (new generation)")
    return survivors[zlib.crc32(key + b"#takeover") % len(survivors)]


@dataclass(frozen=True)
class OwnershipMap:
    """One generation of the sharded write-ownership function.

    `dead` is the set of processes whose lease expired and whose
    buckets survivors have adopted: they own nothing until they
    rejoin (which is a new generation — the version bumps whenever
    the ownership FUNCTION changes, takeover included)."""
    version: int
    num_processes: int
    num_buckets: int
    dead: frozenset = frozenset()

    def owner_of(self, partition: Tuple, bucket: int) -> int:
        return owner_of(partition, bucket, self.num_processes,
                        self.dead)

    def alive(self) -> List[int]:
        return [p for p in range(self.num_processes)
                if p not in self.dead]

    def with_dead(self, dead) -> "OwnershipMap":
        """The takeover generation: same topology, `dead` added to
        the dead set, version bumped (a different ownership function
        must never share a version number)."""
        merged = frozenset(self.dead) | frozenset(dead)
        if merged == frozenset(self.dead):
            return self
        return OwnershipMap(self.version + 1, self.num_processes,
                            self.num_buckets, merged)

    def without_dead(self, returning) -> "OwnershipMap":
        """The rejoin generation: same topology, `returning` removed
        from the dead set, version bumped.  Because ownership is the
        pure crc32 shard, readmitting a host hands it back EXACTLY its
        old primary groups (a group re-shards only while its primary
        is dead) — the warm-rejoin property: SSD-tier SSTs/blocks and
        plan-cache state built for those groups are valid again."""
        remaining = frozenset(self.dead) - frozenset(returning)
        if remaining == frozenset(self.dead):
            return self
        return OwnershipMap(self.version + 1, self.num_processes,
                            self.num_buckets, remaining)

    def owned_groups(self, process_index: int, partitions=((),)
                     ) -> List[Tuple[Tuple, int]]:
        """Every (partition, bucket) this process owns, for the given
        partition universe (default: the unpartitioned table)."""
        return [(part, b) for part in partitions
                for b in range(self.num_buckets)
                if self.owner_of(part, b) == process_index]

    def to_properties(self) -> Dict[str, str]:
        props = {OWNERSHIP_VERSION_PROP: str(self.version),
                 OWNERSHIP_PROCESSES_PROP: str(self.num_processes),
                 OWNERSHIP_BUCKETS_PROP: str(self.num_buckets)}
        if self.dead:
            props[OWNERSHIP_DEAD_PROP] = ",".join(
                str(p) for p in sorted(self.dead))
        return props

    def handoffs_to(self, other: "OwnershipMap") -> int:
        """How many non-partitioned bucket owners move between this
        map and `other` (new buckets count as handoffs — they start
        owned by somebody).  Feeds the ownership_handoffs counter."""
        moved = 0
        for b in range(other.num_buckets):
            if b >= self.num_buckets:
                moved += 1
            elif self.owner_of((), b) != other.owner_of((), b):
                moved += 1
        return moved


def _map_from_properties(props: Dict[str, str]) -> OwnershipMap:
    dead = frozenset(
        int(p) for p in (props.get(OWNERSHIP_DEAD_PROP) or "").split(",")
        if p.strip())
    return OwnershipMap(
        int(props[OWNERSHIP_VERSION_PROP]),
        int(props.get(OWNERSHIP_PROCESSES_PROP) or 0),
        int(props.get(OWNERSHIP_BUCKETS_PROP) or 0), dead)


@dataclass(frozen=True)
class GenerationHistory:
    """The full ownership-generation chain, ascending by version.

    The flat `multihost.ownership.*` properties record only the
    CURRENT generation; chained multi-death takeovers and rejoins need
    the map that actually governed a given peer's writes — before this
    existed, floor evaluation approximated it with `current dead -
    {j}`, which is wrong the moment two deaths share one adoption
    round or a host dies, rejoins and dies again.  The history makes
    `owner_of` at any retained version EXACT.

    Encoding (`to_property`): entries `version:processes:buckets:
    dead0+dead1` joined by `|` — e.g. `1:3:4:|2:3:4:2|3:3:4:1+2`.
    Newest `_HISTORY_CAP` generations retained."""

    entries: Tuple[OwnershipMap, ...]

    @staticmethod
    def initial(m: OwnershipMap) -> "GenerationHistory":
        return GenerationHistory((m,))

    def current(self) -> OwnershipMap:
        return self.entries[-1]

    def at(self, version: int) -> Optional[OwnershipMap]:
        """The exact map of one historical generation (None when the
        version predates the retained window)."""
        for m in reversed(self.entries):
            if m.version == version:
                return m
        return None

    def with_map(self, m: OwnershipMap) -> "GenerationHistory":
        """Append a new generation (same map/version is a no-op; a
        version at or below the tip replaces nothing — the caller
        publishes monotone generations)."""
        if self.entries and m == self.entries[-1]:
            return self
        kept = tuple(e for e in self.entries if e.version < m.version)
        return GenerationHistory((kept + (m,))[-_HISTORY_CAP:])

    def map_governing(self, j: int) -> Optional[OwnershipMap]:
        """The map that governed process j's OWN writes: the newest
        retained generation in which j was alive.  None when j is dead
        in every retained entry (history truncation) — callers fall
        back to the legacy `current dead - {j}` approximation."""
        for m in reversed(self.entries):
            if j not in m.dead and j < m.num_processes:
                return m
        return None

    def to_property(self) -> str:
        return "|".join(
            f"{m.version}:{m.num_processes}:{m.num_buckets}:"
            + "+".join(str(p) for p in sorted(m.dead))
            for m in self.entries)

    @staticmethod
    def from_property(raw: str) -> Optional["GenerationHistory"]:
        entries = []
        try:
            for part in raw.split("|"):
                if not part:
                    continue
                v, n, b, dead = part.split(":")
                entries.append(OwnershipMap(
                    int(v), int(n), int(b),
                    frozenset(int(p) for p in dead.split("+") if p)))
        except ValueError:
            return None
        if not entries:
            return None
        entries.sort(key=lambda m: m.version)
        return GenerationHistory(tuple(entries))

    def to_properties(self) -> Dict[str, str]:
        """The full ownership stamp: the current generation's flat
        properties plus the encoded chain — what every plane-issued
        commit carries."""
        props = self.current().to_properties()
        props[OWNERSHIP_HISTORY_PROP] = self.to_property()
        return props


def stamp_from_properties(props: Dict[str, str]
                          ) -> Optional[Tuple[OwnershipMap,
                                              GenerationHistory]]:
    """THE sanctioned read path for ownership stamps: (current map,
    generation history) from one snapshot's properties, or None when
    the snapshot is unstamped.  A stamp without the history property
    (legacy chain prefix) yields a single-entry history.  Every module
    outside this plane must parse stamps through here — the
    `ownership-history` analysis rule enforces it."""
    if OWNERSHIP_VERSION_PROP not in (props or {}):
        return None
    m = _map_from_properties(props)
    hist = None
    raw = props.get(OWNERSHIP_HISTORY_PROP)
    if raw:
        hist = GenerationHistory.from_property(raw)
    if hist is None or hist.current().version < m.version:
        hist = GenerationHistory.initial(m) if hist is None \
            else hist.with_map(m)
    return m, hist


def has_ownership_stamp(props: Optional[Dict[str, str]]) -> bool:
    """Whether a snapshot carries an ownership-generation stamp (the
    presence test recovery walks use)."""
    return bool(props) and OWNERSHIP_VERSION_PROP in props


def resume_ownership_map(table, max_walk: int = 64
                         ) -> Optional[OwnershipMap]:
    """The ownership map recorded at the table's tip: walk snapshots
    newest-first for the properties.  Every PLANE-issued commit —
    writes, compactions, heartbeats, the rescale overwrite AND the
    empty-rescale stamp — carries them (core/commit.py
    properties_provider), so under plane-only traffic the TIP itself
    is stamped and the walk is one snapshot deep; the bound only
    matters when foreign commit users (ad-hoc batch writers, repair
    tools) interleave.  If the bounded walk finds nothing but the
    chain continues, keep walking to the earliest snapshot rather
    than inventing a fresh generation: before this fix a long run of
    maintenance-only commits under other commit users pushed the last
    stamped snapshot past the 64-snapshot window and the plane
    restarted at version 1 — one version number denoting two
    different ownership functions.  None only when NO retained
    snapshot carries the properties."""
    sm = table.snapshot_manager
    latest = sm.latest_snapshot_id()
    if latest is None:
        return None
    earliest = sm.earliest_snapshot_id() or latest
    for sid in range(latest, earliest - 1, -1):
        if not sm.snapshot_exists(sid):
            continue
        props = sm.snapshot(sid).properties or {}
        if OWNERSHIP_VERSION_PROP in props:
            return _map_from_properties(props)
    return None


def resume_generation_history(table, max_walk: int = 64
                              ) -> Optional[GenerationHistory]:
    """The generation history recorded at the table's tip: same walk
    discipline as resume_ownership_map (bounded newest-first, then on
    to the earliest rather than inventing a generation).  A stamped
    tip without the history property (chain written before the
    history existed) yields a single-entry history seeded from the
    flat map."""
    sm = table.snapshot_manager
    latest = sm.latest_snapshot_id()
    if latest is None:
        return None
    earliest = sm.earliest_snapshot_id() or latest
    for sid in range(latest, earliest - 1, -1):
        if not sm.snapshot_exists(sid):
            continue
        stamp = stamp_from_properties(sm.snapshot(sid).properties or {})
        if stamp is not None:
            return stamp[1]
    return None


def lease_props(process_index: int, now_ms: int,
                view: Optional[Dict[int, int]] = None
                ) -> Dict[str, str]:
    """The lease properties one commit stamps: the committer's view of
    every holder's last renewal, with its OWN entry renewed to
    `now_ms`.  Committing the full known view (not just self) makes
    the tip a usable failure-detector input on its own."""
    merged = dict(view or {})
    merged[process_index] = max(now_ms,
                                merged.get(process_index, 0))
    return {f"{LEASE_PROP_PREFIX}{p}": str(ms)
            for p, ms in sorted(merged.items())}


def merge_lease_view(table, max_walk: int = 16) -> Dict[int, int]:
    """{process -> newest known lease-renewal ms}: max-merge the lease
    properties of the last `max_walk` snapshots.  Folding a small
    window (not just the tip) keeps concurrent committers from
    regressing each other — each stamps the view IT knew, and the
    interleaving is resolved by max()."""
    from paimon_tpu.obs.trace import (
        STAGE_LEASE_FOLD, span, tracing_enabled,
    )
    sm = table.snapshot_manager
    latest = sm.latest_snapshot_id()
    if latest is None:
        return {}
    earliest = sm.earliest_snapshot_id() or latest
    view: Dict[int, int] = {}
    link_ctx = link_sid = None
    for sid in range(latest, max(earliest, latest - max_walk) - 1, -1):
        if not sm.snapshot_exists(sid):
            continue
        props = sm.snapshot(sid).properties or {}
        if link_ctx is None and props.get("trace.context"):
            # newest store-carried context in the fold window: the
            # detector's fold links back to the peer whose commit it
            # consumed — THE worker<->worker boundary in merged traces
            link_ctx, link_sid = props["trace.context"], sid
        for k, v in props.items():
            if not k.startswith(LEASE_PROP_PREFIX):
                continue
            try:
                p, ms = int(k[len(LEASE_PROP_PREFIX):]), int(v)
            except ValueError:
                continue
            if ms > view.get(p, -1):
                view[p] = ms
    if link_ctx is not None and tracing_enabled():
        with span(STAGE_LEASE_FOLD, cat="maintenance", link=link_ctx,
                  snapshot=link_sid):
            pass
    return view


def rejoin_request_props(process_index: int, now_ms: int
                         ) -> Dict[str, str]:
    """The property a refused resurrected host stamps to ask the
    elected survivor for readmission."""
    return {f"{REJOIN_REQUEST_PREFIX}{process_index}": str(now_ms)}


def merge_rejoin_requests(table, max_walk: int = 32) -> Dict[int, int]:
    """{process -> newest rejoin-request ms} max-merged over the last
    `max_walk` snapshots — same window discipline as the lease view.
    The caller decides liveness: a request is actionable only while
    the requester's LEASE is also fresh (the request commit renews it),
    so a host that requested, was readmitted, and died again never
    re-triggers a grant from its stale request."""
    sm = table.snapshot_manager
    latest = sm.latest_snapshot_id()
    if latest is None:
        return {}
    earliest = sm.earliest_snapshot_id() or latest
    out: Dict[int, int] = {}
    for sid in range(latest, max(earliest, latest - max_walk) - 1, -1):
        if not sm.snapshot_exists(sid):
            continue
        props = sm.snapshot(sid).properties or {}
        for k, v in props.items():
            if not k.startswith(REJOIN_REQUEST_PREFIX):
                continue
            try:
                p, ms = int(k[len(REJOIN_REQUEST_PREFIX):]), int(v)
            except ValueError:
                continue
            if ms > out.get(p, -1):
                out[p] = ms
    return out


def rejoin_floor_props(granter: int, rejoiner: int, version: int,
                       offset: int) -> Dict[str, str]:
    """The coverage floor one survivor grants a rejoiner: 'everything
    I ever wrote into your groups is committed and ends at `offset`',
    scoped to the readmission generation `version` so floors from an
    earlier rejoin of the same process can never be mistaken for this
    one's."""
    return {f"{REJOIN_FLOOR_PREFIX}{rejoiner}":
            f"{version}:{granter}:{offset}"}


def merge_rejoin_floors(table, rejoiner: int, version: int,
                        max_walk: int = 32) -> Dict[int, int]:
    """{granter -> offset} of every rejoin floor stamped for
    `rejoiner` at readmission generation `version` OR LATER, folded
    over the last `max_walk` snapshots (each snapshot is one
    committer's stamp; the fold collects the cohort's).  Later
    versions count because a survivor may only notice the readmission
    after yet another generation bump — its floor is stamped at its
    then-current offset, still a valid upper bound on what it ever
    wrote into the rejoiner's groups.  Floors from an EARLIER rejoin
    epoch of the same process stay excluded."""
    sm = table.snapshot_manager
    latest = sm.latest_snapshot_id()
    if latest is None:
        return {}
    earliest = sm.earliest_snapshot_id() or latest
    key = f"{REJOIN_FLOOR_PREFIX}{rejoiner}"
    out: Dict[int, int] = {}
    for sid in range(latest, max(earliest, latest - max_walk) - 1, -1):
        if not sm.snapshot_exists(sid):
            continue
        raw = (sm.snapshot(sid).properties or {}).get(key)
        if not raw:
            continue
        try:
            v, granter, offset = (int(x) for x in raw.split(":"))
        except ValueError:
            continue
        if v >= version and offset > out.get(granter, -(1 << 62)):
            out[granter] = offset
    return out


def resume_ownership_version(table, max_walk: int = 64) -> int:
    """Version-only view of resume_ownership_map (0 = never)."""
    m = resume_ownership_map(table, max_walk)
    return m.version if m is not None else 0


def pinned_scan_plan(table, process_index: Optional[int] = None,
                     process_count: Optional[int] = None):
    """Snapshot-consistent cross-host scan plan: agree on ONE snapshot
    id (process 0's latest, via a small broadcast — unless
    multihost.scan.pin-snapshot=false), plan against it, and return
    (snapshot_id, this process's byte-balanced split share).  Every
    process computes the same global plan; no coordinator hands out
    work.  (None, []) when the table has no snapshot."""
    local = table.snapshot_manager.latest_snapshot_id() or 0
    if table.options.get(CoreOptions.MULTIHOST_SCAN_PIN):
        sid = MH.broadcast_value(local)
    else:
        sid = local
    if sid == 0:
        return None, []
    plan = table.new_read_builder().new_scan().plan(snapshot_id=sid)
    mine = MH.assign_splits(plan.splits, process_index, process_count)
    return sid, mine


def _table_to_ipc(t: pa.Table) -> bytes:
    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, t.schema) as w:
        w.write_table(t)
    return sink.getvalue().to_pybytes()


def _table_from_ipc(b: bytes) -> pa.Table:
    with pa.ipc.open_stream(pa.BufferReader(b)) as r:
        return r.read_all()


class DistributedWritePlane:
    """One process's slice of the multi-host write plane over a
    fixed-bucket table.  SPMD contract: every process constructs the
    plane, calls `write_*` the same number of times (routing
    'exchange' runs one collective per batch), and calls `commit` /
    `rescale_buckets` at the same points — the same program-order
    discipline every jax multi-host program already follows.

    Usage (identical on every host):
        plane = table.new_distributed_write()
        plane.write_dicts(my_host_rows)      # routed to owners
        plane.commit()                       # arbitrated publish
        sid, splits = plane.pinned_scan()    # consistent read share
        plane.close()
    """

    def __init__(self, table, base_user: str = "writer",
                 process_index: Optional[int] = None,
                 process_count: Optional[int] = None,
                 committer_index: int = 0):
        import jax

        self.table = table
        self.process_index = (jax.process_index()
                              if process_index is None else process_index)
        self.process_count = (jax.process_count()
                              if process_count is None else process_count)
        self.committer_index = committer_index % max(1, self.process_count)
        self.base_user = base_user
        if table.options.bucket < 1:
            raise OwnershipError(
                "distributed writes need a fixed-bucket table "
                f"(bucket={table.options.bucket}): dynamic/postpone "
                "bucket assignment is stateful per process and cannot "
                "be sharded deterministically")
        if not table.schema.primary_keys:
            raise OwnershipError(
                "distributed writes need a primary-key table: the "
                "append writer has no precomputed-bucket route for "
                "the ownership split")
        if table.schema.cross_partition_update():
            raise OwnershipError(
                "distributed writes do not support cross-partition "
                "update tables: the global index that reroutes "
                "partition changes is per-process state")
        self.routing = table.options.get(
            CoreOptions.MULTIHOST_WRITE_ROUTING)
        if self.routing not in _ROUTINGS:
            raise ValueError(f"multihost.write.routing must be one of "
                             f"{_ROUTINGS}, got {self.routing!r}")
        self.arbitration = table.options.get(
            CoreOptions.MULTIHOST_COMMIT_ARBITRATION)
        if self.arbitration not in _ARBITRATIONS:
            raise ValueError(f"multihost.commit.arbitration must be one "
                             f"of {_ARBITRATIONS}, got "
                             f"{self.arbitration!r}")
        from paimon_tpu.metrics import (
            MULTIHOST_BARRIER_WAIT_MS, MULTIHOST_COMMIT_CONFLICTS,
            MULTIHOST_COMMIT_RETRIES, MULTIHOST_CONFIG_WARNINGS,
            MULTIHOST_FOREIGN_ROWS, MULTIHOST_OWNERSHIP_HANDOFFS,
            global_registry,
        )
        self._metrics = global_registry().multihost_metrics()
        # pre-allocate the group's series so dashboards and the
        # Prometheus endpoint always expose them (a conflict-free run
        # must render commit_conflicts 0, not omit the series)
        for c in (MULTIHOST_COMMIT_CONFLICTS, MULTIHOST_COMMIT_RETRIES,
                  MULTIHOST_OWNERSHIP_HANDOFFS, MULTIHOST_FOREIGN_ROWS,
                  MULTIHOST_CONFIG_WARNINGS):
            self._metrics.counter(c)
        self._metrics.histogram(MULTIHOST_BARRIER_WAIT_MS)
        # dynamic (load-time) options are NOT in the on-disk schema;
        # remember them so the rescale handoff's table reload can
        # re-apply them (copy() REPLACES dynamic options, and silently
        # losing write-only / retry tuning mid-run is a footgun)
        base_opts = table.schema_manager.latest().options
        self._dynamic_opts = {
            k: v for k, v in table.options.to_map().items()
            if base_opts.get(k) != v}
        recorded_history = resume_generation_history(table)
        recorded = recorded_history.current() \
            if recorded_history is not None else None
        buckets = table.options.bucket
        if recorded is None:
            self.ownership = OwnershipMap(1, self.process_count,
                                          buckets)
        elif (recorded.num_processes, recorded.num_buckets) == \
                (self.process_count, buckets) and not recorded.dead:
            self.ownership = OwnershipMap(recorded.version,
                                          self.process_count, buckets)
        else:
            # the topology changed without a coordinated rescale (a
            # resized cluster, a legacy tip without the full
            # properties, or a recorded DEAD set — the full write
            # cohort standing up again is a rejoin): that IS a new
            # ownership function — reusing the recorded version would
            # let one number denote two different maps.  Bump the
            # generation and account the moved owners.
            self.ownership = OwnershipMap(recorded.version + 1,
                                          self.process_count, buckets)
            if recorded.num_processes and recorded.num_buckets:
                from paimon_tpu.metrics import (
                    MULTIHOST_OWNERSHIP_HANDOFFS,
                )
                moved = recorded.handoffs_to(self.ownership)
                if moved:
                    self._metrics.counter(
                        MULTIHOST_OWNERSHIP_HANDOFFS).inc(moved)
        self.history = (recorded_history
                        or GenerationHistory.initial(self.ownership)
                        ).with_map(self.ownership)
        self._had_conflict = False
        self._closed = False
        # introspection: which new buckets THIS host rewrote in the
        # most recent rescale (the distributed-rescale tests assert
        # the share stays within the host's owned set)
        self.last_rescale_written_buckets: List[int] = []
        self._open_writer()

    # -- wiring --------------------------------------------------------------

    @property
    def commit_user(self) -> str:
        """Per-process under 'cas' (the CAS serializes N users); ONE
        stable committer user under 'coordinator' (exactly-once replay
        dedup keys on it)."""
        if self.arbitration == "coordinator":
            return f"{self.base_user}-committer"
        return f"{self.base_user}-p{self.process_index}"

    def _open_writer(self):
        from paimon_tpu.core.bucket import FixedBucketAssigner
        wb = self.table.new_batch_write_builder()
        wb.commit_user = self.commit_user
        self._write = wb.new_write()
        self._commit = wb.new_commit()
        # commit arbitration IS FileStoreCommit's CAS retry loop;
        # observe its lost races into the multihost group
        self._commit._commit.conflict_listener = self._on_conflict
        schema = self.table.schema
        rt = schema.logical_row_type()
        bucket_keys = schema.bucket_keys() or \
            schema.trimmed_primary_keys()
        self._assigner = FixedBucketAssigner(
            bucket_keys, [rt.get_field(k).type for k in bucket_keys],
            self.table.options.bucket)
        self._partition_keys = schema.partition_keys

    def _on_conflict(self, attempt: int):
        from paimon_tpu.metrics import MULTIHOST_COMMIT_CONFLICTS
        self._metrics.counter(MULTIHOST_COMMIT_CONFLICTS).inc()
        self._had_conflict = True

    # -- writes --------------------------------------------------------------

    def write_dicts(self, rows: Sequence[dict],
                    row_kinds: Optional[Sequence[int]] = None):
        from paimon_tpu.core.write import dicts_to_arrow
        t, kinds = dicts_to_arrow(self.table.arrow_schema(), rows,
                                  row_kinds)
        self.write_arrow(t, kinds)

    def write_arrow(self, data: pa.Table,
                    row_kinds: Optional[np.ndarray] = None):
        """Route a batch: owned rows go straight into the local
        per-bucket actor pipeline; foreign rows are exchanged /
        dropped / rejected per multihost.write.routing.  Routing
        'exchange' is a COLLECTIVE — every process must call
        write_arrow the same number of times, even with empty
        batches."""
        if self._closed:
            raise RuntimeError("write plane is closed")
        from paimon_tpu.core.write import extract_row_kinds
        data, kinds = extract_row_kinds(data, row_kinds)
        # field defaults fill BEFORE the ownership hash: the inner
        # TableWrite applies them after this split, so hashing the
        # pre-default NULLs here would route a defaulted bucket-key
        # row to a different bucket than the single-process path
        # (idempotent — the inner second application sees no NULLs)
        data = self._write._apply_field_defaults(data)
        local_idx, foreign_idx, buckets = self._split_local_foreign(data)
        if self.routing == "local-only" and len(foreign_idx):
            raise OwnershipError(
                f"{len(foreign_idx)} rows hash to buckets owned by "
                f"other processes (routing=local-only); partition the "
                f"input stream by ownership or use routing=exchange")
        if len(local_idx):
            idx = pa.array(local_idx)
            self._write.write_arrow(data.take(idx), kinds[local_idx],
                                    buckets=buckets[local_idx])
        if self.routing == "exchange":
            self._exchange(data, kinds, foreign_idx)

    def _split_local_foreign(self, data: pa.Table):
        """(local_row_indices, foreign_row_indices, bucket[i]) for one
        batch — the ownership split, computed once per batch from the
        same FixedBucketAssigner hash the writers use."""
        from paimon_tpu.core.write import group_by_partition_bucket
        if data.num_rows == 0:
            empty = np.empty(0, dtype=np.int64)
            return empty, empty, np.empty(0, dtype=np.int32)
        buckets = np.asarray(self._assigner.assign(data),
                             dtype=np.int32)
        local: List[np.ndarray] = []
        foreign: List[np.ndarray] = []
        for (part, bucket), idx in group_by_partition_bucket(
                data, buckets, self._partition_keys):
            if self.ownership.owner_of(part, bucket) == \
                    self.process_index:
                local.append(idx)
            else:
                foreign.append(idx)
        cat = (lambda parts: np.sort(np.concatenate(parts))
               if parts else np.empty(0, dtype=np.int64))
        return cat(local), cat(foreign), buckets

    def _exchange(self, data: pa.Table, kinds: np.ndarray,
                  foreign_idx: np.ndarray):
        """Reroute foreign rows to their owners: one padded allgather
        of Arrow-IPC payloads; every process then keeps the rows IT
        owns from every peer's payload.  Runs unconditionally in
        'exchange' mode (collective symmetry — peers with zero foreign
        rows still participate with an empty payload)."""
        from paimon_tpu.core.write import ROW_KIND_COL, extract_row_kinds
        if len(foreign_idx):
            sub = data.take(pa.array(foreign_idx))
            sub = sub.append_column(
                ROW_KIND_COL, pa.array(kinds[foreign_idx], pa.int8()))
        else:
            sub = data.slice(0, 0).append_column(
                ROW_KIND_COL, pa.array([], pa.int8()))
        payloads = MH.allgather_bytes(_table_to_ipc(sub))
        from paimon_tpu.metrics import MULTIHOST_FOREIGN_ROWS
        routed = 0
        for p, payload in enumerate(payloads):
            if p == self.process_index:
                continue          # my own foreign rows went to peers
            recv = _table_from_ipc(payload)
            if recv.num_rows == 0:
                continue
            recv, recv_kinds = extract_row_kinds(recv, None)
            local_idx, _, buckets = self._split_local_foreign(recv)
            if len(local_idx):
                idx = pa.array(local_idx)
                self._write.write_arrow(recv.take(idx),
                                        recv_kinds[local_idx],
                                        buckets=buckets[local_idx])
                routed += len(local_idx)
        if routed:
            self._metrics.counter(MULTIHOST_FOREIGN_ROWS).inc(routed)

    # -- commit arbitration --------------------------------------------------

    def commit(self, commit_identifier: int = BATCH_COMMIT_IDENTIFIER,
               properties: Optional[Dict[str, str]] = None
               ) -> Optional[int]:
        """Arbitrated publish of every process's pending writes; all
        processes return only after every peer's rows are visible
        (barrier).  Returns the latest snapshot id this process
        observed (None when the whole checkpoint was empty)."""
        if self._closed:
            raise RuntimeError("write plane is closed")
        msgs = self._write.prepare_commit()
        props = self.history.to_properties()
        if properties:
            props.update(properties)
        self._had_conflict = False
        if self.arbitration == "coordinator":
            sid = self._commit_coordinator(msgs, commit_identifier,
                                           props)
        else:
            sid = self._commit.commit(msgs, commit_identifier,
                                      properties=props)
            MH.barrier("multihost-commit")
            if sid is None:
                sid = self.table.snapshot_manager.latest_snapshot_id()
        if self._had_conflict:
            from paimon_tpu.metrics import MULTIHOST_COMMIT_RETRIES
            self._metrics.counter(MULTIHOST_COMMIT_RETRIES).inc()
        return sid

    def _commit_coordinator(self, msgs, commit_identifier, props
                            ) -> Optional[int]:
        """Elected-committer arbitration: gather every process's
        commit messages over the mesh, the committer publishes ONE
        snapshot per global checkpoint, everyone barriers on the
        result (reference committer-operator singleton).  The wire is
        pickle over the padded allgather — trusted same-binary
        processes of one mesh, never external input."""
        payloads = MH.allgather_bytes(pickle.dumps(list(msgs)))
        sid = None
        if self.process_index == self.committer_index:
            all_msgs = [m for pl in payloads for m in pickle.loads(pl)]
            sid = self._commit.commit(all_msgs, commit_identifier,
                                      properties=props)
        MH.barrier("multihost-commit")
        if sid is None:
            sid = self.table.snapshot_manager.latest_snapshot_id()
        return sid

    def filter_committed(self, identifiers: Sequence[int]) -> List[int]:
        """Exactly-once replay dedup against this plane's commit user
        (coordinator: the shared committer user)."""
        return self._commit.filter_committed(identifiers)

    # -- scans ---------------------------------------------------------------

    def pinned_scan(self):
        """(snapshot_id, my split share) — see pinned_scan_plan."""
        return pinned_scan_plan(self.table, self.process_index,
                                self.process_count)

    def scan_to_arrow(self) -> pa.Table:
        """Read this process's pinned split share as one Arrow table
        (empty table with the right schema when nothing is owned)."""
        sid, splits = self.pinned_scan()
        read = self.table.new_read_builder().new_read()
        tables = [read.read_split(s) for s in splits]
        if not tables:
            return self.table.arrow_schema().empty_table()
        return pa.concat_tables(tables, promote_options="none")

    # -- online rescale ------------------------------------------------------

    def rescale_buckets(self, new_buckets: int) -> Optional[int]:
        """Change the bucket count under live write traffic:
        drain-and-handoff.  Every process drains and publishes its
        pending rows under the OLD ownership map (arbitrated commit =
        barrier included), the elected process rewrites the table to
        `new_buckets` (parallel/rescale.py), a barrier publishes the
        handoff, and every process reopens its writers under the NEW
        map (version bumped; moved owners counted as
        ownership_handoffs).  Returns the rescale snapshot id as this
        process observes it."""
        if self._closed:
            raise RuntimeError("write plane is closed")
        # preconditions checked on EVERY process BEFORE any barrier:
        # a committer-only failure would strand the peers inside
        # sync_global_devices (and a hard-died peer SIGABRTs the rest
        # at shutdown) — validation errors must raise identically
        # everywhere, with the plane still usable
        if new_buckets < 1:
            raise ValueError(f"new_buckets must be >= 1, got "
                             f"{new_buckets}")
        if self.table.schema.partition_keys:
            raise OwnershipError(
                "rescale of partitioned tables is per-partition and "
                "not supported by the distributed plane")
        # 1. drain: nothing written under the old layout may still be
        # buffered when the layout changes
        self.commit()
        old_map = self.ownership
        new_map = OwnershipMap(old_map.version + 1, self.process_count,
                               new_buckets)
        new_history = self.history.with_map(new_map)
        # an EMPTY drained table has nothing to rewrite —
        # rescale_table_buckets would no-op WITHOUT the schema change
        # and every process would then fail the post-handoff bucket
        # check; the rescale of an empty table is just the schema
        # change.  Every process reads the same post-drain tip (the
        # commit barrier ordered all drains before this), so the
        # branch is deterministic across the mesh.
        tip = self.table.snapshot_manager.latest_snapshot()
        empty = tip is None or tip.total_record_count == 0
        # 2. the rewrite.  On a REAL multi-host mesh every host
        # rewrites only the new buckets it will OWN under the bumped
        # map: each host reads the same drained tip, computes the
        # (pure, key-hash) routing on its HOST-LOCAL devices, writes
        # its owned buckets' files, and ships the resulting commit
        # messages to the elected committer over the allgather — the
        # rewrite IO shards N-ways and only the snapshot publication
        # is elected.  (A global-mesh routing program issued by one
        # process would desynchronize the peers' gloo collective
        # streams; host-local meshes keep the collective orders
        # independent.)  Fake topologies (explicit process_index/count
        # inside ONE real process, where the allgather degrades to
        # [self]) keep the elected full rewrite — sharding there would
        # silently drop the other fake processes' buckets.
        # The overwrite snapshot itself carries the NEW map's version
        # properties, so a process restarting between the rescale and
        # the first post-rescale commit resumes the bumped generation
        # instead of regressing to the drain commit's
        import jax
        sharded_rewrite = (not empty and self.process_count > 1
                           and jax.process_count() == self.process_count)
        self.last_rescale_written_buckets: List[int] = []
        if empty:
            if self.process_index == self.committer_index:
                from paimon_tpu.schema import SchemaChange, SchemaManager
                SchemaManager(
                    self.table.file_io, self.table.path,
                    self.table.branch).commit_changes(
                        SchemaChange.set_option("bucket",
                                                str(new_buckets)))
        elif sharded_rewrite:
            from jax.sharding import Mesh

            from paimon_tpu.parallel.rescale import (
                rescale_commit, rescale_routing, rescale_write_messages,
            )
            local = Mesh(np.asarray(jax.local_devices()), ("buckets",))
            values = self.table.to_arrow()
            routing = rescale_routing(self.table, values, new_buckets,
                                      mesh=local)
            mine = [b for b in routing
                    if new_map.owner_of((), int(b))
                    == self.process_index]
            msgs = rescale_write_messages(self.table, values, routing,
                                          new_buckets, buckets=mine)
            self.last_rescale_written_buckets = sorted(
                int(m.bucket) for m in msgs)
            payloads = MH.allgather_bytes(pickle.dumps(list(msgs)))
            if self.process_index == self.committer_index:
                all_msgs = [m for pl in payloads
                            for m in pickle.loads(pl)]
                rescale_commit(self.table, new_buckets, all_msgs,
                               properties=new_history.to_properties())
        elif self.process_index == self.committer_index:
            from jax.sharding import Mesh
            local = Mesh(np.asarray(jax.local_devices()),
                         ("buckets",))
            sid = self.table.rescale_buckets(
                new_buckets, mesh=local,
                properties=new_history.to_properties())
            if sid is not None:
                self.last_rescale_written_buckets = sorted(
                    range(new_buckets))
        MH.barrier("multihost-rescale")
        # 3. handoff: reopen against the new schema generation,
        # re-applying the load-time dynamic options copy() would drop
        # (minus any stale dynamic bucket override — the rescaled
        # schema is authoritative for the bucket count)
        self._write.close()
        dyn = {k: v for k, v in self._dynamic_opts.items()
               if k != "bucket"}
        self.table = self.table.copy(dyn)
        if self.table.options.bucket != new_buckets:
            raise OwnershipError(
                f"rescale handoff: table reports bucket="
                f"{self.table.options.bucket}, expected {new_buckets}")
        self.ownership = new_map
        self.history = new_history
        from paimon_tpu.metrics import MULTIHOST_OWNERSHIP_HANDOFFS
        moved = old_map.handoffs_to(self.ownership)
        if moved:
            self._metrics.counter(MULTIHOST_OWNERSHIP_HANDOFFS).inc(
                moved)
        self._open_writer()
        if empty:
            # the empty branch produced no snapshot to carry the new
            # generation: stamp it with one forced empty snapshot so
            # a restart before the first post-rescale commit still
            # resumes the bumped version (same guarantee as the
            # overwrite branch)
            if self.process_index == self.committer_index:
                self._commit._commit.commit(
                    [], properties=self.history.to_properties(),
                    force_create=True)
            MH.barrier("multihost-rescale-stamp")
        return self.table.snapshot_manager.latest_snapshot_id()

    # -- lifecycle -----------------------------------------------------------

    def close(self):
        if not self._closed:
            self._closed = True
            self._write.close()

    def __enter__(self) -> "DistributedWritePlane":
        return self

    def __exit__(self, *exc):
        self.close()
        return False
