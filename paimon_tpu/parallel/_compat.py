"""jax version compatibility for the scale-out plane.

`jax.shard_map` became a top-level export only in newer jax; on the
pinned 0.4.x line it lives at `jax.experimental.shard_map.shard_map`
with the same signature.  Every mesh program in parallel/ resolves it
through here so the plane runs on both.
"""

from __future__ import annotations

__all__ = ["shard_map"]


def shard_map(*args, **kwargs):
    """Call-through to the available shard_map implementation."""
    import jax

    fn = getattr(jax, "shard_map", None)
    if fn is None:
        from jax.experimental.shard_map import shard_map as fn
    return fn(*args, **kwargs)
