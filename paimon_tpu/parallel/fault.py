"""Fault classification + per-bucket retry policy for the maintenance
plane.

The mesh compaction engine (parallel/mesh_engine.py) treats a bucket as
its failure domain: a transient error anywhere in one bucket's window
stream — reading a sorted run, the device window kernel, writing or
rolling an output file — aborts and retries THAT bucket with capped
decorrelated-jitter backoff, and after `compaction.retry.max-attempts`
degrades it to the single-chip compact/manager.py path instead of
failing the whole job.  The degradation ladder is:

    mesh window stream  ->  retry (x max-attempts, jittered backoff)
                        ->  single-chip fallback (compaction.mesh.fallback)
                        ->  raise (bucket unrecoverable; job fails)

Only *transient* errors ride the ladder.  Programming errors
(ValueError, KeyError, schema bugs) propagate immediately — retrying
them would loop deterministically and degrade silently.
"""

from __future__ import annotations

import random
from dataclasses import dataclass
from typing import Optional

from paimon_tpu.options import CoreOptions

__all__ = ["is_transient_error", "BucketRetryPolicy"]

# error class NAMES treated as device/lane loss: jax surfaces device
# failures as jaxlib XlaRuntimeError (a RuntimeError subclass we must
# not import at module scope — jax loads lazily everywhere else)
_DEVICE_ERROR_NAMES = frozenset({"XlaRuntimeError"})


def is_transient_error(exc: BaseException) -> bool:
    """True when `exc` is worth retrying: a store-side 503
    (TransientStoreError), an IO fault (OSError covers InjectedIOError
    and FileNotFoundError from racing maintenance), or a device/lane
    loss (XlaRuntimeError).

    DECODE errors are excluded even though they reach us as OSError
    (modern pyarrow raises plain OSError for torn footers / corrupt
    compressed pages): the format readers re-tag decode-phase failures
    as CorruptDataError — deterministic bad bytes, pointless to retry,
    and on the scan path they must stay eligible for the
    scan.ignore-corrupt-files skip.  ArrowException covers the
    ArrowInvalid flavors for completeness.
    """
    import pyarrow as pa

    from paimon_tpu.format.format import CorruptDataError
    from paimon_tpu.fs.object_store import TransientStoreError
    from paimon_tpu.utils.deadline import DeadlineExceededError

    if isinstance(exc, (CorruptDataError, pa.ArrowException)):
        return False
    if isinstance(exc, DeadlineExceededError):
        # the request's end-to-end budget is spent: retrying can only
        # waste a sick backend's capacity on a caller that is gone
        return False
    if isinstance(exc, (TransientStoreError, OSError)):
        return True
    return any(t.__name__ in _DEVICE_ERROR_NAMES
               for t in type(exc).__mro__)


@dataclass
class BucketRetryPolicy:
    """`compaction.retry.*` + `compaction.mesh.fallback` in one bundle."""

    max_attempts: int = 3
    backoff_base_ms: float = 10.0
    fallback: bool = True
    rng: Optional[random.Random] = None

    @classmethod
    def from_options(cls, options: CoreOptions) -> "BucketRetryPolicy":
        return cls(
            max_attempts=options.get(
                CoreOptions.COMPACTION_RETRY_MAX_ATTEMPTS),
            backoff_base_ms=options.get(
                CoreOptions.COMPACTION_RETRY_BACKOFF),
            fallback=options.get(CoreOptions.COMPACTION_MESH_FALLBACK))

    def new_backoff(self):
        from paimon_tpu.utils.backoff import Backoff
        return Backoff(self.backoff_base_ms, rng=self.rng)

    def retry_call(self, fn, *, on_retry=None):
        """Run `fn` under this policy: transient errors retry with
        backoff up to max_attempts total attempts, then re-raise.
        Non-transient errors propagate immediately.  Each backoff
        sleep is a traced span (obs/trace.py) carrying the attempt
        number and error class, so retry storms are visible on the
        timeline instead of reading as unexplained gaps."""
        backoff = self.new_backoff()
        attempt = 0
        while True:
            attempt += 1
            try:
                return fn()
            except BaseException as e:      # noqa: BLE001
                if not is_transient_error(e) or \
                        attempt >= max(1, self.max_attempts):
                    raise
                if on_retry is not None:
                    on_retry(attempt, e)
                from paimon_tpu.obs.flight import EV_RETRY, record
                record(EV_RETRY, attempt=attempt,
                       error=type(e).__name__)
                from paimon_tpu.obs.trace import span
                with span("retry.backoff", cat="compaction",
                          attempt=attempt, error=type(e).__name__):
                    backoff.pause()
