"""Pipelined write/ingest flush executor.

The serial write path runs every per-(partition,bucket) flush — sort
the buffered batches, merge spills, encode parquet, upload — inline on
the caller's thread: the object store sits idle while the sort/encode
runs and the CPU sits idle during uploads.  This module is the write
path's counterpart of `scan_pipeline.py`: a bounded producer-consumer
pool that overlaps bucket k's encode+upload with bucket k+1's sort and
with the incoming batch's hash/group-by on the caller thread.

    write() ──► snapshot buffers (+ seq reserved HERE, single-threaded)
       │              │ submit(bucket_key, est_bytes, task)
       ▼              ▼
    byte budget ◄── [ FlushPool: per-bucket actor queues over a
                      shared worker pool (sort/encode/upload) ]
       ▲              │
       └─ prepare_commit() = drain() barrier, then assemble messages

Design points:

* **per-bucket ordering**: tasks for the same (partition, bucket) run
  strictly in submission order through a per-key "actor" queue, so
  file metas / spill runs / changelog files publish deterministically;
  tasks for different keys run on up to `write.flush.parallelism`
  workers (Arrow encode and file IO release the GIL);
* **byte budget**: `submit` blocks the producer while the estimated
  buffered bytes in flight exceed `write.flush.max-bytes` — hard
  backpressure, with at least one task always admitted so a budget
  below one buffer cannot deadlock;
* **fault policy**: transient store faults inside a flush retry under
  `write.retry.*` via the parallel/fault.py taxonomy +
  utils/backoff.py (see `flush_retrying`); an exhausted or
  non-transient error is latched and re-raised at the `drain()`
  barrier with all still-queued tasks cancelled — a flush is NEVER
  silently dropped;
* **serial fast path**: parallelism 1 runs every task inline on the
  caller thread, byte-for-byte the legacy write path.

Everything that writes batches routes through here: the pk and append
file-store writes (core/write.py, core/append.py) and therefore
`TableWrite` (table/table.py), the SQL executor's INSERT/UPDATE/DELETE
paths, the CDC sink, the ingest topology and the integrations.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Callable, Dict, Optional

from paimon_tpu.options import CoreOptions

__all__ = ["FlushPool", "flush_retrying", "lpt_order",
           "resolve_flush_parallelism"]


def lpt_order(groups):
    """Largest (partition,bucket) group first — row count stands in
    for estimated bytes; the same longest-processing-time discipline
    as parallel/packing.py, shared by the pk and append dispatchers so
    the cost estimate cannot drift between them.  The flush pool
    receives skewed buckets' work first, overlapping the hot bucket's
    encode+upload with all the small ones instead of trailing it as
    the long tail.  Stable sort: equal sizes keep grouping order."""
    return sorted(groups, key=lambda g: -len(g[1]))


def resolve_flush_parallelism(options: Optional[CoreOptions]) -> int:
    """Worker threads for the pipelined write: write.flush.parallelism,
    defaulting to min(8, cpu count).  1 means the serial inline path."""
    par = None
    if options is not None:
        par = options.get(CoreOptions.WRITE_FLUSH_PARALLELISM)
    if par is None:
        par = min(8, os.cpu_count() or 1)
    return max(1, int(par))


def flush_retrying(fn: Callable[[], object],
                   options: Optional[CoreOptions],
                   what: str = "bucket flush"):
    """Run one flush-granularity operation under write.retry.*.

    Transient store faults (fault.py taxonomy: 503 TransientStoreError,
    OSError IO faults) retry with capped decorrelated-jitter backoff up
    to write.retry.max-attempts total attempts, then re-raise the
    original error.  Non-transient errors propagate immediately.  The
    retried `fn` must be restartable from the top: flush closures
    publish their outputs (file metas, spill paths) only after the
    write succeeded, and every attempt picks fresh file names, so a
    half-written attempt leaves only orphan files for maintenance."""
    from paimon_tpu.parallel.fault import is_transient_error
    from paimon_tpu.utils.backoff import Backoff

    if options is not None:
        attempts = options.get(CoreOptions.WRITE_RETRY_MAX_ATTEMPTS)
        base_ms = options.get(CoreOptions.WRITE_RETRY_BACKOFF)
    else:
        attempts = CoreOptions.WRITE_RETRY_MAX_ATTEMPTS.default
        base_ms = CoreOptions.WRITE_RETRY_BACKOFF.default
    attempts = max(1, attempts)
    backoff = None
    attempt = 0
    while True:
        attempt += 1
        try:
            return fn()
        except Exception as e:      # noqa: BLE001 — reclassified below
            if not is_transient_error(e) or attempt >= attempts:
                raise
            from paimon_tpu.metrics import WRITE_RETRIES, global_registry
            global_registry().write_metrics() \
                .counter(WRITE_RETRIES).inc()
            if backoff is None:
                backoff = Backoff(base_ms)
            from paimon_tpu.obs.trace import span as _span
            with _span("retry.backoff", cat="write", attempt=attempt,
                       what=what, error=type(e).__name__):
                backoff.pause()


class FlushPool:
    """Bounded flush executor with per-key FIFO ordering.

    `submit(key, est_bytes, fn)` enqueues `fn` on the key's actor
    queue (strict submission order per key) and wakes a shared worker;
    it blocks the producer while the in-flight byte budget is
    exceeded.  `drain()` is the prepare-commit barrier: it waits for
    every admitted task and re-raises the first task error with the
    remaining queued tasks cancelled AND the pool poisoned — the
    cancelled payloads are unrecoverable, so the owning writer must be
    closed and replaced rather than retried (see `drain`).
    `shutdown()` joins the workers; no threads outlive the owner.
    """

    def __init__(self, parallelism: int, max_bytes: int,
                 options: Optional[CoreOptions] = None):
        self.parallelism = max(1, int(parallelism))
        self.max_bytes = max(1, int(max_bytes))
        self.options = options
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queues: Dict[object, deque] = {}
        self._active: set = {*()}
        self._inflight_bytes = 0
        self._inflight_tasks = 0
        self._error: Optional[BaseException] = None
        self._poisoned: Optional[BaseException] = None
        self._pool = None
        self._shut = False
        # observability for tests/benchmarks (mirrors scan stats)
        self.peak_inflight_bytes = 0
        self.max_inflight_tasks = 0
        self.submitted = 0
        from paimon_tpu.metrics import (
            WRITE_FLUSHED_BYTES, WRITE_FLUSHES, WRITE_FLUSH_WAIT_MS,
            WRITE_INFLIGHT_BYTES, global_registry,
        )
        group = global_registry().write_metrics()
        self._c_flushes = group.counter(WRITE_FLUSHES)
        self._c_bytes = group.counter(WRITE_FLUSHED_BYTES)
        self._c_wait = group.counter(WRITE_FLUSH_WAIT_MS)
        self._g_inflight = group.gauge(WRITE_INFLIGHT_BYTES)
        from paimon_tpu.obs import trace as _trace
        _trace.sync_from_options(options)

    @classmethod
    def from_options(cls, options: Optional[CoreOptions]) -> "FlushPool":
        par = resolve_flush_parallelism(options)
        if options is not None:
            max_bytes = options.get(CoreOptions.WRITE_FLUSH_MAX_BYTES)
        else:
            max_bytes = CoreOptions.WRITE_FLUSH_MAX_BYTES.default
        return cls(par, max_bytes, options)

    @property
    def serial(self) -> bool:
        return self.parallelism <= 1

    # -- producer side -------------------------------------------------------

    def submit(self, key, est_bytes: int, fn: Callable[[], None]):
        """Admit one flush task for `key`.  Serial pools run it inline
        (errors propagate immediately, exactly like the legacy path)."""
        est_bytes = max(1, int(est_bytes))
        self._c_flushes.inc()
        self._c_bytes.inc(est_bytes)
        self.submitted += 1
        if self.serial:
            self.peak_inflight_bytes = max(self.peak_inflight_bytes,
                                           est_bytes)
            self.max_inflight_tasks = max(self.max_inflight_tasks, 1)
            self._run_task(key, fn)
            return
        with self._cond:
            self._check_poisoned()
            if self._error is not None:
                raise self._first_error()
            # backpressure: block while over budget, unless the pool is
            # empty (always admit one so a small budget cannot stall)
            waited = None
            wait_span = None
            try:
                while self._inflight_tasks > 0 and \
                        self._inflight_bytes + est_bytes > self.max_bytes:
                    if waited is None:
                        waited = time.perf_counter()
                        from paimon_tpu.obs.trace import span as _span
                        wait_span = _span("write.flush_wait",
                                          cat="write", key=key,
                                          est_bytes=est_bytes)
                        wait_span.__enter__()
                    self._cond.wait(timeout=0.5)
                    if self._error is not None:
                        raise self._first_error()
            finally:
                # always close the span (KeyboardInterrupt included) or
                # the producer thread's contextvar keeps a dead parent
                if wait_span is not None:
                    wait_span.__exit__(None, None, None)
            if waited is not None:
                self._c_wait.inc(
                    int((time.perf_counter() - waited) * 1000))
            self._inflight_bytes += est_bytes
            self._inflight_tasks += 1
            self.peak_inflight_bytes = max(self.peak_inflight_bytes,
                                           self._inflight_bytes)
            self.max_inflight_tasks = max(self.max_inflight_tasks,
                                          self._inflight_tasks)
            self._g_inflight.set(self._inflight_bytes)
            self._queues.setdefault(key, deque()).append((est_bytes, fn))
            if key not in self._active:
                self._active.add(key)
                self._ensure_pool().submit(self._drain_key, key)

    def drain(self):
        """Barrier: wait for every admitted task; re-raise the first
        task error with the remaining queued tasks cancelled.  A drain
        that raised POISONS the pool: the cancelled tasks' payloads
        (snapshots already detached from their writers, sequence ranges
        already reserved) are gone, so a retried prepare on the same
        writer would commit with rows silently missing — every later
        submit/drain raises instead; the caller must close this writer
        and start a fresh one."""
        if self.serial:
            return
        with self._cond:
            self._check_poisoned()
            while self._inflight_tasks > 0 and self._error is None:
                self._cond.wait(timeout=0.5)
            if self._error is not None:
                # cancel everything still queued, then wait for the
                # running tasks to finish so state stops mutating
                for q in self._queues.values():
                    while q:
                        est, _ = q.popleft()
                        self._inflight_bytes -= est
                        self._inflight_tasks -= 1
                while self._inflight_tasks > 0:
                    self._cond.wait(timeout=0.5)
                self._g_inflight.set(self._inflight_bytes)
                err, self._error = self._error, None
                self._poisoned = err
                raise err

    def _check_poisoned(self):
        if self._poisoned is not None:
            raise RuntimeError(
                "write pipeline failed earlier and in-flight flushes "
                "were cancelled; close this writer and retry with a "
                "fresh one") from self._poisoned

    def shutdown(self, wait: bool = True):
        with self._cond:
            self._shut = True
            pool, self._pool = self._pool, None
        if pool is not None:
            pool.shutdown(wait=wait, cancel_futures=True)
        from paimon_tpu.obs import trace as _trace
        _trace.maybe_export()

    # -- worker side ---------------------------------------------------------

    def _run_task(self, key, fn):
        """One flush task (sort + encode + upload) under its span —
        per-bucket-actor tracks in the trace; sort/encode/upload child
        spans come from core/write.py and format/format.py."""
        from paimon_tpu.metrics import WRITE_FLUSH_TASK_MS
        from paimon_tpu.obs.trace import span
        part, bucket = key if isinstance(key, tuple) and len(key) == 2 \
            else (None, key)
        with span("write.flush", cat="write", group="write",
                  metric=WRITE_FLUSH_TASK_MS, partition=part,
                  bucket=bucket):
            flush_retrying(fn, self.options)

    def _first_error(self) -> BaseException:
        return RuntimeError("write pipeline already failed; "
                            "drain() reports the cause") \
            if self._error is None else self._error

    def _ensure_pool(self):
        if self._pool is None:
            if self._shut:
                raise RuntimeError("FlushPool is shut down")
            from paimon_tpu.parallel.executors import new_thread_pool
            self._pool = new_thread_pool(self.parallelism, "paimon-write")
        return self._pool

    def _drain_key(self, key):
        """Run `key`'s queued tasks one at a time, in order (the
        per-bucket actor: no two tasks of one bucket ever overlap)."""
        while True:
            with self._cond:
                q = self._queues.get(key)
                if not q or self._error is not None:
                    if q:
                        # pipeline failed: cancel this key's backlog
                        while q:
                            est, _ = q.popleft()
                            self._inflight_bytes -= est
                            self._inflight_tasks -= 1
                        self._g_inflight.set(self._inflight_bytes)
                    self._active.discard(key)
                    self._cond.notify_all()
                    return
                est, fn = q.popleft()
            try:
                self._run_task(key, fn)
            except BaseException as e:      # noqa: BLE001 — latched
                with self._cond:
                    if self._error is None:
                        self._error = e
            finally:
                with self._cond:
                    self._inflight_bytes -= est
                    self._inflight_tasks -= 1
                    self._g_inflight.set(self._inflight_bytes)
                    self._cond.notify_all()
