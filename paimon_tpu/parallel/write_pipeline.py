"""Pipelined write/ingest flush executor.

The serial write path runs every per-(partition,bucket) flush — sort
the buffered batches, merge spills, encode parquet, upload — inline on
the caller's thread: the object store sits idle while the sort/encode
runs and the CPU sits idle during uploads.  This module is the write
path's counterpart of `scan_pipeline.py`: a bounded producer-consumer
pool that overlaps bucket k's encode+upload with bucket k+1's sort and
with the incoming batch's hash/group-by on the caller thread.

    write() ──► snapshot buffers (+ seq reserved HERE, single-threaded)
       │              │ submit(bucket_key, est_bytes, task)
       ▼              ▼
    byte budget ◄── [ FlushPool: per-bucket actor queues over a
                      shared worker pool (sort/encode/upload) ]
       ▲              │
       └─ prepare_commit() = drain() barrier, then assemble messages

Design points:

* **per-bucket ordering**: tasks for the same (partition, bucket) run
  strictly in submission order through a per-key "actor" queue, so
  file metas / spill runs / changelog files publish deterministically;
  tasks for different keys run on up to `write.flush.parallelism`
  workers (Arrow encode and file IO release the GIL);
* **byte budget**: `submit` blocks the producer while the estimated
  buffered bytes in flight exceed `write.flush.max-bytes` — hard
  backpressure, with at least one task always admitted so a budget
  below one buffer cannot deadlock;
* **fault policy**: transient store faults inside a flush retry under
  `write.retry.*` via the parallel/fault.py taxonomy +
  utils/backoff.py (see `flush_retrying`); an exhausted or
  non-transient error is latched and re-raised at the `drain()`
  barrier with all still-queued tasks cancelled — a flush is NEVER
  silently dropped;
* **serial fast path**: parallelism 1 runs every task inline on the
  caller thread, byte-for-byte the legacy write path.

Everything that writes batches routes through here: the pk and append
file-store writes (core/write.py, core/append.py) and therefore
`TableWrite` (table/table.py), the SQL executor's INSERT/UPDATE/DELETE
paths, the CDC sink, the ingest topology and the integrations.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Callable, Dict, Optional

from paimon_tpu.options import CoreOptions

__all__ = ["FlushPool", "UploadStager", "flush_retrying", "lpt_order",
           "maybe_wrap_staging", "resolve_flush_parallelism",
           "resolve_stage_parallelism"]


def lpt_order(groups):
    """Largest (partition,bucket) group first — row count stands in
    for estimated bytes; the same longest-processing-time discipline
    as parallel/packing.py, shared by the pk and append dispatchers so
    the cost estimate cannot drift between them.  The flush pool
    receives skewed buckets' work first, overlapping the hot bucket's
    encode+upload with all the small ones instead of trailing it as
    the long tail.  Stable sort: equal sizes keep grouping order."""
    return sorted(groups, key=lambda g: -len(g[1]))


def resolve_flush_parallelism(options: Optional[CoreOptions]) -> int:
    """Worker threads for the pipelined write: write.flush.parallelism,
    defaulting to min(8, cpu count).  1 means the serial inline path."""
    par = None
    if options is not None:
        par = options.get(CoreOptions.WRITE_FLUSH_PARALLELISM)
    if par is None:
        par = min(8, os.cpu_count() or 1)
    return max(1, int(par))


def resolve_stage_parallelism(options: Optional[CoreOptions]) -> int:
    """Upload workers for staged uploads: write.stage.parallelism,
    defaulting to min(8, cpu count).  Uploads are independent PUTs to
    writer-unique names, so width here directly hides store latency."""
    par = None
    if options is not None:
        par = options.get(CoreOptions.WRITE_STAGE_PARALLELISM)
    if par is None:
        par = min(8, os.cpu_count() or 1)
    return max(1, int(par))


def maybe_wrap_staging(file_io, options: Optional[CoreOptions]):
    """(file_io, stager-or-None): when write.stage.dir is set, build
    the writer's UploadStager and wrap its FileIO in a StagingFileIO —
    the ONE construction point shared by the pk and append file-store
    writes (flush workers then encode to local SSD + fsync, the upload
    pool owns the store PUTs, and the writer drains the stager LAST in
    prepare_commit to keep the durability contract)."""
    stage_dir = options.get(CoreOptions.WRITE_STAGE_DIR) \
        if options is not None else None
    if not stage_dir:
        return file_io, None
    from paimon_tpu.fs.staging import StagingFileIO
    stager = UploadStager(stage_dir, resolve_stage_parallelism(options),
                          options)
    return StagingFileIO(file_io, stager), stager


def flush_retrying(fn: Callable[[], object],
                   options: Optional[CoreOptions],
                   what: str = "bucket flush"):
    """Run one flush-granularity operation under write.retry.*.

    Transient store faults (fault.py taxonomy: 503 TransientStoreError,
    OSError IO faults) retry with capped decorrelated-jitter backoff up
    to write.retry.max-attempts total attempts, then re-raise the
    original error.  Non-transient errors propagate immediately.  The
    retried `fn` must be restartable from the top: flush closures
    publish their outputs (file metas, spill paths) only after the
    write succeeded, and every attempt picks fresh file names, so a
    half-written attempt leaves only orphan files for maintenance."""
    from paimon_tpu.parallel.fault import is_transient_error
    from paimon_tpu.utils.backoff import Backoff

    if options is not None:
        attempts = options.get(CoreOptions.WRITE_RETRY_MAX_ATTEMPTS)
        base_ms = options.get(CoreOptions.WRITE_RETRY_BACKOFF)
    else:
        attempts = CoreOptions.WRITE_RETRY_MAX_ATTEMPTS.default
        base_ms = CoreOptions.WRITE_RETRY_BACKOFF.default
    attempts = max(1, attempts)
    backoff = None
    attempt = 0
    while True:
        attempt += 1
        try:
            return fn()
        except Exception as e:      # noqa: BLE001 — reclassified below
            if not is_transient_error(e) or attempt >= attempts:
                raise
            from paimon_tpu.metrics import WRITE_RETRIES, global_registry
            global_registry().write_metrics() \
                .counter(WRITE_RETRIES).inc()
            if backoff is None:
                backoff = Backoff(base_ms)
            from paimon_tpu.obs.trace import span as _span
            with _span("retry.backoff", cat="write", attempt=attempt,
                       what=what, error=type(e).__name__):
                backoff.pause()


class FlushPool:
    """Bounded flush executor with per-key FIFO ordering.

    `submit(key, est_bytes, fn)` enqueues `fn` on the key's actor
    queue (strict submission order per key) and wakes a shared worker;
    it blocks the producer while the in-flight byte budget is
    exceeded.  `drain()` is the prepare-commit barrier: it waits for
    every admitted task and re-raises the first task error with the
    remaining queued tasks cancelled AND the pool poisoned — the
    cancelled payloads are unrecoverable, so the owning writer must be
    closed and replaced rather than retried (see `drain`).
    `shutdown()` joins the workers; no threads outlive the owner.
    """

    def __init__(self, parallelism: int, max_bytes: int,
                 options: Optional[CoreOptions] = None):
        self.parallelism = max(1, int(parallelism))
        self.max_bytes = max(1, int(max_bytes))
        self.options = options
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queues: Dict[object, deque] = {}
        self._active: set = {*()}
        self._inflight_bytes = 0
        self._inflight_tasks = 0
        self._error: Optional[BaseException] = None
        self._poisoned: Optional[BaseException] = None
        self._pool = None
        self._shut = False
        # observability for tests/benchmarks (mirrors scan stats)
        self.peak_inflight_bytes = 0
        self.max_inflight_tasks = 0
        self.submitted = 0
        from paimon_tpu.metrics import (
            WRITE_FLUSHED_BYTES, WRITE_FLUSHES, WRITE_FLUSH_WAIT_MS,
            WRITE_INFLIGHT_BYTES, global_registry,
        )
        group = global_registry().write_metrics()
        self._c_flushes = group.counter(WRITE_FLUSHES)
        self._c_bytes = group.counter(WRITE_FLUSHED_BYTES)
        self._c_wait = group.counter(WRITE_FLUSH_WAIT_MS)
        self._g_inflight = group.gauge(WRITE_INFLIGHT_BYTES)
        from paimon_tpu.obs import trace as _trace
        _trace.sync_from_options(options)

    @classmethod
    def from_options(cls, options: Optional[CoreOptions]) -> "FlushPool":
        par = resolve_flush_parallelism(options)
        if options is not None:
            max_bytes = options.get(CoreOptions.WRITE_FLUSH_MAX_BYTES)
        else:
            max_bytes = CoreOptions.WRITE_FLUSH_MAX_BYTES.default
        return cls(par, max_bytes, options)

    @property
    def serial(self) -> bool:
        return self.parallelism <= 1

    # -- producer side -------------------------------------------------------

    def submit(self, key, est_bytes: int, fn: Callable[[], None]):
        """Admit one flush task for `key`.  Serial pools run it inline
        (errors propagate immediately, exactly like the legacy path)."""
        est_bytes = max(1, int(est_bytes))
        self._c_flushes.inc()
        self._c_bytes.inc(est_bytes)
        self.submitted += 1
        if self.serial:
            self.peak_inflight_bytes = max(self.peak_inflight_bytes,
                                           est_bytes)
            self.max_inflight_tasks = max(self.max_inflight_tasks, 1)
            self._run_task(key, fn)
            return
        with self._cond:
            self._check_poisoned()
            if self._error is not None:
                raise self._first_error()
            # backpressure: block while over budget, unless the pool is
            # empty (always admit one so a small budget cannot stall)
            waited = None
            wait_span = None
            try:
                while self._inflight_tasks > 0 and \
                        self._inflight_bytes + est_bytes > self.max_bytes:
                    # the byte-budget block honors the request
                    # deadline: the un-admitted task's rows would be
                    # lost to a retried prepare, so a tripped deadline
                    # poisons the pool like any other producer-side
                    # abort (the caller must start a fresh writer)
                    from paimon_tpu.utils.deadline import (
                        DeadlineExceededError, check_deadline,
                    )
                    try:
                        check_deadline("write byte-budget wait")
                    except DeadlineExceededError as e:
                        self._poisoned = e
                        raise
                    if waited is None:
                        waited = time.perf_counter()
                        from paimon_tpu.obs.trace import span as _span
                        wait_span = _span("write.flush_wait",
                                          cat="write", key=key,
                                          est_bytes=est_bytes)
                        wait_span.__enter__()
                    self._cond.wait(timeout=0.5)
                    if self._error is not None:
                        raise self._first_error()
            finally:
                # always close the span (KeyboardInterrupt included) or
                # the producer thread's contextvar keeps a dead parent
                if wait_span is not None:
                    wait_span.__exit__(None, None, None)
            if waited is not None:
                self._c_wait.inc(
                    int((time.perf_counter() - waited) * 1000))
            self._inflight_bytes += est_bytes
            self._inflight_tasks += 1
            self.peak_inflight_bytes = max(self.peak_inflight_bytes,
                                           self._inflight_bytes)
            self.max_inflight_tasks = max(self.max_inflight_tasks,
                                          self._inflight_tasks)
            self._g_inflight.set(self._inflight_bytes)
            self._queues.setdefault(key, deque()).append((est_bytes, fn))
            if key not in self._active:
                self._active.add(key)
                self._ensure_pool().submit(self._drain_key, key)

    def drain(self):
        """Barrier: wait for every admitted task; re-raise the first
        task error with the remaining queued tasks cancelled.  A drain
        that raised POISONS the pool: the cancelled tasks' payloads
        (snapshots already detached from their writers, sequence ranges
        already reserved) are gone, so a retried prepare on the same
        writer would commit with rows silently missing — every later
        submit/drain raises instead; the caller must close this writer
        and start a fresh one."""
        if self.serial:
            return
        with self._cond:
            self._check_poisoned()
            while self._inflight_tasks > 0 and self._error is None:
                from paimon_tpu.utils.deadline import (
                    DeadlineExceededError, check_deadline,
                )
                try:
                    check_deadline("write drain barrier")
                except DeadlineExceededError as e:
                    # cancel what never started and poison: the
                    # cancelled payloads are unrecoverable on this
                    # writer (running tasks are ABANDONED, not joined
                    # — the deadline must not wait on a hung upload)
                    for q in self._queues.values():
                        while q:
                            est, _ = q.popleft()
                            self._inflight_bytes -= est
                            self._inflight_tasks -= 1
                    self._g_inflight.set(self._inflight_bytes)
                    self._poisoned = e
                    raise
                self._cond.wait(timeout=0.5)
            if self._error is not None:
                # cancel everything still queued, then wait for the
                # running tasks to finish so state stops mutating
                for q in self._queues.values():
                    while q:
                        est, _ = q.popleft()
                        self._inflight_bytes -= est
                        self._inflight_tasks -= 1
                while self._inflight_tasks > 0:
                    self._cond.wait(timeout=0.5)
                self._g_inflight.set(self._inflight_bytes)
                err, self._error = self._error, None
                self._poisoned = err
                raise err

    def _check_poisoned(self):
        if self._poisoned is not None:
            raise RuntimeError(
                "write pipeline failed earlier and in-flight flushes "
                "were cancelled; close this writer and retry with a "
                "fresh one") from self._poisoned

    def shutdown(self, wait: bool = True):
        with self._cond:
            self._shut = True
            pool, self._pool = self._pool, None
        if pool is not None:
            pool.shutdown(wait=wait, cancel_futures=True)
        from paimon_tpu.obs import trace as _trace
        _trace.maybe_export()

    # -- worker side ---------------------------------------------------------

    def _run_task(self, key, fn):
        """One flush task (sort + encode + upload) under its span —
        per-bucket-actor tracks in the trace; sort/encode/upload child
        spans come from core/write.py and format/format.py."""
        from paimon_tpu.metrics import WRITE_FLUSH_TASK_MS
        from paimon_tpu.obs.trace import span
        part, bucket = key if isinstance(key, tuple) and len(key) == 2 \
            else (None, key)
        with span("write.flush", cat="write", group="write",
                  metric=WRITE_FLUSH_TASK_MS, partition=part,
                  bucket=bucket):
            flush_retrying(fn, self.options)

    def _first_error(self) -> BaseException:
        return RuntimeError("write pipeline already failed; "
                            "drain() reports the cause") \
            if self._error is None else self._error

    def _ensure_pool(self):
        if self._pool is None:
            if self._shut:
                raise RuntimeError("FlushPool is shut down")
            from paimon_tpu.parallel.executors import new_thread_pool
            self._pool = new_thread_pool(self.parallelism, "paimon-write")
        return self._pool

    def _drain_key(self, key):
        """Run `key`'s queued tasks one at a time, in order (the
        per-bucket actor: no two tasks of one bucket ever overlap)."""
        while True:
            with self._cond:
                q = self._queues.get(key)
                if not q or self._error is not None:
                    if q:
                        # pipeline failed: cancel this key's backlog
                        while q:
                            est, _ = q.popleft()
                            self._inflight_bytes -= est
                            self._inflight_tasks -= 1
                        self._g_inflight.set(self._inflight_bytes)
                    self._active.discard(key)
                    self._cond.notify_all()
                    return
                est, fn = q.popleft()
            try:
                self._run_task(key, fn)
            except BaseException as e:      # noqa: BLE001 — latched
                with self._cond:
                    if self._error is None:
                        self._error = e
            finally:
                with self._cond:
                    self._inflight_bytes -= est
                    self._inflight_tasks -= 1
                    self._g_inflight.set(self._inflight_bytes)
                    self._cond.notify_all()


class UploadStager:
    """Local-SSD staging between the flush workers and the object
    store (write.stage.dir; "A Host-SSD Collaborative Write
    Accelerator for LSM-Tree-Based KV Stores", arxiv 2410.21760).

    `stage(inner, path, data)` writes `data` to a staged local file
    (tmp + atomic replace on the flush worker; the upload worker
    fsyncs it just before the PUT, so "fsync, then upload" holds
    without the sync riding the per-bucket actor's critical path),
    registers it so reads of `path` can be served from the staged
    bytes while the upload is in flight (fs/staging.StagingFileIO — compaction re-reading a fresh
    L0 file inside prepare_commit never waits on the store), and hands
    the object-store PUT to a bounded upload pool.  Consequences:

    * the flush worker returns after the local fsync — encode and
      upload overlap even WITHIN one bucket (the per-bucket actor only
      serializes sort/encode/stage, not the PUTs);
    * an upload retry (write.retry.*) re-reads the staged bytes — it
      never re-sorts or re-encodes;
    * a completed upload seeds the host-SSD read tier
      (fs/caching.seed_read_cache): newly written files are the
      hottest reads;
    * `drain()` is the durability barrier: prepare_commit() calls it
      LAST, so by the time commit messages leave the writer every file
      they name is acked by the object store — the commit contract is
      byte-identical to the inline-upload path.

    Error policy mirrors FlushPool: the first upload error is latched,
    later stage() calls fail fast, drain() re-raises it with the
    stager poisoned (cancelled uploads' files are unrecoverable — the
    writer must be closed and replaced)."""

    def __init__(self, stage_dir: str, parallelism: int,
                 options: Optional[CoreOptions] = None):
        import uuid
        self.parallelism = max(1, int(parallelism))
        self.options = options
        # one private subdir per stager: concurrent writers sharing
        # write.stage.dir never collide, close() can rmtree safely
        self.dir = os.path.join(stage_dir, f"stage-{uuid.uuid4().hex}")
        os.makedirs(self.dir, exist_ok=True)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._pending: Dict[str, str] = {}      # final path -> staged
        self._inflight = 0
        self._error: Optional[BaseException] = None
        self._poisoned: Optional[BaseException] = None
        self._pool = None
        self._shut = False
        self.staged = 0                          # observability (tests)
        from paimon_tpu.metrics import (
            CACHE_DISK_STAGED_UPLOADS, global_registry,
        )
        self._c_uploads = global_registry().cache_disk_metrics() \
            .counter(CACHE_DISK_STAGED_UPLOADS)

    def accepts(self, path: str) -> bool:
        """Only immutable-named files (uuid'd data/changelog/index
        blobs) stage; mutable refs must hit the store synchronously."""
        from paimon_tpu.fs.caching import _cacheable
        return _cacheable(path)

    def stage(self, inner, path: str, data: bytes):
        """Durably stage `data` for `path` and schedule its upload.
        Called from flush workers; raises the latched upload error (if
        any) so a failing store surfaces at the next flush instead of
        only at the barrier."""
        import uuid

        from paimon_tpu.metrics import CACHE_DISK_STAGE_MS
        from paimon_tpu.obs.trace import span
        with self._cond:
            self._check_poisoned()
            if self._error is not None:
                raise self._error
        staged = os.path.join(self.dir, f"{uuid.uuid4().hex}.staged")

        def _write_staged():
            # plain atomic write on the FLUSH worker (tmp+replace so
            # pending-read racers never see a torn file); the fsync
            # happens on the UPLOAD worker just before the PUT —
            # "fsync, then upload" holds, but the sync cost rides the
            # wide upload pool instead of the per-bucket actor's
            # critical path
            tmp = f"{staged}.tmp"
            with open(tmp, "wb") as f:
                f.write(data)
            os.replace(tmp, staged)

        with span("io.stage", cat="io", group="cache_disk",
                  metric=CACHE_DISK_STAGE_MS, path=path,
                  bytes=len(data)):
            try:
                _write_staged()
            except OSError:
                # stage dir wiped mid-run: recreate once, else degrade
                # to the inline upload (staging is an accelerator, a
                # broken local disk must not fail the write)
                try:
                    os.makedirs(self.dir, exist_ok=True)
                    _write_staged()
                except OSError:
                    inner.write_bytes(path, data, overwrite=False)
                    return
        with self._cond:
            self._pending[path] = staged
            self._inflight += 1
            self.staged += 1
        self._ensure_pool().submit(self._upload, inner, path, staged)

    def pending_bytes(self, path: str) -> Optional[bytes]:
        """The staged bytes of a not-yet-acked upload, or None.  Racing
        an upload completion is safe: the staged file is unlinked only
        AFTER the store acked and the path left `_pending`, so a lost
        race falls back to the store, which now has the file."""
        with self._lock:
            staged = self._pending.get(path)
        if staged is None:
            return None
        try:
            with open(staged, "rb") as f:
                return f.read()
        except OSError:
            return None

    def pending_size(self, path: str) -> Optional[int]:
        with self._lock:
            staged = self._pending.get(path)
        if staged is None:
            return None
        try:
            return os.path.getsize(staged)
        except OSError:
            return None

    def _upload(self, inner, path: str, staged: str):
        ok = False
        try:
            # fsync BEFORE the PUT (deferred from stage(): the staged
            # bytes must be on stable storage before any object-store
            # ack can reference them), then re-read the STAGED bytes
            # (not a closure capture): the retry contract — and crash
            # evidence — live on local SSD
            with open(staged, "rb") as f:
                os.fsync(f.fileno())
                data = f.read()

            def attempt():
                try:
                    inner.write_bytes(path, data, overwrite=False)
                except FileExistsError:
                    # ambiguous earlier attempt landed (error after
                    # effect); byte-equality identifies our write —
                    # data-file payloads are writer-unique (uuid names)
                    if inner.read_bytes(path) == data:
                        return
                    raise

            flush_retrying(attempt, self.options, what="staged upload")
            from paimon_tpu.fs.caching import (
                CachingFileIO, seed_read_cache,
            )
            # seed the tier this writer's table actually READS: the
            # staged wrapper sits over the table's own CachingFileIO,
            # whose state may be private rather than the shared one
            seed_read_cache(path, data,
                            state=inner.state
                            if isinstance(inner, CachingFileIO)
                            else None)
            self._c_uploads.inc()
            ok = True
        except BaseException as e:      # noqa: BLE001 — latched
            with self._cond:
                if self._error is None:
                    self._error = e
        finally:
            with self._cond:
                self._pending.pop(path, None)
                self._inflight -= 1
                self._cond.notify_all()
            if ok:
                try:
                    os.unlink(staged)
                except OSError:
                    pass

    def drain(self):
        """The durability barrier: wait for every staged upload's ack;
        re-raise the first upload error with the stager poisoned."""
        with self._cond:
            self._check_poisoned()
            while self._inflight > 0:
                if self._shut:
                    # close(cancel_futures) left queued uploads that
                    # will never run their finally — fail fast instead
                    # of waiting on an _inflight that cannot drop
                    raise RuntimeError(
                        "UploadStager is shut down with uploads "
                        "cancelled; nothing to drain")
                from paimon_tpu.utils.deadline import (
                    DeadlineExceededError, check_deadline,
                )
                try:
                    check_deadline("staged-upload drain barrier")
                except DeadlineExceededError as e:
                    # in-flight PUTs are abandoned; the stager is
                    # poisoned so no commit message naming un-acked
                    # files can ever be assembled
                    self._poisoned = e
                    raise
                self._cond.wait(timeout=0.5)
            if self._error is not None:
                err, self._error = self._error, None
                self._poisoned = err
                raise err

    def _check_poisoned(self):
        if self._poisoned is not None:
            raise RuntimeError(
                "staged uploads failed earlier; close this writer and "
                "retry with a fresh one") from self._poisoned

    def _ensure_pool(self):
        with self._lock:
            if self._pool is None:
                if self._shut:
                    raise RuntimeError("UploadStager is shut down")
                from paimon_tpu.parallel.executors import new_thread_pool
                self._pool = new_thread_pool(self.parallelism,
                                             "paimon-stage")
            return self._pool

    def close(self):
        import shutil
        with self._cond:
            self._shut = True
            pool, self._pool = self._pool, None
            self._cond.notify_all()      # wake any drain() to fail fast
        if pool is not None:
            pool.shutdown(wait=True, cancel_futures=True)
        shutil.rmtree(self.dir, ignore_errors=True)
