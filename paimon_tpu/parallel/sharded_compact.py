"""End-to-end sharded bucket compaction over a device mesh.

reference: compaction parallelism is one JVM task per bucket
(mergetree/compact/MergeTreeCompactTask.java:83 scheduled by
flink sink topologies via table/sink/ChannelComputer.java).  The TPU
layout runs EVERY bucket's compaction in one mesh program instead:

  host:   decode each bucket's sorted runs (Arrow, variable-length data
          stays on host) and encode fixed-width key lanes
  device: [B, N] bucket-stacked lanes sharded over the mesh axis; each
          device sort-merges its buckets (vmapped segmented kernel) and
          computes the COMMIT STATISTICS on device: per-bucket output
          row counts, live-row counts (delete kinds excluded) and the
          psum'd totals that the commit message needs
  host:   takes winner indices per bucket, encodes output files, and
          commits compact_before/compact_after in one snapshot

So the merge AND the bookkeeping reductions ride the mesh; only
file IO and Arrow assembly stay on host.
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional, Tuple

import numpy as np

__all__ = ["ShardedCompactStats", "compact_table_sharded"]


class _ShardedCompactKernel:
    """shard_map(vmap(segmented merge)) + device-side stats reductions.

    __call__(lanes[B,N,L], seq_hi, seq_lo, invalid, kinds[B,N]) ->
    (perm[B,N], winner[B,N], live[B,N],
     per_bucket_out[B], total_out, total_live) — totals psum'd over the
    mesh and replicated."""

    def __init__(self, mesh, num_lanes: int, axis: str = "buckets"):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from paimon_tpu.ops.merge import segmented_merge_body
        from paimon_tpu.parallel._compat import shard_map

        self.mesh = mesh
        self.axis = axis
        self.sharding = NamedSharding(mesh, P(axis))
        self.replicated = NamedSharding(mesh, P())
        self._n_dev = mesh.shape[axis]

        def per_bucket(lanes, seq_hi, seq_lo, invalid, kinds):
            perm, winner, _ = segmented_merge_body(
                [lanes[:, i] for i in range(num_lanes)],
                seq_hi, seq_lo, invalid, "last")
            # kinds travel in input order; gather to sorted order so the
            # winner mask lines up (0=+I, 2=+U survive full compaction)
            s_kinds = kinds[perm]
            live = winner & ((s_kinds == 0) | (s_kinds == 2))
            return perm, winner, live

        @partial(shard_map, mesh=mesh,
                 in_specs=(P(axis), P(axis), P(axis), P(axis), P(axis)),
                 out_specs=(P(axis), P(axis), P(axis), P(axis), P(), P()))
        def step(lanes, seq_hi, seq_lo, invalid, kinds):
            perm, winner, live = jax.vmap(per_bucket)(
                lanes, seq_hi, seq_lo, invalid, kinds)
            per_bucket_out = jnp.sum(live, axis=1, dtype=jnp.int64)
            total_out = jax.lax.psum(jnp.sum(winner, dtype=jnp.int64),
                                     self.axis)
            total_live = jax.lax.psum(jnp.sum(per_bucket_out), self.axis)
            return (perm, winner, live, per_bucket_out,
                    total_out.reshape(1), total_live.reshape(1))

        self._fn = jax.jit(step)

    def __call__(self, lanes, seq_hi, seq_lo, invalid, kinds):
        import jax

        b = lanes.shape[0]
        pad = (-b) % self._n_dev
        if pad:
            def ext(a, fill=0):
                shape = (pad,) + a.shape[1:]
                return np.concatenate(
                    [a, np.full(shape, fill, a.dtype)])
            lanes, seq_hi, seq_lo = ext(lanes), ext(seq_hi), ext(seq_lo)
            invalid = ext(invalid, 1)
            kinds = ext(kinds)
        args = [jax.device_put(a, self.sharding)
                for a in (lanes, seq_hi, seq_lo, invalid, kinds)]
        out = self._fn(*args)
        jax.block_until_ready(out)
        perm, winner, live, per_bucket, total, total_live = out
        return (np.asarray(perm)[:b], np.asarray(live)[:b],
                np.asarray(per_bucket)[:b], int(np.asarray(total)[0]),
                int(np.asarray(total_live)[0]))


class ShardedCompactStats:
    def __init__(self, buckets: int, input_rows: int, output_rows: int,
                 total_winners: int, snapshot_id: Optional[int]):
        self.buckets = buckets
        self.input_rows = input_rows
        self.output_rows = output_rows
        self.total_winners = total_winners
        self.snapshot_id = snapshot_id


_KERNEL_CACHE: dict = {}


def compact_table_sharded(table, mesh=None,
                          axis: str = "buckets") -> ShardedCompactStats:
    """Full compaction of every bucket of a primary-key table in one
    mesh program: read -> sharded merge + device stats -> encode ->
    COMPACT commit.  The deduplicate winner select runs vmapped per
    bucket with bucket-axis sharding; commit row counts come from the
    device psum, not host recounting."""
    import pyarrow as pa

    from paimon_tpu.core.kv_file import KeyValueFileWriter, read_kv_file
    from paimon_tpu.core.read import MergeFileSplitRead, assemble_runs
    from paimon_tpu.core.write import CommitMessage
    from paimon_tpu.core.commit import FileStoreCommit
    from paimon_tpu.ops.merge import KIND_COL, SEQ_COL
    from paimon_tpu.parallel.sharded_merge import (
        bucket_mesh, pad_bucket_batches,
    )
    from paimon_tpu.options import CoreOptions

    # this legacy path hard-codes the deduplicate winner select; any
    # other engine must fail loudly instead of silently deduping while
    # callers migrate to parallel/mesh_engine.compact_table_mesh
    from paimon_tpu.parallel.mesh_engine import UnsupportedMergeEngineError
    from paimon_tpu.options import MergeEngine
    engine = table.options.merge_engine
    if engine != MergeEngine.DEDUPLICATE:
        raise UnsupportedMergeEngineError(
            f"compact_table_sharded only implements merge-engine "
            f"'deduplicate', got {engine!r}; use "
            f"parallel.mesh_engine.compact_table_mesh, which dispatches "
            f"on the merge engine")
    if not table.primary_keys:
        raise ValueError("sharded compaction targets primary-key tables")
    if mesh is None:
        mesh = bucket_mesh(axis=axis)
    plan = table.new_read_builder().new_scan().plan()
    splits = [s for s in plan.splits if len(s.data_files) > 0]
    if not splits:
        return ShardedCompactStats(0, 0, 0, 0, None)

    reader = MergeFileSplitRead(table.file_io, table.path, table.schema,
                                table.options)
    encoder = reader.key_encoder
    lanes_list, seq_list, kinds_list, tables = [], [], [], []
    n_input = 0
    for s in splits:
        runs_meta = assemble_runs(s.data_files)
        runs = []
        for run_files in runs_meta:
            for f in run_files:
                runs.append(read_kv_file(
                    reader.file_io, reader.path_factory, s.partition,
                    s.bucket, f, None, None, schema=table.schema,
                    schema_manager=table.schema_manager))
        t = pa.concat_tables(runs, promote_options="none")
        lanes, _ = encoder.encode_table(t, reader.key_cols)
        seq = np.asarray(t.column(SEQ_COL).combine_chunks()
                         .cast(pa.int64()))
        kinds = np.asarray(t.column(KIND_COL).combine_chunks()
                           .cast(pa.int8()))
        lanes_list.append(lanes)
        seq_list.append(seq)
        kinds_list.append(kinds)
        tables.append(t)
        n_input += t.num_rows

    lanes, seq_hi, seq_lo, invalid = pad_bucket_batches(lanes_list,
                                                        seq_list)
    n_pad = lanes.shape[1]
    kinds = np.zeros((lanes.shape[0], n_pad), dtype=np.int8)
    for i, k in enumerate(kinds_list):
        kinds[i, :len(k)] = k

    key = (mesh, lanes.shape[2], axis)
    kernel = _KERNEL_CACHE.get(key)
    if kernel is None:
        kernel = _KERNEL_CACHE[key] = _ShardedCompactKernel(
            mesh, lanes.shape[2], axis)
    perm, live, per_bucket, total_win, total_live = kernel(
        lanes, seq_hi, seq_lo, invalid, kinds)

    # host: take winners per bucket, roll output files, build the commit
    writer = KeyValueFileWriter(
        table.file_io, reader.path_factory, table.schema,
        file_format=table.options.file_format,
        compression=table.options.file_compression,
        target_file_size=table.options.target_file_size,
        index_spec=table.options.file_index_spec,
        bloom_fpp=table.options.get(CoreOptions.FILE_INDEX_BLOOM_FPP),
        format_per_level=table.options.file_format_per_level,
        format_options=table.options.format_options,
        **table.options.kv_writer_kwargs())
    max_level = table.options.max_level
    messages = []
    out_rows = 0
    for i, s in enumerate(splits):
        win_pos = np.flatnonzero(live[i])
        indices = perm[i][win_pos].astype(np.int64)
        merged = tables[i].take(pa.array(indices))
        out_rows += merged.num_rows
        after = writer.write(s.partition, s.bucket, merged,
                             level=max_level) if merged.num_rows else []
        messages.append(CommitMessage(
            s.partition, s.bucket, s.total_buckets,
            compact_before=list(s.data_files), compact_after=after))
    assert out_rows == total_live, (out_rows, total_live)

    commit = FileStoreCommit(table.file_io, table.path, table.schema,
                             table.options, branch=table.branch)
    sid = commit.commit(messages)
    return ShardedCompactStats(len(splits), n_input, out_rows,
                               total_win, sid)
