"""Streaming mesh compaction engine: every merge engine, bounded
key-windows, skew-aware bucket packing.

Replaces the monolithic pad-everything path in sharded_compact.py for
table-level mesh compaction.  Three deltas over that path:

1. ENGINE DISPATCH.  The window kernel is parameterized on the table's
   merge engine — deduplicate, partial-update (incl. sequence groups),
   aggregation and first-row — instead of hard-coding the deduplicate
   winner select.  Deduplicate/first-row consume the kernel's winner
   mask directly; aggregation/partial-update feed the kernel's sorted
   order + segment boundaries into the SAME aggregation epilogue the
   single-chip path runs (ops/agg.py aggregate_sorted_segments), so
   mesh output is row-identical to single-chip output by construction.
   Any other engine raises UnsupportedMergeEngineError — never a
   silent dedup.

2. BOUNDED WINDOWS.  Buckets stream through the mesh in key windows
   (ops/merge_stream.py iter_merge_windows lifted to [B, window]): each
   mesh step stacks one window per device lane, so a 100M-row bucket
   compacts under a host-RAM budget of ~ runs x window-rows per bucket
   (Krueger et al., "Fast Updates on Read-Optimized Databases Using
   Multi-Core CPUs": bounded multi-pass merges beat whole-table
   materialization exactly here).  Window row counts pad to the next
   power of two, so XLA compiles O(log) shapes per engine run.

3. SKEW-AWARE PACKING.  Buckets pack onto mesh lanes by manifest row
   counts with a greedy LPT bin-packer (parallel/packing.py) — one
   lane per device — so a hot bucket no longer pads every lane to its
   size; it occupies one lane while cold buckets share the rest.

4. PER-BUCKET FAULT ISOLATION.  A bucket is the failure domain: a
   transient error (object-store 503, injected IO fault, lane/device
   loss) anywhere in one bucket's window stream aborts and retries
   that bucket with capped decorrelated-jitter backoff
   (compaction.retry.max-attempts / compaction.retry.backoff), then
   degrades it to the single-chip compact/manager.py path
   (compaction.mesh.fallback) instead of failing the whole job.
   Partial output files of a failed attempt are deleted before the
   retry, so the committed result is file-level identical to a
   fault-free run.  Non-transient errors propagate immediately
   (parallel/fault.py is the classification + policy).

The device still only ever sees fixed-width u32 normkey lanes + u64
sequence halves (Graefe et al.'s offset-value-coding lesson: keep the
comparison loop on fixed-width prefixes); variable-length Arrow data
stays on host, and output files roll per bucket as windows emit.
"""

from __future__ import annotations

import time as _time
from dataclasses import dataclass, field as dc_field
from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from paimon_tpu.options import ChangelogProducer, CoreOptions, MergeEngine
from paimon_tpu.parallel.packing import (
    bucket_row_counts, pack_buckets, packing_skew,
)

__all__ = ["UnsupportedMergeEngineError", "MeshCompactStats",
           "compact_table_mesh", "SUPPORTED_MERGE_ENGINES"]

SUPPORTED_MERGE_ENGINES = (
    MergeEngine.DEDUPLICATE, MergeEngine.PARTIAL_UPDATE,
    MergeEngine.AGGREGATE, MergeEngine.FIRST_ROW,
)


class UnsupportedMergeEngineError(ValueError):
    """A mesh compaction path was asked to run a merge engine it has no
    kernel for.  Raised instead of silently deduplicating (the legacy
    sharded path's failure mode)."""


@dataclass
class MeshCompactStats:
    buckets: int = 0            # buckets that needed a rewrite
    lanes: int = 0              # mesh lanes (= devices)
    input_rows: int = 0         # manifest row count over rewritten files
    output_rows: int = 0
    windows: int = 0            # device window merges executed
    peak_window_rows: int = 0   # largest single window (pre-padding)
    peak_buffered_rows: int = 0  # max per-bucket run-buffer rows
    skew: float = 1.0           # max/mean lane load after packing
    snapshot_id: Optional[int] = None
    lane_rows: List[int] = dc_field(default_factory=list)
    retries: int = 0            # per-bucket transient-failure retries
    fallbacks: int = 0          # buckets degraded to single-chip
    cleanup_errors: int = 0     # best-effort partial-file deletes failed


# ---------------------------------------------------------------------------
# window kernel: shard_map(vmap(segmented merge)) over [B, N]
# ---------------------------------------------------------------------------

_KERNEL_CACHE: dict = {}


class _MeshWindowKernel:
    """Engine-parameterized window merge over a [B, N] lane stack.

    __call__(lanes[B,N,L], seq_hi[B,N], seq_lo[B,N], invalid[B,N]) ->
    (perm[B,N], winner[B,N], psum'd total winners).  `keep` selects the
    winner row per key segment (last = dedup/partial-update/agg segment
    ends, first = first-row); the first `num_key_lanes` lanes define
    segment identity, further lanes are user-defined sequence order.
    """

    def __init__(self, mesh, num_lanes: int, num_key_lanes: int,
                 keep: str, axis: str):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from paimon_tpu.ops.merge import segmented_merge_body
        from paimon_tpu.parallel._compat import shard_map

        self.sharding = NamedSharding(mesh, P(axis))
        self._n_dev = mesh.shape[axis]

        def per_lane(lanes, seq_hi, seq_lo, invalid, ovc_off):
            perm, winner, _ = segmented_merge_body(
                [lanes[:, i] for i in range(num_lanes)],
                seq_hi, seq_lo, invalid, keep,
                num_key_lanes=num_key_lanes, ovc_off=ovc_off)
            return perm, winner

        @partial(shard_map, mesh=mesh,
                 in_specs=(P(axis), P(axis), P(axis), P(axis),
                           P(axis)),
                 out_specs=(P(axis), P(axis), P()))
        def step(lanes, seq_hi, seq_lo, invalid, ovc_off):
            perm, winner = jax.vmap(per_lane)(lanes, seq_hi, seq_lo,
                                              invalid, ovc_off)
            total = jax.lax.psum(
                jnp.sum(winner.astype(jnp.int64)), axis)
            return perm, winner, total.reshape(1)

        self._fn = jax.jit(step)

    def __call__(self, lanes: np.ndarray, seq_hi: np.ndarray,
                 seq_lo: np.ndarray, invalid: np.ndarray,
                 ovc_off: np.ndarray):
        import jax

        args = [jax.device_put(a, self.sharding)
                for a in (lanes, seq_hi, seq_lo, invalid, ovc_off)]
        perm, winner, total = self._fn(*args)
        jax.block_until_ready((perm, winner, total))
        return (np.asarray(perm), np.asarray(winner),
                int(np.asarray(total)[0]))


def _window_kernel(mesh, num_lanes: int, num_key_lanes: int, keep: str,
                   axis: str) -> _MeshWindowKernel:
    key = (mesh, num_lanes, num_key_lanes, keep, axis)
    k = _KERNEL_CACHE.get(key)
    if k is None:
        k = _KERNEL_CACHE[key] = _MeshWindowKernel(
            mesh, num_lanes, num_key_lanes, keep, axis)
    return k


# ---------------------------------------------------------------------------
# engine context + per-bucket streamed jobs
# ---------------------------------------------------------------------------


class _EngineContext:
    """Per-run bundle: reader/writer planes, key encoding, engine mode."""

    def __init__(self, table):
        from paimon_tpu.core.read import MergeFileSplitRead
        from paimon_tpu.core.kv_file import KeyValueFileWriter
        from paimon_tpu.format.blob import blob_column_names

        self.table = table
        self.schema = table.schema
        self.options = table.options
        self.schema_manager = table.schema_manager
        self.schema_cache = {table.schema.id: table.schema}
        self.reader = MergeFileSplitRead(table.file_io, table.path,
                                         table.schema, table.options)
        self.key_cols = self.reader.key_cols
        self.key_encoder = self.reader.key_encoder
        self.path_factory = self.reader.path_factory
        self.writer = KeyValueFileWriter(
            table.file_io, self.path_factory, table.schema,
            file_format=table.options.file_format,
            compression=table.options.file_compression,
            target_file_size=table.options.target_file_size,
            index_spec=table.options.file_index_spec,
            bloom_fpp=table.options.get(CoreOptions.FILE_INDEX_BLOOM_FPP),
            format_per_level=table.options.file_format_per_level,
            format_options=table.options.format_options,
            **table.options.kv_writer_kwargs())
        self.max_level = table.options.max_level
        self.chunk_rows = table.options.get(CoreOptions.MESH_WINDOW_ROWS)
        self.has_blobs = bool(blob_column_names(table.schema))
        self.engine = table.options.merge_engine
        self.keep = ("first" if self.engine == MergeEngine.FIRST_ROW
                     else "last")
        self.seq_fields = table.options.sequence_field or None
        self.seq_desc = table.options.sequence_field_descending
        # fixed lane geometry for the whole run (uniform across buckets)
        self.num_key_lanes = sum(self.key_encoder.lanes_per_col)
        self.num_order_lanes = 0
        if self.seq_fields:
            from paimon_tpu.ops.normkey import NormalizedKeyEncoder
            from paimon_tpu.types import data_type_to_arrow
            rt = table.schema.logical_row_type()
            enc = NormalizedKeyEncoder(
                [data_type_to_arrow(rt.get_field(f).type)
                 for f in self.seq_fields],
                nullable=[True] * len(self.seq_fields))
            self.num_order_lanes = sum(enc.lanes_per_col)
        self.num_lanes = self.num_key_lanes + self.num_order_lanes

    # -- engine-specific window epilogues (host side) -----------------------

    def live_filter(self, merged):
        """Full compaction drops rows whose surviving kind is a
        retract (+I / +U only survive) — same as the single-chip
        manager's _live_view."""
        import pyarrow as pa
        import pyarrow.compute as pc

        from paimon_tpu.ops.merge import KIND_COL
        from paimon_tpu.types import RowKind

        kinds = merged.column(KIND_COL).combine_chunks().cast(pa.int8())
        keep = pc.or_(pc.equal(kinds, RowKind.INSERT),
                      pc.equal(kinds, RowKind.UPDATE_AFTER))
        return merged.filter(keep)

    def expire_filter(self, merged):
        from paimon_tpu.core.read import record_level_expire_filter
        return record_level_expire_filter(self.options, merged)

    def merge_window_host(self, items):
        """Exact single-chip merge of one window — the fallback for
        windows containing prefix-truncated keys (their repair path
        lives in the single-chip kernels) and the reference the
        equivalence tests compare against."""
        from paimon_tpu.ops.agg import merge_runs_agg
        from paimon_tpu.ops.merge import merge_runs

        tables = [it[0] for it in items]
        encoded = [it[1:] for it in items]
        if self.engine in (MergeEngine.DEDUPLICATE, MergeEngine.FIRST_ROW):
            res = merge_runs(
                tables, self.key_cols,
                merge_engine=("first-row"
                              if self.engine == MergeEngine.FIRST_ROW
                              else "deduplicate"),
                drop_deletes=True, key_encoder=self.key_encoder,
                seq_fields=self.seq_fields, seq_desc=self.seq_desc,
                encoded=encoded)
            merged = res.take()
        else:
            merged = merge_runs_agg(tables, self.key_cols, self.schema,
                                    self.options,
                                    key_encoder=self.key_encoder,
                                    seq_fields=self.seq_fields)
            merged = self.live_filter(merged)
        return self.expire_filter(merged)

    def merge_window_device(self, wtable, perm_row: np.ndarray,
                            winner_row: np.ndarray):
        """Fold one window given the mesh kernel's sorted order."""
        import pyarrow as pa

        from paimon_tpu.ops.merge import KIND_COL
        from paimon_tpu.types import RowKind

        n = wtable.num_rows
        if self.engine in (MergeEngine.DEDUPLICATE, MergeEngine.FIRST_ROW):
            win_pos = np.flatnonzero(winner_row)
            indices = perm_row[win_pos].astype(np.int64)
            kinds = np.asarray(wtable.column(KIND_COL).combine_chunks()
                               .cast(pa.int8()))
            keep_mask = (kinds[indices] == RowKind.INSERT) | \
                        (kinds[indices] == RowKind.UPDATE_AFTER)
            merged = wtable.take(pa.array(indices[keep_mask]))
            return self.expire_filter(merged)
        # aggregation / partial-update: kernel order + segment ends feed
        # the shared single-chip aggregation epilogue
        from paimon_tpu.ops.agg import aggregate_sorted_segments

        real = perm_row < n
        order = perm_row[real].astype(np.int64)
        win_sorted = np.asarray(winner_row[real], dtype=bool)
        if len(win_sorted):
            win_sorted[-1] = True
            seg_end = win_sorted
            seg_id = np.concatenate(
                [[0], np.cumsum(seg_end[:-1])]).astype(np.int64)
        else:
            seg_id = np.zeros(0, np.int64)
        merged = aggregate_sorted_segments(
            wtable, order, seg_id, win_sorted, self.key_cols,
            self.schema, self.options)
        return self.expire_filter(self.live_filter(merged))


class _BucketJob:
    """One (partition, bucket)'s streamed full rewrite: a window
    iterator over its sorted runs plus a rolling output-file writer."""

    def __init__(self, ctx: _EngineContext, split):
        self.ctx = ctx
        self.split = split
        self.files = list(split.data_files)
        self.stream_stats: Dict[str, int] = {}
        self.acc: List = []
        self.acc_bytes = 0
        self.metas: List = []
        self.out_rows = 0
        self._windows = None
        # backoff deadline (monotonic seconds): a retried bucket is
        # requeued with a not-before instead of sleeping the whole
        # mesh — other lanes keep streaming through the wait
        self.ready_at = 0.0

    def _run_iter(self, run_files):
        """Decode one sorted run in bounded chunks, lane-encoding inside
        the prefetch thread (same shape as the single-chip streamed
        rewrite in compact/manager.py)."""
        from paimon_tpu.core.kv_file import read_kv_file
        from paimon_tpu.core.read import evolve_table
        from paimon_tpu.format import get_format

        from paimon_tpu.fs.caching import scoped_batches

        ctx = self.ctx
        options = ctx.table.options
        for f in run_files:
            if ctx.has_blobs:
                t = read_kv_file(ctx.table.file_io, ctx.path_factory,
                                 self.split.partition, self.split.bucket,
                                 f, schema=ctx.schema,
                                 schema_manager=ctx.schema_manager,
                                 options=options)
                t = evolve_table(t, f.schema_id, ctx.schema,
                                 ctx.schema_manager, ctx.schema_cache,
                                 keep_sys_cols=True)
                yield (t, *ctx.key_encoder.encode_table_ex(
                    t, ctx.key_cols))
                continue
            ext = f.file_name.rsplit(".", 1)[-1]
            fmt = get_format(ext)
            path = f.external_path or ctx.path_factory.data_file_path(
                self.split.partition, self.split.bucket, f.file_name)
            if fmt.identifier == "parquet" and options.get(
                    CoreOptions.READ_DEVICE_DECODE):
                # row-group-at-a-time device decode (memory bound as
                # the pyarrow batch path); unsupported files drop to
                # the format reader below
                from paimon_tpu.format.rawpage import (
                    _FALLBACK_ERRORS, iter_batches_device,
                )
                batches = None
                try:
                    batches = iter_batches_device(
                        ctx.table.file_io, path, ctx.chunk_rows,
                        options)
                except _FALLBACK_ERRORS:
                    from paimon_tpu.metrics import (
                        SCAN_DEVICE_DECODE_FALLBACKS, global_registry,
                    )
                    global_registry().group("scan").counter(
                        SCAN_DEVICE_DECODE_FALLBACKS).inc()
                if batches is not None:
                    for batch in batches:
                        t = evolve_table(
                            batch, f.schema_id, ctx.schema,
                            ctx.schema_manager, ctx.schema_cache,
                            keep_sys_cols=True)
                        yield (t, *ctx.key_encoder.encode_table_ex(
                            t, ctx.key_cols))
                    continue
            # gate held only while advancing the inner iterator (see
            # fs.caching.scoped_batches), never across our yields
            for batch in scoped_batches(
                    fmt.create_reader().read_batches(
                        ctx.table.file_io, path,
                        batch_rows=ctx.chunk_rows), options):
                t = evolve_table(batch, f.schema_id, ctx.schema,
                                 ctx.schema_manager, ctx.schema_cache,
                                 keep_sys_cols=True)
                yield (t, *ctx.key_encoder.encode_table_ex(
                    t, ctx.key_cols))

    def next_window(self):
        """Next run-ordered item list, or None when the bucket drains."""
        if self._windows is None:
            from paimon_tpu.compact.manager import _prefetch
            from paimon_tpu.core.read import assemble_runs
            from paimon_tpu.ops.merge_stream import iter_merge_windows

            runs_meta = assemble_runs(self.files)
            self._windows = iter_merge_windows(
                [_prefetch(self._run_iter(rf)) for rf in runs_meta],
                self.ctx.key_cols, self.ctx.key_encoder,
                stats=self.stream_stats,
                window_rows=self.ctx.table.options.get(
                    CoreOptions.MERGE_WINDOW_ROWS))
        return next(self._windows, None)

    def emit(self, merged) -> None:
        if merged.num_rows == 0:
            return
        self.out_rows += merged.num_rows
        self.acc.append(merged)
        self.acc_bytes += merged.nbytes
        if self.acc_bytes >= self.ctx.writer.target_file_size:
            self.flush()

    def flush(self) -> None:
        if not self.acc:
            return
        import pyarrow as pa

        from paimon_tpu.manifest import FileSource

        merged = pa.concat_tables(self.acc, promote_options="none") \
            if len(self.acc) > 1 else self.acc[0]
        self.acc, self.acc_bytes = [], 0
        self.metas.extend(self.ctx.writer.write(
            self.split.partition, self.split.bucket, merged,
            level=self.ctx.max_level, file_source=FileSource.COMPACT))


class _LaneState:
    """A mesh lane's queue of bucket jobs; at most one is streaming."""

    def __init__(self, jobs: List[_BucketJob]):
        self.queue = list(jobs)
        self.current: Optional[_BucketJob] = None

    def next_window(self, finalize):
        """(job, window items) for this lane's next window; None when
        the lane has drained OR every queued job is still inside its
        retry-backoff window (ready_at in the future).  Finished
        buckets flush + finalize before the lane advances."""
        while True:
            if self.current is None:
                now = _time.monotonic()
                ready = next((j for j in self.queue
                              if j.ready_at <= now), None)
                if ready is None:
                    return None
                self.queue.remove(ready)
                self.current = ready
            w = self.current.next_window()
            if w is not None:
                return (self.current, w)
            finalize(self.current)
            self.current = None


# ---------------------------------------------------------------------------
# table-level entry
# ---------------------------------------------------------------------------


def _needs_rewrite(split, max_level: int) -> bool:
    """Mirror the single-chip manager's no-op condition: one file
    already at the top level with no deletes has nothing to fold."""
    fs = split.data_files
    return not (len(fs) == 1 and fs[0].level == max_level
                and (fs[0].delete_row_count or 0) == 0)


def compact_table_mesh(table, mesh=None, axis: str = "buckets",
                       retry_policy=None, group_filter=None,
                       commit_user=None, properties=None,
                       properties_provider=None) -> MeshCompactStats:
    """Full compaction of every bucket of a primary-key table through
    the streaming mesh engine: engine-dispatched window kernels over a
    [B, window] lane stack, skew-aware bucket packing, one COMPACT
    snapshot.  Peak host memory per bucket ~ runs x window-rows,
    independent of bucket size.

    Transient failures are isolated per bucket: retry with jittered
    backoff, then single-chip fallback (see module docstring §4 and
    parallel/fault.py).  `retry_policy` overrides the table's
    compaction.retry.* / compaction.mesh.fallback options."""
    from paimon_tpu.core.commit import FileStoreCommit
    from paimon_tpu.core.write import CommitMessage
    from paimon_tpu.metrics import (
        COMPACTION_BUCKET_FAILURES, COMPACTION_BUCKET_FALLBACKS,
        COMPACTION_BUCKET_RETRIES, global_registry,
    )
    from paimon_tpu.ops.merge import SEQ_COL, _pad_size
    from paimon_tpu.parallel.fault import (
        BucketRetryPolicy, is_transient_error,
    )
    from paimon_tpu.parallel.sharded_merge import bucket_mesh

    engine = table.options.merge_engine
    if engine not in SUPPORTED_MERGE_ENGINES:
        raise UnsupportedMergeEngineError(
            f"merge-engine {engine!r} has no mesh compaction kernel "
            f"(supported: {', '.join(SUPPORTED_MERGE_ENGINES)})")
    if not table.primary_keys:
        raise ValueError("mesh compaction targets primary-key tables")
    if table.options.changelog_producer != ChangelogProducer.NONE:
        raise ValueError(
            "mesh compaction does not produce changelog; use the "
            "single-chip compaction path for changelog producers")
    if table.options.sequence_field and engine == MergeEngine.FIRST_ROW:
        raise ValueError(
            "sequence.field cannot be used with merge-engine first-row")

    if mesh is None:
        mesh = bucket_mesh(axis=axis)
    n_dev = mesh.shape[axis]

    plan = table.new_read_builder().new_scan().plan()
    max_level = table.options.max_level
    splits = [s for s in plan.splits if s.data_files]
    if group_filter is not None:
        # sharded maintenance plane: this host compacts only the
        # (partition, bucket) groups it owns (the scheduling seam of
        # parallel/maintenance_plane.py) — peers run the same program
        # over their own shares
        splits = [s for s in splits
                  if group_filter(tuple(s.partition), s.bucket)]
    jobs_splits = [s for s in splits if _needs_rewrite(s, max_level)]
    stats = MeshCompactStats(lanes=n_dev)
    if not jobs_splits:
        return stats

    row_counts = bucket_row_counts(jobs_splits)
    lane_assign = pack_buckets(row_counts, n_dev)
    stats.buckets = len(jobs_splits)
    stats.input_rows = sum(row_counts)
    stats.lane_rows = [sum(row_counts[i] for i in lane)
                       for lane in lane_assign]
    stats.skew = packing_skew(row_counts, lane_assign)

    ctx = _EngineContext(table)
    lanes_state = [
        _LaneState([_BucketJob(ctx, jobs_splits[i]) for i in lane])
        for lane in lane_assign
    ]

    messages: List[CommitMessage] = []

    def finalize(job: _BucketJob) -> None:
        job.flush()
        stats.output_rows += job.out_rows
        stats.peak_buffered_rows = max(
            stats.peak_buffered_rows,
            job.stream_stats.get("peak_buffered_rows", 0))
        messages.append(CommitMessage(
            job.split.partition, job.split.bucket,
            job.split.total_buckets,
            compact_before=job.files, compact_after=job.metas))

    # -- per-bucket fault isolation (module docstring §4) -------------------
    from paimon_tpu.obs import trace as _trace
    from paimon_tpu.obs.trace import span as _obs_span
    _trace.sync_from_options(table.options)
    policy = retry_policy or BucketRetryPolicy.from_options(table.options)
    fault_metrics = global_registry().compaction_metrics()
    attempts: Dict[Tuple, int] = {}
    backoffs: Dict[Tuple, object] = {}

    def _job_key(split) -> Tuple:
        return (tuple(split.partition), split.bucket)

    def _cleanup_job(job: _BucketJob) -> None:
        """Abort a failed attempt: drop buffered output, close the
        window stream, delete any files the attempt already rolled —
        the retry/fallback must start from the untouched inputs."""
        job.acc, job.acc_bytes = [], 0
        if job._windows is not None:
            try:
                job._windows.close()
            except Exception:               # noqa: BLE001
                stats.cleanup_errors += 1
            job._windows = None
        for m in job.metas:
            names = [m.file_name, *m.extra_files]
            for name in names:
                path = m.external_path \
                    if (name == m.file_name and m.external_path) \
                    else ctx.path_factory.data_file_path(
                        job.split.partition, job.split.bucket, name)
                try:
                    table.file_io.delete_quietly(path)
                except Exception:           # noqa: BLE001
                    stats.cleanup_errors += 1
        job.metas = []

    def _fallback_single_chip(split) -> Optional[CommitMessage]:
        """Degrade one bucket to the exact single-chip full rewrite
        (same merge semantics — the equivalence tests compare these
        two paths row-for-row), itself retried under the policy."""
        from paimon_tpu.compact.manager import MergeTreeCompactManager

        def run():
            from paimon_tpu.metrics import COMPACTION_FALLBACK_MS
            with _obs_span("compaction.fallback", cat="compaction",
                           group="compaction",
                           metric=COMPACTION_FALLBACK_MS,
                           partition=split.partition,
                           bucket=split.bucket, table=table.path):
                mgr = MergeTreeCompactManager(
                    table.file_io, table.path, table.schema,
                    table.options, split.partition, split.bucket,
                    list(split.data_files),
                    schema_manager=table.schema_manager)
                return mgr.compact(full=True)

        result = policy.retry_call(run)
        if result is None or result.is_empty():
            return None
        return CommitMessage(
            split.partition, split.bucket, split.total_buckets,
            compact_before=result.before, compact_after=result.after,
            compact_changelog=result.changelog)

    def _handle_bucket_failure(lane_idx: int, job: _BucketJob,
                               exc: BaseException) -> None:
        """Ride the degradation ladder for one bucket; re-raises when
        the error is not transient or the ladder is exhausted."""
        if not is_transient_error(exc):
            raise exc
        lane = lanes_state[lane_idx]
        if lane.current is job:
            lane.current = None
        _cleanup_job(job)
        key = _job_key(job.split)
        n = attempts.get(key, 0) + 1
        attempts[key] = n
        if n < max(1, policy.max_attempts):
            stats.retries += 1
            fault_metrics.counter(COMPACTION_BUCKET_RETRIES).inc()
            if key not in backoffs:
                backoffs[key] = policy.new_backoff()
            # deadline, not a sleep: only THIS bucket waits out its
            # jittered backoff; the other lanes keep streaming
            retry_job = _BucketJob(ctx, job.split)
            retry_job.ready_at = _time.monotonic() + \
                backoffs[key].next_ms() / 1000.0
            lane.queue.insert(0, retry_job)
            return
        if policy.fallback:
            stats.fallbacks += 1
            fault_metrics.counter(COMPACTION_BUCKET_FALLBACKS).inc()
            try:
                msg = _fallback_single_chip(job.split)
            except Exception:
                fault_metrics.counter(COMPACTION_BUCKET_FAILURES).inc()
                raise
            if msg is not None:
                messages.append(msg)
            return
        fault_metrics.counter(COMPACTION_BUCKET_FAILURES).inc()
        raise exc

    import pyarrow as pa

    kernel = _window_kernel(mesh, ctx.num_lanes, ctx.num_key_lanes,
                            ctx.keep, axis)
    while True:
        step: List[Optional[Tuple]] = []
        for li, lane in enumerate(lanes_state):
            try:
                step.append(lane.next_window(finalize))
            except Exception as e:          # noqa: BLE001
                failed = lane.current
                if failed is None:
                    raise
                _handle_bucket_failure(li, failed, e)
                step.append(None)
        if all(w is None for w in step):
            deadlines = [j.ready_at for lane in lanes_state
                         for j in lane.queue]
            if not deadlines and all(lane.current is None
                                     for lane in lanes_state):
                break
            # nothing runnable anywhere: every remaining job is inside
            # its backoff window — sleep to the earliest deadline
            # instead of spinning (only here does the loop ever wait)
            if deadlines:
                wait = min(deadlines) - _time.monotonic()
                if wait > 0:
                    from paimon_tpu.utils.backoff import wait_for
                    with _obs_span("compaction.backoff_wait",
                                   cat="compaction",
                                   pending=len(deadlines)):
                        wait_for(wait, what="compaction backoff")
            continue
        # assemble each active lane's window; truncated-key windows take
        # the exact host merge instead of the device kernel
        device_rows: List[Optional[Tuple]] = [None] * n_dev
        n_max = 0
        for li, item in enumerate(step):
            if item is None:
                continue
            job, items = item
            try:
                wtable = pa.concat_tables([it[0] for it in items],
                                          promote_options="none") \
                    if len(items) > 1 else items[0][0]
                trunc_any = any(np.asarray(it[2]).any() for it in items)
                if trunc_any or wtable.num_rows == 0:
                    job.emit(ctx.merge_window_host(items))
                    continue
                lanes_mat = np.concatenate([np.asarray(it[1])
                                            for it in items]) \
                    if len(items) > 1 else np.asarray(items[0][1])
                if ctx.seq_fields:
                    from paimon_tpu.ops.merge import user_seq_order_lanes
                    order_lanes = user_seq_order_lanes(
                        wtable, ctx.seq_fields, ctx.seq_desc)
                    lanes_mat = np.concatenate([lanes_mat, order_lanes],
                                               axis=1)
                seq = np.asarray(wtable.column(SEQ_COL).combine_chunks()
                                 .cast("int64"))
                # each window item is one sorted-run piece: its
                # offset-value codes ride to the device so the kernel's
                # winner-select consumes the single-int offsets first
                item_starts = np.concatenate(
                    [[0], np.cumsum([it[0].num_rows
                                     for it in items])]).astype(np.int64)
            except Exception as e:          # noqa: BLE001
                _handle_bucket_failure(li, job, e)
                continue
            device_rows[li] = (job, wtable, lanes_mat, seq, item_starts)
            n_max = max(n_max, wtable.num_rows)
        if n_max == 0:
            continue
        from paimon_tpu.ops.ovc import OVC_OFF_SENTINEL, run_ovc_offsets
        n_pad = _pad_size(n_max)
        lanes_arr = np.zeros((n_dev, n_pad, ctx.num_lanes),
                             dtype=np.uint32)
        seq_hi = np.zeros((n_dev, n_pad), dtype=np.uint32)
        seq_lo = np.zeros((n_dev, n_pad), dtype=np.uint32)
        invalid = np.ones((n_dev, n_pad), dtype=np.uint32)
        ovc_arr = np.full((n_dev, n_pad), OVC_OFF_SENTINEL,
                          dtype=np.uint32)
        for li, entry in enumerate(device_rows):
            if entry is None:
                continue
            _, wtable, lanes_mat, seq, item_starts = entry
            k = wtable.num_rows
            lanes_arr[li, :k] = lanes_mat
            u = seq.astype(np.int64).view(np.uint64)
            seq_hi[li, :k] = (u >> np.uint64(32)).astype(np.uint32)
            seq_lo[li, :k] = (u & np.uint64(0xFFFFFFFF)).astype(np.uint32)
            invalid[li, :k] = 0
            ovc_arr[li, :k] = run_ovc_offsets(lanes_arr[li, :k],
                                              item_starts)
        try:
            from paimon_tpu.metrics import COMPACTION_WINDOW_MS
            with _obs_span("compaction.window", cat="compaction",
                           group="compaction",
                           metric=COMPACTION_WINDOW_MS,
                           lanes=sum(1 for e in device_rows
                                     if e is not None),
                           rows=n_max, table=table.path):
                perm, winner, _ = kernel(lanes_arr, seq_hi, seq_lo,
                                         invalid, ovc_arr)
        except Exception as e:              # noqa: BLE001
            # a kernel failure is a lane/device failure for every
            # bucket in flight this step: each rides its own ladder
            for li, entry in enumerate(device_rows):
                if entry is not None:
                    _handle_bucket_failure(li, entry[0], e)
            continue
        for li, entry in enumerate(device_rows):
            if entry is None:
                continue
            job, wtable = entry[0], entry[1]
            try:
                job.emit(ctx.merge_window_device(wtable, perm[li],
                                                 winner[li]))
            except Exception as e:          # noqa: BLE001
                _handle_bucket_failure(li, job, e)
                continue
            stats.windows += 1
            stats.peak_window_rows = max(stats.peak_window_rows,
                                         wtable.num_rows)

    if not messages:
        _trace.maybe_export()
        return stats
    commit = FileStoreCommit(table.file_io, table.path, table.schema,
                             table.options, commit_user=commit_user,
                             branch=table.branch)
    if properties_provider is not None:
        commit.properties_provider = properties_provider
    stats.snapshot_id = commit.commit(messages, properties=properties)
    _trace.maybe_export()
    return stats
