"""Skew-aware bucket -> device-lane packing for the mesh engine.

The legacy sharded compactor stacked EVERY bucket as its own mesh lane
and padded all lanes to the hottest bucket's row count, so one skewed
bucket inflated every device's work by its size (VERDICT: "pads all
buckets to the largest bucket's row count").  The mesh engine instead
packs buckets onto a FIXED number of lanes (one per device) with a
greedy longest-processing-time bin-packer keyed on per-bucket row
counts taken from manifest statistics — no file reads.  A hot bucket
then occupies one lane alone while the cold buckets share the others,
and the per-step window padding is bounded by the window budget, not
by the hot bucket.

Classic LPT guarantees a makespan within 4/3 of optimal; for the
compaction engine the makespan IS the wall-clock of the mesh program,
so the packing quality is directly the scale-out efficiency.
"""

from __future__ import annotations

from typing import List, Sequence

__all__ = ["pack_buckets", "packing_skew", "bucket_row_counts"]


def bucket_row_counts(splits) -> List[int]:
    """Per-split input row counts from manifest stats (DataFileMeta
    row_count sums) — the packer's key, available before any file IO."""
    return [sum(f.row_count for f in s.data_files) for s in splits]


def pack_buckets(row_counts: Sequence[int],
                 num_lanes: int) -> List[List[int]]:
    """Greedy LPT bin-packing: assign each bucket (descending by row
    count) to the currently least-loaded lane.

    Returns `num_lanes` lists of bucket indices (a lane may be empty
    when there are fewer buckets than lanes).  Deterministic: ties
    break on the lower bucket index and the lower lane index, so the
    same stats always produce the same mesh layout.
    """
    if num_lanes < 1:
        raise ValueError(f"num_lanes must be >= 1, got {num_lanes}")
    lanes: List[List[int]] = [[] for _ in range(num_lanes)]
    loads = [0] * num_lanes
    order = sorted(range(len(row_counts)),
                   key=lambda i: (-int(row_counts[i]), i))
    for i in order:
        target = min(range(num_lanes), key=lambda j: (loads[j], j))
        lanes[target].append(i)
        loads[target] += int(row_counts[i])
    return lanes


def packing_skew(row_counts: Sequence[int],
                 lanes: Sequence[Sequence[int]]) -> float:
    """max lane load / mean non-trivial lane load (1.0 = perfectly
    balanced).  Reported in MeshCompactStats for observability."""
    loads = [sum(int(row_counts[i]) for i in lane) for lane in lanes]
    total = sum(loads)
    if total == 0:
        return 1.0
    # empty lanes are idle by construction (fewer buckets than devices),
    # not a packing failure — exclude them from the mean
    used = [ld for ld in loads if ld > 0]
    mean = total / len(used)
    return max(loads) / mean
