"""Mesh-sharded multi-bucket merge.

Buckets are the unit of parallelism (reference shuffles rows to bucket
tasks via table/sink/ChannelComputer + FlinkStreamPartitioner; each task
merges one bucket with a loser tree). The TPU layout instead stacks all
buckets into [B, N, ...] arrays, shards the bucket axis over a
`jax.sharding.Mesh`, and runs the per-bucket segmented sort-merge
(ops/merge.py kernel) vmapped on every device, with commit statistics
(row counts) reduced across the mesh by `psum` over ICI.

Used by the multi-bucket compaction path and by the driver's multichip
dryrun; exercised on a virtual 8-device CPU mesh in tests.
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["bucket_mesh", "pad_bucket_batches", "ShardedBucketMerge"]


def bucket_mesh(n_devices: Optional[int] = None, axis: str = "buckets"):
    """A 1-D device mesh over the bucket axis."""
    import jax
    from jax.sharding import Mesh

    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.array(devices), axis_names=(axis,))


def pad_bucket_batches(
    lanes_list: Sequence[np.ndarray], seq_list: Sequence[np.ndarray]
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Stack per-bucket (lanes uint32[N_b, L], seq int64[N_b]) into padded
    [B, N, ...] arrays with an invalid mask (padding sorts last)."""
    from paimon_tpu.ops.merge import _pad_size

    b = len(lanes_list)
    num_lanes = lanes_list[0].shape[1] if b else 0
    # pad the row axis to a power of two so successive calls with nearby
    # bucket sizes reuse the compiled sharded program
    n = _pad_size(max((len(s) for s in seq_list), default=0))
    lanes = np.zeros((b, n, num_lanes), dtype=np.uint32)
    seq_hi = np.zeros((b, n), dtype=np.uint32)
    seq_lo = np.zeros((b, n), dtype=np.uint32)
    invalid = np.ones((b, n), dtype=np.uint32)
    for i, (la, sq) in enumerate(zip(lanes_list, seq_list)):
        k = len(sq)
        lanes[i, :k] = la
        u = sq.astype(np.int64).view(np.uint64)
        seq_hi[i, :k] = (u >> np.uint64(32)).astype(np.uint32)
        seq_lo[i, :k] = (u & np.uint64(0xFFFFFFFF)).astype(np.uint32)
        invalid[i, :k] = 0
    return lanes, seq_hi, seq_lo, invalid


class ShardedBucketMerge:
    """Compile-once sharded merge over a mesh.

    __call__(lanes[B,N,L], seq_hi[B,N], seq_lo[B,N], invalid[B,N]) ->
    (perm[B,N] int32, winner[B,N] bool, total_rows int64 replicated).
    B must be a multiple of the mesh axis size.
    """

    def __init__(self, mesh, num_lanes: int, keep: str = "last",
                 axis: str = "buckets"):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        self.mesh = mesh
        self.axis = axis
        self.num_lanes = num_lanes
        self.sharding = NamedSharding(mesh, P(axis))
        n_dev = mesh.shape[axis]

        from paimon_tpu.ops.merge import segmented_merge_body
        from paimon_tpu.parallel._compat import shard_map

        def per_bucket(lanes, seq_hi, seq_lo, invalid):
            perm, winner, _ = segmented_merge_body(
                [lanes[:, i] for i in range(num_lanes)],
                seq_hi, seq_lo, invalid, keep)
            return perm, winner

        @partial(shard_map, mesh=mesh,
                 in_specs=(P(axis), P(axis), P(axis), P(axis)),
                 out_specs=(P(axis), P(axis), P()))
        def step(lanes, seq_hi, seq_lo, invalid):
            perm, winner = jax.vmap(per_bucket)(lanes, seq_hi, seq_lo,
                                                invalid)
            local_rows = jnp.sum(winner.astype(jnp.int64))
            total_rows = jax.lax.psum(local_rows, axis)
            return perm, winner, total_rows.reshape(1)

        self._fn = jax.jit(step)
        self._n_dev = n_dev

    def __call__(self, lanes: np.ndarray, seq_hi: np.ndarray,
                 seq_lo: np.ndarray, invalid: np.ndarray):
        import jax

        b = lanes.shape[0]
        if b % self._n_dev != 0:
            pad = self._n_dev - b % self._n_dev
            lanes = np.concatenate(
                [lanes, np.zeros((pad,) + lanes.shape[1:], lanes.dtype)])
            seq_hi = np.concatenate(
                [seq_hi, np.zeros((pad,) + seq_hi.shape[1:], seq_hi.dtype)])
            seq_lo = np.concatenate(
                [seq_lo, np.zeros((pad,) + seq_lo.shape[1:], seq_lo.dtype)])
            invalid = np.concatenate(
                [invalid, np.ones((pad,) + invalid.shape[1:], invalid.dtype)])
        args = [jax.device_put(a, self.sharding)
                for a in (lanes, seq_hi, seq_lo, invalid)]
        perm, winner, total = self._fn(*args)
        jax.block_until_ready((perm, winner, total))
        return (np.asarray(perm)[:b], np.asarray(winner)[:b],
                int(np.asarray(total)[0]))


_MERGER_CACHE: dict = {}


def _cached_merger(mesh, num_lanes: int, keep: str) -> "ShardedBucketMerge":
    key = (mesh, num_lanes, keep)
    m = _MERGER_CACHE.get(key)
    if m is None:
        m = _MERGER_CACHE[key] = ShardedBucketMerge(mesh, num_lanes,
                                                    keep=keep)
    return m


def merge_buckets_sharded(
    lanes_list: Sequence[np.ndarray], seq_list: Sequence[np.ndarray],
    mesh=None, keep: str = "last"
) -> Tuple[List[np.ndarray], int]:
    """Merge many buckets at once over a mesh.

    Each bucket b has key lanes uint32[N_b, L] and sequence int64[N_b]
    (rows in arrival order, runs already concatenated oldest-first).
    Returns per-bucket winner indices (into the bucket's input order,
    sorted by key) and the psum'd total output row count.
    """
    if not lanes_list:
        return [], 0
    if mesh is None:
        mesh = bucket_mesh()
    lanes, seq_hi, seq_lo, invalid = pad_bucket_batches(lanes_list, seq_list)
    merger = _cached_merger(mesh, lanes.shape[2], keep)
    perm, winner, total = merger(lanes, seq_hi, seq_lo, invalid)
    out = []
    for i in range(len(lanes_list)):
        win_pos = np.flatnonzero(winner[i])
        out.append(perm[i][win_pos].astype(np.int64))
    return out, total
