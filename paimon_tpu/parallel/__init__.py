"""Scale-out plane: bucket sharding over a jax device mesh.

The reference scales by shuffling rows to per-bucket writer tasks over the
engine's network (flink/sink/FlinkStreamPartitioner via ChannelComputer)
and merging each bucket on one core. Here buckets are laid out over a
`jax.sharding.Mesh` axis: every device merges its shard of buckets with
the same segmented-sort kernel used single-chip, and commit-level
statistics reduce across the mesh with `psum` over ICI.
"""

from paimon_tpu.parallel.sharded_merge import (  # noqa: F401
    ShardedBucketMerge, bucket_mesh, merge_buckets_sharded,
    pad_bucket_batches,
)
from paimon_tpu.parallel.sharded_compact import (  # noqa: F401
    ShardedCompactStats, compact_table_sharded,
)
from paimon_tpu.parallel.rescale import (  # noqa: F401
    rescale_dispatch_sharded, rescale_table_buckets,
)
from paimon_tpu.parallel.mesh_engine import (  # noqa: F401
    MeshCompactStats, SUPPORTED_MERGE_ENGINES,
    UnsupportedMergeEngineError, compact_table_mesh,
)
from paimon_tpu.parallel.fault import (  # noqa: F401
    BucketRetryPolicy, is_transient_error,
)
from paimon_tpu.parallel.scan_pipeline import (  # noqa: F401
    iter_split_tables, read_file_retrying, resolve_parallelism,
)
from paimon_tpu.parallel.packing import (  # noqa: F401
    bucket_row_counts, pack_buckets, packing_skew,
)
