"""Multichip dryrun: one full sharded write→merge→commit-stats step.

This is the library path the driver's `dryrun_multichip` exercises: a real
multi-bucket primary-key table is written through the normal write/commit
plane, every bucket's runs are encoded to key lanes, and all buckets merge
in ONE mesh-sharded kernel launch (buckets sharded over devices, commit
row-count reduced with psum). Shapes are tiny; the point is that the
sharded program compiles and executes.
"""

from __future__ import annotations

import os


def run(n_devices: int) -> None:
    # Force the CPU platform before any backend initializes: the real TPU
    # tunnel is single-client and must never be touched by dryruns.
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_devices}"
        ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")

    import tempfile

    import numpy as np
    import pyarrow as pa

    from paimon_tpu.ops.merge import SEQ_COL
    from paimon_tpu.parallel import bucket_mesh, merge_buckets_sharded
    from paimon_tpu.schema import Schema
    from paimon_tpu.table import FileStoreTable
    from paimon_tpu.types import BigIntType, DoubleType

    n_buckets = n_devices
    rows_per_commit = 256

    with tempfile.TemporaryDirectory() as tmp:
        schema = (Schema.builder()
                  .column("id", BigIntType(False))
                  .column("v", DoubleType())
                  .primary_key("id")
                  .options({"bucket": str(n_buckets),
                            "write-only": "true"})
                  .build())
        table = FileStoreTable.create(os.path.join(tmp, "t"), schema)
        rng = np.random.default_rng(0)
        # two commits -> two overlapping L0 runs per bucket
        for _ in range(2):
            ids = rng.integers(0, rows_per_commit, rows_per_commit * 2)
            data = pa.table({
                "id": pa.array(ids, pa.int64()),
                "v": pa.array(rng.random(len(ids)), pa.float64()),
            })
            wb = table.new_batch_write_builder()
            w = wb.new_write()
            w.write_arrow(data)
            wb.new_commit().commit(w.prepare_commit())
            w.close()

        # plan all buckets, encode key lanes per bucket with the SAME
        # encoder/key columns the real read path derives from the schema
        splits = table.new_read_builder().new_scan().plan().splits
        assert splits, "no splits planned"
        from paimon_tpu.core.kv_file import read_kv_file
        from paimon_tpu.core.read import MergeFileSplitRead
        reader = MergeFileSplitRead(table.file_io, table.path, table.schema,
                                    table.options)
        encoder = reader.key_encoder
        lanes_list, seq_list, n_input = [], [], 0
        for s in splits:
            runs = []
            for f in s.data_files:
                runs.append(read_kv_file(
                    reader.file_io, reader.path_factory, s.partition,
                    s.bucket, f, None, None))
            t = pa.concat_tables(runs, promote_options="none")
            lanes, _ = encoder.encode_table(t, reader.key_cols)
            seq = np.asarray(t.column(SEQ_COL).combine_chunks()
                             .cast(pa.int64()))
            lanes_list.append(lanes)
            seq_list.append(seq)
            n_input += t.num_rows

        mesh = bucket_mesh(n_devices)
        winners, total = merge_buckets_sharded(lanes_list, seq_list, mesh)
        assert len(winners) == len(splits)
        assert 0 < total <= n_input, (total, n_input)
        # cross-check against the sequential single-chip read path
        seq_total = table.to_arrow().num_rows
        assert total == seq_total, (total, seq_total)
        print(f"dryrun_multichip OK: {n_devices} devices, "
              f"{len(splits)} buckets, {n_input} input rows -> "
              f"{total} merged rows (psum over mesh)")
