"""Multichip dryrun: sharded write -> end-to-end mesh compaction ->
all_to_all bucket rescale, at >= 1M rows.

This is the library path the driver's `dryrun_multichip` exercises: a
real multi-bucket primary-key table is written through the normal
write/commit plane, then

1. `compact_table_sharded` runs EVERY bucket's full compaction in one
   mesh program (bucket-axis sharding, vmapped segmented merge, commit
   stats psum'd on device) and commits the COMPACT snapshot;
2. `rescale_table_buckets` re-routes every row to 2x the buckets with
   the all_to_all dispatch collective and commits the overwrite;
3. the read-back after both is checked against the pre-compaction
   merge-on-read state.

Scale: DRYRUN_ROWS rows (default 1,000,000) so the dryrun proves
meaningful data volumes, not just compilation.
"""

from __future__ import annotations

import os


def run(n_devices: int) -> None:
    # Force the CPU platform before any backend initializes: the real TPU
    # tunnel is single-client and must never be touched by dryruns.
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_devices}"
        ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")

    import tempfile

    import numpy as np
    import pyarrow as pa

    from paimon_tpu.parallel import (
        bucket_mesh, compact_table_sharded, rescale_table_buckets,
    )
    from paimon_tpu.schema import Schema
    from paimon_tpu.table import FileStoreTable
    from paimon_tpu.types import BigIntType, DoubleType

    n_buckets = n_devices
    # write-path flush pre-merges duplicate keys, so size the keyspace
    # so that >= 1M rows survive into the sharded compaction itself
    total_rows = int(os.environ.get("DRYRUN_ROWS", "1300000"))

    with tempfile.TemporaryDirectory() as tmp:
        schema = (Schema.builder()
                  .column("id", BigIntType(False))
                  .column("v", DoubleType())
                  .primary_key("id")
                  .options({"bucket": str(n_buckets),
                            "write-only": "true"})
                  .build())
        table = FileStoreTable.create(os.path.join(tmp, "t"), schema)
        rng = np.random.default_rng(0)
        # two commits -> two overlapping L0 runs per bucket
        for _ in range(2):
            ids = rng.integers(0, total_rows, total_rows // 2)
            data = pa.table({
                "id": pa.array(ids, pa.int64()),
                "v": pa.array(rng.random(len(ids)), pa.float64()),
            })
            wb = table.new_batch_write_builder()
            w = wb.new_write()
            w.write_arrow(data)
            wb.new_commit().commit(w.prepare_commit())
            w.close()

        expected = table.to_arrow().num_rows   # merge-on-read truth
        n_input = sum(
            f.row_count for s in
            table.new_read_builder().new_scan().plan().splits
            for f in s.data_files)

        mesh = bucket_mesh(n_devices)
        stats = compact_table_sharded(table, mesh)
        assert stats.snapshot_id is not None
        assert stats.buckets == n_buckets, (stats.buckets, n_buckets)
        assert stats.output_rows == expected, (stats.output_rows,
                                               expected)
        assert table.latest_snapshot().commit_kind == "COMPACT"

        sid = rescale_table_buckets(table, 2 * n_buckets, mesh=mesh)
        assert sid is not None
        table2 = FileStoreTable.load(table.path)
        assert table2.options.bucket == 2 * n_buckets
        after = table2.to_arrow().num_rows
        assert after == expected, (after, expected)

        print(f"dryrun_multichip OK: {n_devices} devices, "
              f"{n_buckets}->{2 * n_buckets} buckets, "
              f"{n_input} input rows -> {expected} merged rows "
              f"(sharded compact + all_to_all rescale on mesh)")
