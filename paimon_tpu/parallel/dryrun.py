"""Multichip dryrun: sharded write -> end-to-end mesh compaction ->
all_to_all bucket rescale, at >= 1M rows.

This is the library path the driver's `dryrun_multichip` exercises: a
real multi-bucket primary-key table is written through the normal
write/commit plane, then

1. `compact_table_mesh` (parallel/mesh_engine.py) runs EVERY bucket's
   full compaction in one streamed mesh program (skew-aware bucket ->
   lane packing, engine-dispatched [B, window] kernels) and commits
   the COMPACT snapshot;
2. `rescale_table_buckets` re-routes every row to 2x the buckets with
   the all_to_all dispatch collective and commits the overwrite;
3. the read-back after both is checked against the pre-compaction
   merge-on-read state.

`run_engines` is the round-6 multichip benchmark entry: deduplicate +
aggregation full compactions through the mesh engine at >= 10M rows,
rows/s recorded to MULTICHIP_r06.json by the slow pytest entry
(tests/test_mesh_engine.py::test_dryrun_multichip_engines).

Scale: DRYRUN_ROWS rows (default 1,000,000) so the dryrun proves
meaningful data volumes, not just compilation.
"""

from __future__ import annotations

import os
from typing import Optional


def run(n_devices: int) -> None:
    # Force the CPU platform before any backend initializes: the real TPU
    # tunnel is single-client and must never be touched by dryruns.
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_devices}"
        ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")

    import tempfile

    import numpy as np
    import pyarrow as pa

    from paimon_tpu.parallel import (
        bucket_mesh, compact_table_mesh, rescale_table_buckets,
    )
    from paimon_tpu.schema import Schema
    from paimon_tpu.table import FileStoreTable
    from paimon_tpu.types import BigIntType, DoubleType

    n_buckets = n_devices
    # write-path flush pre-merges duplicate keys, so size the keyspace
    # so that >= 1M rows survive into the sharded compaction itself
    total_rows = int(os.environ.get("DRYRUN_ROWS", "1300000"))

    with tempfile.TemporaryDirectory() as tmp:
        schema = (Schema.builder()
                  .column("id", BigIntType(False))
                  .column("v", DoubleType())
                  .primary_key("id")
                  .options({"bucket": str(n_buckets),
                            "write-only": "true"})
                  .build())
        table = FileStoreTable.create(os.path.join(tmp, "t"), schema)
        rng = np.random.default_rng(0)
        # two commits -> two overlapping L0 runs per bucket
        for _ in range(2):
            ids = rng.integers(0, total_rows, total_rows // 2)
            data = pa.table({
                "id": pa.array(ids, pa.int64()),
                "v": pa.array(rng.random(len(ids)), pa.float64()),
            })
            wb = table.new_batch_write_builder()
            with wb.new_write() as w:
                w.write_arrow(data)
                wb.new_commit().commit(w.prepare_commit())

        expected = table.to_arrow().num_rows   # merge-on-read truth
        n_input = sum(
            f.row_count for s in
            table.new_read_builder().new_scan().plan().splits
            for f in s.data_files)

        mesh = bucket_mesh(n_devices)
        stats = compact_table_mesh(table, mesh)
        assert stats.snapshot_id is not None
        assert stats.buckets == n_buckets, (stats.buckets, n_buckets)
        assert stats.output_rows == expected, (stats.output_rows,
                                               expected)
        assert table.latest_snapshot().commit_kind == "COMPACT"

        sid = rescale_table_buckets(table, 2 * n_buckets, mesh=mesh)
        assert sid is not None
        table2 = FileStoreTable.load(table.path)
        assert table2.options.bucket == 2 * n_buckets
        after = table2.to_arrow().num_rows
        assert after == expected, (after, expected)

        print(f"dryrun_multichip OK: {n_devices} devices, "
              f"{n_buckets}->{2 * n_buckets} buckets, "
              f"{n_input} input rows -> {expected} merged rows "
              f"(mesh-engine compact + all_to_all rescale on mesh)")


def run_engines(n_devices: int = 8, rows: int = 10_000_000,
                mesh=None, out_path: Optional[str] = None) -> dict:
    """Mesh-engine multichip benchmark: deduplicate + aggregation full
    compactions at `rows` input rows each, on an already-initialized
    CPU mesh backend (tests/conftest.py or run() set one up).  Returns
    (and optionally JSON-writes) per-engine rows/s plus the engine's
    window/packing observability counters."""
    import json
    import tempfile
    import time

    import numpy as np
    import pyarrow as pa

    from paimon_tpu.parallel import bucket_mesh, compact_table_mesh
    from paimon_tpu.schema import Schema
    from paimon_tpu.table import FileStoreTable
    from paimon_tpu.types import BigIntType, DoubleType

    if mesh is None:
        mesh = bucket_mesh(n_devices)
    # record the geometry actually measured, not the requested one
    n_dev = int(np.prod([mesh.shape[a] for a in mesh.axis_names]))
    record = {"devices": n_dev, "requested_rows": rows,
              "backend": "cpu-mesh", "engines": {}}
    for engine in ("deduplicate", "aggregation"):
        opts = {"bucket": str(n_dev), "write-only": "true",
                "merge-engine": engine}
        if engine == "aggregation":
            opts["fields.v.aggregate-function"] = "sum"
        schema = (Schema.builder()
                  .column("id", BigIntType(False))
                  .column("v", DoubleType())
                  .primary_key("id")
                  .options(opts)
                  .build())
        with tempfile.TemporaryDirectory() as tmp:
            table = FileStoreTable.create(
                os.path.join(tmp, engine.replace("-", "_")), schema)
            rng = np.random.default_rng(6)

            def scanned_rows():
                return sum(
                    f.row_count for s in
                    table.new_read_builder().new_scan().plan().splits
                    for f in s.data_files)

            # two commits minimum (two overlapping L0 runs per bucket),
            # then keep committing until >= `rows` survive into the
            # compaction input: the write-path flush pre-merges
            # duplicate keys, so a fixed write count undershoots
            commits = 0
            while commits < 2 or scanned_rows() < rows:
                ids = rng.integers(0, rows, rows // 2)
                wb = table.new_batch_write_builder()
                with wb.new_write() as w:
                    w.write_arrow(pa.table({
                        "id": pa.array(ids, pa.int64()),
                        "v": pa.array(rng.random(len(ids)), pa.float64()),
                    }))
                    wb.new_commit().commit(w.prepare_commit())
                commits += 1
            t0 = time.perf_counter()
            stats = compact_table_mesh(table, mesh)
            dt = time.perf_counter() - t0
            assert stats.snapshot_id is not None
            after = table.to_arrow().num_rows
            assert stats.output_rows == after, (stats.output_rows, after)
            record["engines"][engine] = {
                "input_rows": stats.input_rows,
                "output_rows": stats.output_rows,
                "buckets": stats.buckets,
                "windows": stats.windows,
                "peak_window_rows": stats.peak_window_rows,
                "peak_buffered_rows": stats.peak_buffered_rows,
                "packing_skew": round(stats.skew, 4),
                "seconds": round(dt, 3),
                "rows_per_sec": round(stats.input_rows / dt, 1),
            }
            print(f"run_engines {engine}: {stats.input_rows} rows in "
                  f"{dt:.2f}s = {stats.input_rows / dt:,.0f} rows/s "
                  f"({stats.windows} windows, skew {stats.skew:.2f})")
    if out_path:
        with open(out_path, "w") as f:
            json.dump(record, f, indent=2, sort_keys=True)
            f.write("\n")
    return record
