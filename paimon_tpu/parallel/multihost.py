"""Multi-host bootstrap and topology helpers.

The reference scales out through engine clusters whose workers talk
NCCL/MPI-style through Flink/Spark RPC (SURVEY §5 "distributed
communication backend").  The TPU-native counterpart is jax's
distributed runtime: every host runs the same program, devices of all
hosts form ONE global `Mesh`, and XLA inserts ICI/DCN collectives for
the shardings used — nothing in the table format itself needs a
message bus.  This module is the glue:

- `initialize(...)`: `jax.distributed.initialize` with env fallbacks
  (COORDINATOR_ADDRESS / NUM_PROCESSES / PROCESS_ID — the same shape
  torchrun/mpirun environments provide).
- `global_mesh(...)`: a Mesh over every device of every host.
- `process_local_batch(...)`: turn each host's local Arrow/numpy batch
  into one globally-sharded jax.Array
  (`jax.make_array_from_process_local_data`) — the multi-host data
  ingestion path for jax_data loaders.
- `assign_splits(...)`: deterministic scan-split ownership per process
  (the analog of the reference's split enumerator handing splits to
  parallel source readers).

Everything degrades to single-process: `initialize` is a no-op when
num_processes==1, the mesh covers local devices, split assignment
returns everything.
"""

import os
from typing import List, Optional, Sequence, Tuple

import numpy as np


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> Tuple[int, int]:
    """Bring up jax's distributed runtime (multi-host). Arguments
    default from the standard env vars; single-process is a no-op.
    Returns (process_index, process_count)."""
    import jax

    coordinator_address = coordinator_address or \
        os.environ.get("COORDINATOR_ADDRESS")
    if num_processes is None:
        num_processes = int(os.environ.get("NUM_PROCESSES", "1"))
    if process_id is None:
        process_id = int(os.environ.get("PROCESS_ID", "0"))
    if num_processes > 1:
        # jax 0.4.x ships the CPU backend with cross-process
        # collectives DISABLED by default — without opting into the
        # Gloo implementation, the first multiprocess computation
        # fails with "Multiprocess computations aren't implemented on
        # the CPU backend" (the long-standing test_multihost_real
        # red).  Harmless on TPU (the setting only affects the CPU
        # backend); must run before the backend initializes.
        try:
            jax.config.update("jax_cpu_collectives_implementation",
                              "gloo")
        except (AttributeError, ValueError, KeyError):
            # other jax versions: the flag may not exist (newer
            # releases enable cross-process CPU collectives through
            # the distributed runtime itself)
            pass
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id)
    return jax.process_index(), jax.process_count()


def global_mesh(axis_names: Sequence[str] = ("data",),
                shape: Optional[Sequence[int]] = None):
    """A Mesh over ALL devices (every process's chips). With one axis
    the shape is inferred; multi-axis shapes must multiply out to the
    global device count."""
    import jax
    from jax.sharding import Mesh

    devices = np.asarray(jax.devices())
    if shape is None:
        if len(axis_names) != 1:
            raise ValueError("shape is required for a multi-axis mesh")
        shape = (len(devices),)
    if int(np.prod(shape)) != len(devices):
        raise ValueError(f"mesh shape {tuple(shape)} != device count "
                         f"{len(devices)}")
    return Mesh(devices.reshape(shape), tuple(axis_names))


def process_local_batch(mesh, name_to_array, axis: str = "data"):
    """Assemble each process's host-local numpy columns into ONE
    globally sharded array per column: host batches concatenate along
    `axis` across processes without any host gathering the whole batch
    (reference: parallel source readers each feeding their workers).
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    sharding = NamedSharding(mesh, PartitionSpec(axis))
    out = {}
    for name, arr in name_to_array.items():
        arr = np.asarray(arr)
        out[name] = jax.make_array_from_process_local_data(
            sharding, arr)
    return out


def assign_splits(splits: Sequence, process_index: Optional[int] = None,
                  process_count: Optional[int] = None) -> List:
    """Deterministic split ownership: split i belongs to process
    i % process_count.  Every process plans the same scan and reads
    only its own splits — no coordinator, no shuffle, same contract as
    the torch loader's (rank, worker) sharding."""
    import jax

    if process_index is None:
        process_index = jax.process_index()
    if process_count is None:
        process_count = jax.process_count()
    return [s for i, s in enumerate(splits)
            if i % process_count == process_index]


def distributed_write_commit_user(base: str = "writer") -> str:
    """Per-process commit user for multi-host writers: processes write
    independently and the snapshot CAS serializes their commits (the
    object-store conditional-PUT / rename-CAS is the only global
    agreement point — reference: committer operator singleton)."""
    import jax

    return f"{base}-p{jax.process_index()}"
